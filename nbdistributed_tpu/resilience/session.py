"""Durable sessions: manifest, stale-run GC, and coordinator reattach.

The notebook kernel is the coordinator of the worker fleet, so a kernel
restart — the single most common failure in interactive work — used to
destroy the whole session: every worker's REPL namespace, compiled
functions, and device state died with it.  This module makes the
*coordinator* the disposable part and the *fleet* the durable part:

- A **session manifest** (``session.json`` under the shared
  ``NBD_RUN_DIR``) records everything a fresh coordinator needs to
  find and adopt a surviving fleet: world size, the control-plane
  endpoint, per-rank pids, a session token, and a monotonically
  increasing **epoch**.  Written at ``%dist_init``, refreshed on every
  heal, removed by explicit ``%dist_shutdown``.
- :func:`attach` is the reattach path (``%dist_attach`` /
  ``%dist_init --attach``): read the manifest, re-bind the recorded
  control port (orphaned workers dial it back), bump the epoch, adopt
  the worker pids into a :class:`~..manager.ProcessManager`, and run
  the epoch-stamped hello exchange that fences out any stale
  coordinator still holding the previous epoch.
- :func:`gc_runs` sweeps abandoned run directories (old manifest, no
  live pids) so rings/manifests don't accumulate under the tmp root.

Architecture note vs the reference design: the reference coordinator
owns per-worker ROUTER/PUB sockets, so a manifest there would record
per-rank endpoints.  This stack inverts the dial direction — ONE
coordinator listener, workers dial out — so the manifest records the
single control endpoint and the workers' reconnect loop re-reads it to
discover a replacement port if the new coordinator couldn't re-bind
the old one.

Durable sessions are **single-host** by design: pid adoption and the
shared run-dir manifest assume the new coordinator shares a filesystem
and a pid namespace with the fleet (multi-host worlds still recover
via ``%dist_heal`` respawn).
"""

from __future__ import annotations

import json
import os
import secrets
import shutil
import tempfile
import time

from ..utils import knobs

MANIFEST_NAME = "session.json"
LOCK_NAME = "session.lock"
MANIFEST_VERSION = 1

# An attach lock older than this whose holder pid is unknown is
# presumed abandoned (a coordinator that died between claiming the
# epoch and releasing).
ATTACH_LOCK_STALE_S = 60.0

# Default sweep age for stale sibling run dirs (overridable per call /
# NBD_GC_TTL_S): long enough that a lunch-break orphan fleet's run dir
# is never swept under it, short enough that a day of chaos-test runs
# doesn't accumulate forever.
DEFAULT_GC_TTL_S = 6 * 3600.0


def mint_token() -> str:
    """Per-session shared secret: proves a reattaching coordinator is
    resuming THIS session and keeps a sibling session's manifest from
    hijacking an orphaned worker's reconnect loop."""
    return secrets.token_hex(8)


def token_fingerprint(token: str | None) -> str:
    """Short display hash — the token itself never gets printed."""
    if not token:
        return "-"
    import hashlib

    return hashlib.sha256(token.encode()).hexdigest()[:8]


def default_runs_root() -> str:
    return os.path.join(tempfile.gettempdir(), "nbd_runs")


def manifest_path(run_dir: str) -> str:
    return os.path.join(run_dir, MANIFEST_NAME)


def make_manifest(*, world_size: int, control_host: str,
                  control_port: int, token: str, epoch: int,
                  pids: dict[int, int], backend: str | None = None,
                  dist_port: int | None = None,
                  bind_host: str | None = None,
                  auth_token: str | None = None,
                  init_line: str | None = None,
                  supervised: bool = False) -> dict:
    """Build a manifest dict.  ``control_host`` is the address workers
    DIAL; ``bind_host`` the address a reattaching coordinator binds
    (they differ on multihost's 0.0.0.0 binds)."""
    return {
        "version": MANIFEST_VERSION,
        "world_size": int(world_size),
        "control": {"host": control_host, "port": int(control_port),
                    "bind_host": bind_host or control_host},
        "token": token,
        "epoch": int(epoch),
        "pids": {str(r): int(p) for r, p in pids.items()},
        "backend": backend,
        "dist_port": dist_port,
        "auth_token": auth_token,
        "init_line": init_line,
        "supervised": bool(supervised),
        "created_ts": time.time(),
    }


def write_manifest(run_dir: str, manifest: dict) -> str:
    """Atomic write (tmp + replace): an orphaned worker polling the
    manifest mid-write must never read a torn file."""
    os.makedirs(run_dir, exist_ok=True)
    manifest = dict(manifest)
    manifest["updated_ts"] = time.time()
    path = manifest_path(run_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, path)
    return path


def read_manifest(run_dir: str) -> dict | None:
    """The run dir's manifest, or None (missing / unreadable / torn —
    a durable-session consumer must treat all three as 'no session')."""
    try:
        with open(manifest_path(run_dir)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    return m if isinstance(m, dict) else None


def update_manifest(run_dir: str, **fields) -> dict | None:
    """Read-modify-write specific fields (epoch bump, healed pids,
    replacement control endpoint).  Returns the new manifest, or None
    when there was nothing to update."""
    m = read_manifest(run_dir)
    if m is None:
        return None
    m.update(fields)
    write_manifest(run_dir, m)
    return m


def end_session(run_dir: str | None) -> bool:
    """Remove the manifest — explicit fleet teardown (`%dist_shutdown`)
    ends the durable session; a kernel exit does NOT call this, which
    is exactly what leaves the fleet adoptable."""
    if not run_dir:
        return False
    try:
        os.remove(manifest_path(run_dir))
        return True
    except OSError:
        return False


# ----------------------------------------------------------------------
# liveness

def pid_alive(pid: int) -> bool:
    """Signal-0 probe; PermissionError means alive-but-other-uid."""
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except (OverflowError, ValueError, OSError):
        return False
    return True


def live_pids(manifest: dict) -> dict[int, int]:
    """rank -> pid for the manifest entries whose process still runs."""
    out: dict[int, int] = {}
    for r, p in (manifest.get("pids") or {}).items():
        try:
            rank, pid = int(r), int(p)
        except (TypeError, ValueError):
            continue
        if pid_alive(pid):
            out[rank] = pid
    return out


# ----------------------------------------------------------------------
# stale-session GC

def gc_runs(root: str | None = None, *, ttl_s: float | None = None,
            dry_run: bool = False, now: float | None = None) -> dict:
    """Sweep abandoned sibling run dirs under ``root``.

    A run dir is **stale** when its manifest mtime (the dir mtime when
    no manifest exists) is older than ``ttl_s`` AND none of its
    manifest pids are alive — an orphaned-but-within-grace fleet keeps
    its dir no matter how old the manifest is.  The CURRENT run dir
    (``NBD_RUN_DIR``) is never swept, and neither is a dir owned by a
    **live gateway daemon** (pid-liveness probe on its
    ``gateway.json`` — a pooled fleet may sit idle far past any TTL
    while its tenants are away).  Returns
    ``{"root", "swept": [...], "kept": [...], "kept_why": {dir:
    reason}, "errors": [...]}`` — ``kept_why`` is what ``%dist_gc
    --dry-run`` prints so a skip is explainable; with ``dry_run``
    nothing is removed but ``swept`` still lists the candidates.
    """
    root = root or default_runs_root()
    if ttl_s is None:
        ttl_s = knobs.get_float("NBD_GC_TTL_S", float(DEFAULT_GC_TTL_S))
    now = now if now is not None else time.time()
    current = knobs.get_str("NBD_RUN_DIR")
    current = os.path.realpath(current) if current else None
    swept: list[str] = []
    kept: list[str] = []
    kept_why: dict[str, str] = {}
    errors: list[str] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        names = []
    for name in names:
        d = os.path.join(root, name)
        if not os.path.isdir(d):
            continue
        if current and os.path.realpath(d) == current:
            kept.append(d)
            kept_why[d] = "current session's run dir (NBD_RUN_DIR)"
            continue
        try:
            # Live gateway daemons protect their run dir regardless of
            # age: an idle pool's manifest can be arbitrarily old while
            # the daemon (and its tenants' parked state) is live.
            from ..gateway.daemon import (gateway_alive,
                                          read_gateway_manifest)
            gw = read_gateway_manifest(d)
            if gateway_alive(gw):
                kept.append(d)
                kept_why[d] = (f"live gateway daemon "
                               f"(pid {gw.get('pid')})")
                continue
            if gw is not None:
                # Mid-resize/restart window (ISSUE 16): a resize is a
                # drain + fleet restart under a bumped epoch, and a
                # migration may be replaying this dir's journal into
                # another pool — during both, the daemon pid probe
                # races the restart and reads "dead".  A manifest
                # whose epoch/heartbeat was bumped within the orphan
                # TTL is a pool in transition, not an abandoned one.
                gw_ts = gw.get("updated_ts") or gw.get("created_ts") \
                    or 0.0
                orphan_ttl = knobs.get_float("NBD_ORPHAN_TTL_S", 600.0)
                try:
                    recent = (now - float(gw_ts)) <= orphan_ttl
                except (TypeError, ValueError):
                    recent = False
                if recent:
                    kept.append(d)
                    kept_why[d] = (
                        f"gateway manifest updated "
                        f"{now - float(gw_ts):.0f}s ago (epoch "
                        f"{gw.get('epoch', '?')}) — resize/restart "
                        f"window, within orphan ttl "
                        f"{orphan_ttl:.0f}s")
                    continue
            mpath = manifest_path(d)
            ref = mpath if os.path.exists(mpath) else d
            age = now - os.path.getmtime(ref)
            manifest = read_manifest(d)
            alive = live_pids(manifest) if manifest else {}
            if age > ttl_s and not alive:
                if not dry_run:
                    shutil.rmtree(d, ignore_errors=True)
                swept.append(d)
            else:
                kept.append(d)
                if alive:
                    kept_why[d] = (f"live worker pid(s) "
                                   f"{sorted(alive.values())}")
                else:
                    kept_why[d] = (f"younger than ttl "
                                   f"({age:.0f}s < {ttl_s:.0f}s)")
        except OSError as e:
            errors.append(f"{d}: {e}")
    return {"root": root, "ttl_s": ttl_s, "swept": swept, "kept": kept,
            "kept_why": kept_why, "errors": errors, "dry_run": dry_run}


# ----------------------------------------------------------------------
# attach lock: the epoch bump is a read-modify-write on the manifest,
# and two kernels racing %dist_attach must not both claim epoch N+1
# (both would pass the workers' fence and split-brain the fleet).
# O_EXCL on a lockfile serializes the claim; durable sessions are
# single-host by design, so one filesystem's O_EXCL is authoritative.

def _acquire_attach_lock(run_dir: str) -> str:
    path = os.path.join(run_dir, LOCK_NAME)
    for _ in range(3):
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                         0o644)
        except FileExistsError:
            try:
                holder = int(open(path).read().strip() or 0)
            except (OSError, ValueError):
                holder = 0
            try:
                age = time.time() - os.path.getmtime(path)
            except OSError:
                continue  # vanished between open and stat: retry
            if (holder and not pid_alive(holder)) \
                    or age > ATTACH_LOCK_STALE_S:
                # Abandoned claim (holder died mid-attach): break it.
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            raise RuntimeError(
                f"another coordinator (pid {holder or '?'}) is "
                f"attaching to this session right now — retry in a "
                f"moment, or remove {path} if it is stale")
        os.write(fd, str(os.getpid()).encode())
        os.close(fd)
        return path
    raise RuntimeError(f"could not acquire {path}")


def _release_attach_lock(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


# ----------------------------------------------------------------------
# reattach

def discover_run_dir() -> str | None:
    """Best reattach candidate when the caller names none: the env run
    dir if it holds a manifest, else the newest sibling under the runs
    root whose manifest still has live pids."""
    env = knobs.get_str("NBD_RUN_DIR")
    if env and read_manifest(env) is not None:
        return env
    root = default_runs_root()
    best: tuple[float, str] | None = None
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        d = os.path.join(root, name)
        m = read_manifest(d)
        if m is None or not live_pids(m):
            continue
        ts = m.get("updated_ts") or 0.0
        if best is None or ts > best[0]:
            best = (ts, d)
    return best[1] if best else None


def attach(run_dir: str | None = None, *, attach_timeout: float = 90.0,
           request_timeout: float | None = None, retry=None):
    """Reattach a fresh coordinator to a surviving fleet.

    Reads the manifest, binds the recorded control port (falling back
    to an ephemeral one — published back to the manifest so orphaned
    workers' reconnect loops discover it), bumps the session epoch,
    adopts the recorded pids, waits for every rank to dial back in,
    and runs the hello exchange that hands the fleet to THIS
    coordinator (token verified; the bumped epoch fences any stale
    coordinator's frames out at the workers).

    Returns ``(comm, pm, manifest, hello)`` where ``hello`` maps
    rank -> hello response Message (``data["parked"]`` lists mailbox
    msg_ids awaiting :func:`drain_mailboxes`).  On any failure the
    adopted fleet is left RUNNING (quiesce + listener close only) —
    a failed attach must never kill the session it failed to join.
    """
    from ..manager import ProcessManager, wait_until_ready
    from ..messaging import CommunicationManager

    run_dir = run_dir or discover_run_dir()
    if not run_dir:
        raise RuntimeError(
            "no session to attach: pass a run dir, or set NBD_RUN_DIR "
            f"(no live manifest under {default_runs_root()})")
    if read_manifest(run_dir) is None:
        raise RuntimeError(f"no session manifest in {run_dir}")
    # Serialize the epoch claim: two kernels racing attach must not
    # both compute epoch N+1 (both would pass the workers' fence).
    lock = _acquire_attach_lock(run_dir)
    try:
        manifest = read_manifest(run_dir)
        if manifest is None:
            raise RuntimeError(f"no session manifest in {run_dir}")
        pids = {int(r): int(p) for r, p in
                (manifest.get("pids") or {}).items()}
        world = int(manifest.get("world_size") or len(pids))
        alive = live_pids(manifest)
        if len(alive) < world:
            dead = sorted(set(pids) - set(alive))
            raise RuntimeError(
                f"fleet is not intact: ranks {dead} have no live "
                f"process (orphan TTL expired, or they crashed) — "
                f"%dist_init to start fresh, %dist_gc to sweep the "
                f"remains")
        # Future children (heals) and this process's flight ring must
        # land in the adopted session's run dir, not a freshly minted
        # one — restored on ANY failure below, so a failed attach
        # doesn't leave this kernel pointed at (and a later %dist_init
        # clobbering) a fleet it never joined.
        prev_run_dir = knobs.get_str("NBD_RUN_DIR")
        os.environ["NBD_RUN_DIR"] = run_dir
        comm = None
        try:
            epoch = int(manifest.get("epoch") or 0) + 1
            ctl = manifest.get("control") or {}
            dial_host = ctl.get("host") or "127.0.0.1"
            bind_host = ctl.get("bind_host") or dial_host
            token = manifest.get("token")
            auth = manifest.get("auth_token")
            kw = dict(num_workers=world, host=bind_host,
                      timeout=request_timeout, auth_token=auth,
                      retry=retry, session_token=token,
                      session_epoch=epoch)
            try:
                comm = CommunicationManager(
                    port=int(ctl.get("port") or 0), **kw)
            except OSError:
                # The old port was taken (often by the stale
                # coordinator still holding it): bind ephemeral and
                # let the manifest redirect the workers' reconnect
                # loops.
                comm = CommunicationManager(port=0, **kw)
            # Publish endpoint + epoch BEFORE waiting: orphaned
            # workers poll the manifest between reconnect attempts.
            update_manifest(run_dir, epoch=epoch,
                            control={"host": dial_host,
                                     "port": comm.port,
                                     "bind_host": bind_host})
        except Exception:
            if prev_run_dir is None:
                os.environ.pop("NBD_RUN_DIR", None)
            else:
                os.environ["NBD_RUN_DIR"] = prev_run_dir
            if comm is not None:
                comm.shutdown()
            raise
    finally:
        _release_attach_lock(lock)
    pm = ProcessManager()
    pm.adopt(pids, backend=manifest.get("backend"),
             dist_port=manifest.get("dist_port"))
    pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
    try:
        wait_until_ready(comm, pm, attach_timeout)
        hello = comm.send_to_all(
            "hello", {"token": token, "epoch": epoch}, timeout=30)
        errs = {r: m.data.get("error") for r, m in hello.items()
                if isinstance(m.data, dict) and m.data.get("error")}
        if errs:
            raise RuntimeError(f"hello rejected by ranks {errs}")
    except Exception:
        # Detach WITHOUT killing the fleet: stop the death monitor and
        # close the listener; the workers stay orphaned and adoptable.
        pm.quiesce()
        pm.processes.clear()
        pm.io.clear()
        comm.shutdown()
        if prev_run_dir is None:
            os.environ.pop("NBD_RUN_DIR", None)
        else:
            os.environ["NBD_RUN_DIR"] = prev_run_dir
        raise
    update_manifest(run_dir, attached_ts=time.time())
    return comm, pm, read_manifest(run_dir) or manifest, hello


def drain_mailboxes(comm, *, timeout: float = 30.0) -> dict:
    """Claim every parked result from every rank's mailbox — exactly
    once (a second drain returns empty dicts; a redelivered drain is
    answered from the workers' replay caches).  Returns
    ``{rank: {msg_id: result_data}}``."""
    resps = comm.send_to_all("mailbox", {"action": "drain"},
                             timeout=timeout)
    return {r: (m.data or {}).get("results") or {}
            for r, m in resps.items()}


def refresh_after_heal(comm, pm) -> dict | None:
    """Manifest upkeep after a supervisor heal: the respawned fleet's
    pids/endpoint replace the dead ones, or a later ``%dist_attach``
    would adopt corpses.  No-op (None) without a run dir or manifest."""
    run_dir = knobs.get_str("NBD_RUN_DIR")
    if not run_dir:
        return None
    pids = {}
    for r, p in getattr(pm, "processes", {}).items():
        pid = getattr(p, "pid", None)
        if pid is not None:
            pids[str(r)] = int(pid)
    m = read_manifest(run_dir)
    if m is None:
        return None
    ctl = dict(m.get("control") or {})
    ctl["port"] = comm.port
    return update_manifest(run_dir, pids=pids, control=ctl,
                           world_size=comm.num_workers,
                           epoch=max(int(m.get("epoch") or 0),
                                     int(getattr(comm, "session_epoch", 0)
                                         or 0)))
