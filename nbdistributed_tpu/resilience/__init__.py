"""Fault injection + self-healing for the control plane (L2/L3).

The reference assumes a well-behaved cluster: the coordinator
fail-fasts on ``WorkerDied`` and recovery is a *manual* ``%dist_heal``
replay.  Real TPU fleets see preemptions, slow hosts, and flaky DCN
links — pod-scale work treats preemption-tolerance and supervised
re-attachment as table stakes ("Exploring the limits of Concurrency in
ML Training on Google TPUs"; the Podracer architectures).  This package
makes failures *injectable deterministically* in CI and *survivable
automatically* at runtime:

- :mod:`~nbdistributed_tpu.resilience.faults` — a seeded
  :class:`FaultPlan` that drops / delays / duplicates / truncates
  control-plane frames, freezes heartbeats, and SIGKILLs a chosen rank
  at a chosen message index.  Hooked into the transport send paths and
  the worker loop; enabled via the ``NBD_FAULT_PLAN`` env knob or the
  ``%dist_chaos`` magic.
- :mod:`~nbdistributed_tpu.resilience.retry` — :class:`RetryPolicy`:
  per-request deadlines with exponential backoff + jitter redelivery
  for ``CommunicationManager.send_to_ranks``.
- :mod:`~nbdistributed_tpu.resilience.dedup` — :class:`ReplayCache`:
  the worker-side bounded reply cache that makes request redelivery
  idempotent (a retried ``execute`` is never double-executed).
- :mod:`~nbdistributed_tpu.resilience.session` — durable sessions:
  the ``session.json`` manifest under ``NBD_RUN_DIR`` (world size,
  control endpoint, pids, token, epoch), :func:`session.attach` — the
  ``%dist_attach`` reattach path that lets a fresh kernel adopt a
  fleet orphaned by coordinator death — and :func:`session.gc_runs`
  stale-run sweeping.
- :mod:`~nbdistributed_tpu.resilience.supervisor` —
  :class:`Supervisor`: consumes process-death callbacks + heartbeat
  staleness, distinguishes *degraded* from *dead*, and auto-heals
  (replay ``%dist_init`` + restore the last checkpoint) under a capped
  restart budget.
- :mod:`~nbdistributed_tpu.resilience.watchdog` — the collective hang
  watchdog + stuck-cell doctor: :class:`HangWatchdog` compares
  per-rank collective-stream positions (piggybacked on heartbeats)
  and flags cells HUNG — cross-rank skew, absolute stall, or a blown
  ``--deadline`` — distinct from merely slow, then walks a
  configurable escalation ladder (warn → stack-dump → interrupt →
  heal); :func:`~nbdistributed_tpu.resilience.watchdog.hang_report`
  assembles the ``%dist_doctor`` diagnosis.

Everything here is stdlib-only (no JAX import) so the coordinator side
stays light and the modules are unit-testable without a backend.
"""

from . import session
from .dedup import ReplayCache, ResultMailbox
from .faults import CorruptSpec, FaultPlan
from .retry import RetryPolicy
from .supervisor import Supervisor, SupervisorPolicy
from .watchdog import HangPolicy, HangWatchdog, SkewDetector, hang_report

__all__ = ["CorruptSpec", "FaultPlan", "HangPolicy", "HangWatchdog",
           "ReplayCache", "ResultMailbox", "RetryPolicy", "SkewDetector",
           "Supervisor", "SupervisorPolicy", "hang_report", "session"]
