"""Live scrape endpoint: ``GET /metrics``, ``/healthz``,
``/latency.json`` (ISSUE 13, part 3).

A stdlib :class:`~http.server.ThreadingHTTPServer` owned by the
coordinator (``%dist_init`` + ``NBD_METRICS_PORT``) or the gateway
daemon (``%dist_pool start --metrics-port``), so a deployment can be
scraped by a stock Prometheus — no shim, no notebook round-trip:

- ``/metrics`` — Prometheus exposition text (version 0.0.4) from the
  coordinator's registry, with the per-rank **worker view merged in
  through the existing telemetry piggyback**: every heartbeat already
  pushes each rank's HBM / live-buffer / compile / dedup numbers to
  the coordinator, and the collector mirrors the newest snapshot into
  rank-labeled gauges.  Push-based on purpose — probing a worker's
  registry goes through its SERIAL request loop and would stall the
  scrape exactly when a long cell makes the numbers interesting.
  Clock-offset gauges and flight-ring health ride the same export.
- ``/healthz`` — liveness JSON (world size, alive/dead ranks, and —
  on a gateway — tenant/scheduler counts).  Never token-gated: a load
  balancer's prober holds no secrets.
- ``/latency.json`` — the latency observatory's summary + recent raw
  stage records (:mod:`.latency`), the machine-readable twin of
  ``%dist_lat``.

On a gateway pool, ``/metrics`` and ``/latency.json`` are
**token-gated like the admin plane** (the pool token, via
``?token=…`` or ``Authorization: Bearer …``) — the manifest that
tells a kernel where to attach also authorizes its scrapes.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import flightrec
from . import latency as obs_latency
from . import metrics as obs_metrics
from . import telemetry as obs_telemetry


class MetricsHTTPD:
    """The scrape server.  Collectors are injected callables so the
    unit tests drive it with fakes and both owners (single-kernel
    coordinator, gateway daemon) share one implementation.

    ``collect_metrics() -> str`` (Prometheus text),
    ``collect_health() -> dict``, ``collect_latency() -> dict``;
    ``token`` gates /metrics and /latency.json when set.
    """

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1",
                 collect_metrics, collect_health,
                 collect_latency=None, token: str | None = None):
        self.host = host
        self.token = token
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # Scrapes are high-frequency; default request logging
            # would spam the daemon's log file.
            def log_message(self, fmt, *args):  # noqa: N802
                pass

            def _reply(self, code: int, body: bytes,
                       ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self, query: dict) -> bool:
                if not outer.token:
                    return True
                if query.get("token", [None])[0] == outer.token:
                    return True
                auth = self.headers.get("Authorization", "")
                return auth == f"Bearer {outer.token}"

            def do_GET(self):  # noqa: N802
                try:
                    url = urlparse(self.path)
                    path = url.path.rstrip("/") or "/"
                    if path == "/healthz":
                        body = json.dumps(collect_health()).encode()
                        self._reply(200, body, "application/json")
                        return
                    if path not in ("/metrics", "/latency.json"):
                        self._reply(404, b"not found\n", "text/plain")
                        return
                    if not self._authorized(parse_qs(url.query)):
                        self._reply(
                            401,
                            b"pool token required (?token= or "
                            b"Authorization: Bearer)\n", "text/plain")
                        return
                    if path == "/metrics":
                        self._reply(
                            200, collect_metrics().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    else:
                        payload = (collect_latency()
                                   if collect_latency is not None
                                   else {})
                        self._reply(200, json.dumps(payload).encode(),
                                    "application/json")
                except BrokenPipeError:
                    pass  # scraper hung up mid-reply
                except Exception as e:
                    try:
                        self._reply(500, f"{type(e).__name__}: {e}\n"
                                    .encode(), "text/plain")
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="nbd-metrics-httpd",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass


# ----------------------------------------------------------------------
# collectors over a CommunicationManager (both owners use these)


def _mirror_worker_view(reg, comm) -> None:
    """Fold each rank's newest heartbeat-piggybacked telemetry into
    rank-labeled gauges — the /metrics "merged worker registries"
    without a single request on the serial worker loops."""
    import time
    now = time.time()
    for r in range(getattr(comm, "num_workers", 0)):
        seen = comm.last_seen(r)
        if seen is not None:
            reg.gauge("nbd_heartbeat_staleness_seconds",
                      "seconds since this rank was last heard",
                      {"rank": str(r)}).set(round(now - seen, 3))
        tel = comm.last_telemetry(r)
        if not tel:
            continue
        labels = {"rank": str(r)}
        hbm = obs_telemetry.hbm_totals(tel)
        if hbm:
            for key in ("in_use", "peak", "limit"):
                if hbm.get(key) is not None:
                    reg.gauge(f"nbd_worker_hbm_{key}_bytes",
                              f"rank HBM {key} (all local devices, "
                              "from the heartbeat telemetry "
                              "piggyback)", labels).set(hbm[key])
        for field, name, help in (
                ("bufs", "nbd_worker_live_buffers",
                 "live jax.Array count on this rank"),
                ("compiles", "nbd_worker_backend_compiles",
                 "XLA backend compiles observed on this rank"),
                ("compile_s", "nbd_worker_compile_seconds",
                 "cumulative XLA compile seconds on this rank"),
                ("dedup", "nbd_worker_dedup_hits",
                 "replay-cache hits on this rank"),
                ("msgs", "nbd_worker_messages_seen",
                 "control messages this rank has received")):
            v = tel.get(field)
            if v is not None:
                reg.gauge(name, help, labels).set(float(v))


def collectors_for_comm(comm, *, extra_health=None,
                        extra_latency=None):
    """(collect_metrics, collect_health, collect_latency) bound to a
    :class:`~..messaging.coordinator.CommunicationManager`.

    ``extra_latency`` (ISSUE 18) is a zero-arg callable whose dict is
    merged into the ``/latency.json`` payload — the daemon hangs the
    serving observatory's stage/utilization block there."""

    def collect_metrics() -> str:
        reg = obs_metrics.registry()
        obs_latency.export_clock_metrics(comm.clock, reg)
        flightrec.export_health(reg)
        _mirror_worker_view(reg, comm)
        return reg.prometheus_text()

    def collect_health() -> dict:
        import time
        dead = sorted(comm.dead_ranks())
        out = {
            "status": "degraded" if dead else "ok",
            "world_size": comm.num_workers,
            "alive": comm.connected_ranks(),
            "dead": dead,
            "pending": len(comm.pending_snapshot()),
            "ts": round(time.time(), 3),
        }
        if extra_health is not None:
            try:
                out.update(extra_health() or {})
            except Exception:
                pass
        return out

    def collect_latency() -> dict:
        out = comm.lat.status_block()
        if extra_latency is not None:
            try:
                out.update(extra_latency() or {})
            except Exception:
                pass
        return out

    return collect_metrics, collect_health, collect_latency


def start_for_comm(comm, *, port: int, host: str = "127.0.0.1",
                   token: str | None = None, extra_health=None,
                   extra_latency=None) -> MetricsHTTPD:
    """Start the scrape endpoint over a live coordinator.  ``port``
    0 binds an ephemeral port (read it back from ``.port``)."""
    cm, ch, cl = collectors_for_comm(comm, extra_health=extra_health,
                                     extra_latency=extra_latency)
    return MetricsHTTPD(port=port, host=host, token=token,
                        collect_metrics=cm, collect_health=ch,
                        collect_latency=cl)
