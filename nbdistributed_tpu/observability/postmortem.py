"""Crash postmortems: assemble the black boxes into one bundle.

When a worker dies (``WorkerDied``, a supervisor restart, or on
demand via ``%dist_postmortem``), this module gathers everything the
run left behind and writes a **postmortem bundle** directory:

- ``manifest.json`` — what happened, when, which ranks died, what the
  bundle contains;
- ``flight_rank{r}.json`` / ``flight_coordinator.json`` — each
  process's flight ring, *recovered from the file* (so a SIGKILLed
  rank's last events — including the dispatch record of the message it
  died on — are present), with the torn-tail flag;
- ``telemetry.json`` — the last heartbeat-piggybacked telemetry
  snapshots per rank (the dead rank's final HBM numbers);
- ``trace.json`` — one Chrome-trace JSON merged through the existing
  clock-aligned export path: coordinator spans (when a ``%dist_trace``
  session was active), every recovered flight ring as instant events
  (``pid`` = rank, coordinator −1), and fault-plan decisions — loads
  directly in ui.perfetto.dev;
- ``report.txt`` — the human-readable story.

Bundles land under ``<run_dir>/postmortem-NNN/``; the newest one is
what ``%dist_postmortem --last`` shows.  Assembly is deliberately
read-only with respect to the cluster: it talks to no worker (they may
be dead) and never raises into its caller (the supervisor's heal path
must proceed even if the postmortem disk is full).
"""

from __future__ import annotations

import json
import os
import time

from . import export as obs_export
from . import flightrec
from ..utils import knobs


def flight_to_trace_dump(ring: dict | None) -> dict:
    """Shape a recovered ring as a ``Tracer.dump()`` payload whose
    instants are the flight events — the adapter that lets
    :func:`~nbdistributed_tpu.observability.export.merge_trace` put
    recovered events on the same clock-aligned timeline as live
    spans."""
    instants = []
    for ev in (ring or {}).get("events", []):
        attrs = {k: v for k, v in ev.items() if k not in ("t", "ts")}
        if ring.get("torn_tail"):
            attrs.setdefault("ring_torn_tail", True)
        instants.append({"name": f"fr:{ev.get('t', '?')}",
                         "kind": "flight",
                         "t0": ev.get("ts", 0.0),
                         "tid": 0,
                         "attrs": attrs})
    return {"trace_id": None, "spans": [], "instants": instants,
            "dropped": 0}


def _merge_dump(live: dict | None, flight: dict | None) -> dict:
    """One rank's trace payload: live spans (if any) + flight
    instants."""
    live = dict(live or {"spans": [], "instants": []})
    fl = flight_to_trace_dump(flight)
    live["instants"] = list(live.get("instants", [])) + fl["instants"]
    live.setdefault("spans", [])
    return live


def _next_bundle_dir(root: str) -> str:
    os.makedirs(root, exist_ok=True)
    n = 0
    while True:
        d = os.path.join(root, f"postmortem-{n:03d}")
        if not os.path.exists(d):
            return d
        n += 1


def list_bundles(directory: str | None = None) -> list[str]:
    """Bundle directories under the run dir, oldest → newest."""
    d = directory or knobs.get_str("NBD_RUN_DIR")
    if not d or not os.path.isdir(d):
        return []
    out = [os.path.join(d, n) for n in sorted(os.listdir(d))
           if n.startswith("postmortem-")]
    return [p for p in out if os.path.isdir(p)]


def render_report(manifest: dict, rings: dict, telemetry: dict) -> str:
    """The human-readable side of the bundle."""
    lines = [
        "nbdistributed_tpu postmortem",
        "=" * 28,
        f"created : {manifest.get('created')}",
        f"reason  : {manifest.get('reason') or 'on demand'}",
        f"dead    : ranks {manifest.get('dead_ranks') or '(none)'}",
        f"run dir : {manifest.get('run_dir')}",
        "",
    ]
    # Multi-host worlds: per-host grouping + link health at capture
    # time — "which link was sick" belongs next to "which rank died".
    links = manifest.get("link_stats") or {}
    if len(links.get("hosts") or {}) > 1:
        from ..resilience.partition import format_link_suffix
        lines.append("hosts / links at capture:")
        for h, hs in sorted(links["hosts"].items()):
            dead = [r for r in hs.get("ranks", ())
                    if r in (manifest.get("dead_ranks") or [])]
            lines.append(f"   {h:<14} ranks {hs.get('ranks')} · "
                         f"{format_link_suffix(hs)}"
                         + (f" · DEAD {dead}" if dead else ""))
        lines.append("")
    for key in sorted(rings, key=str):
        ring = rings[key]
        if ring is None:
            lines.append(f"-- {key}: no flight ring found")
            continue
        dead = (isinstance(key, int)
                and key in (manifest.get("dead_ranks") or []))
        tag = " [DEAD]" if dead else ""
        lines.append(
            f"-- {('rank ' + str(key)) if isinstance(key, int) else key}"
            f"{tag}: {ring['recovered']} events recovered"
            + (f", {ring['overwritten']} overwritten" if
               ring.get("overwritten") else "")
            + (", TORN final record" if ring.get("torn_tail") else ""))
        for ev in ring["events"][-8:]:
            ts = time.strftime("%H:%M:%S",
                               time.localtime(ev.get("ts", 0)))
            detail = {k: v for k, v in ev.items() if k not in ("t", "ts")}
            lines.append(f"     {ts} {ev.get('t', '?'):<22} "
                         f"{json.dumps(detail, default=str)[:120]}")
    if telemetry:
        lines.append("")
        lines.append("last telemetry per rank:")
        for r in sorted(telemetry, key=str):
            snaps = telemetry[r] or []
            last = snaps[-1] if snaps else None
            if not last:
                lines.append(f"   rank {r}: (none)")
                continue
            from .telemetry import hbm_totals
            tot = hbm_totals(last)
            mem = (f"{(tot['in_use'] or 0) / 1e9:.2f}"
                   f"/{(tot['limit'] or 0) / 1e9:.2f} GB"
                   + (f" over {tot['devices']} devices"
                      if tot["devices"] > 1 else "")
                   if tot else "n/a")
            lines.append(
                f"   rank {r}: hbm {mem} · bufs {last.get('bufs', '?')}"
                f" · compiles {last.get('compiles', '?')}"
                f" · sampled "
                f"{time.strftime('%H:%M:%S', time.localtime(last.get('ts', 0)))}")
    lines.append("")
    lines.append("files: trace.json (ui.perfetto.dev) · "
                 "flight_*.json · telemetry.json · manifest.json")
    return "\n".join(lines)


def build_bundle(out_dir: str, *, run_dir: str,
                 dead_ranks: list[int],
                 ranks: list[int],
                 coordinator_dump: dict | None = None,
                 rank_dumps: dict | None = None,
                 offsets: dict | None = None,
                 coordinator_faults: list | None = None,
                 rank_faults: dict | None = None,
                 telemetry: dict | None = None,
                 hang_report: str | None = None,
                 link_stats: dict | None = None,
                 reason: str = "") -> dict:
    """Assemble and write one bundle; returns the manifest (with
    ``"dir"`` set).  Pure function of its inputs + the ring files on
    disk — the capture front-ends (:func:`capture`, the supervisor, the
    magics) gather the live-process inputs."""
    os.makedirs(out_dir, exist_ok=True)
    rings: dict = {}
    for r in sorted(set(ranks) | set(dead_ranks)):
        rings[r] = flightrec.read_latest(run_dir, f"rank{r}")
    rings["coordinator"] = flightrec.read_latest(run_dir, "coordinator")

    telemetry = telemetry or {}
    manifest = {
        "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "created_unix": time.time(),
        "reason": reason,
        "run_dir": run_dir,
        "dead_ranks": sorted(dead_ranks),
        "ranks": sorted(set(ranks) | set(dead_ranks)),
        "rings": {str(k): {"recovered": v["recovered"],
                           "torn_tail": v["torn_tail"],
                           "overwritten": v["overwritten"],
                           "path": v["path"]}
                  for k, v in rings.items() if v is not None},
        "link_stats": link_stats or {},
        "dir": out_dir,
    }

    # Merged Chrome trace: live coordinator spans + every ring's
    # recovered events as instants, clock-corrected per rank.
    merged_ranks = {r: _merge_dump((rank_dumps or {}).get(r), rings[r])
                    for r in manifest["ranks"]}
    coord = _merge_dump(coordinator_dump, rings["coordinator"])
    merged = obs_export.merge_trace(
        coord, merged_ranks, offsets or {},
        coordinator_faults=coordinator_faults or [],
        rank_faults=rank_faults or {})
    files = {"trace.json": merged,
             "telemetry.json": {str(r): list(v or [])
                                for r, v in telemetry.items()},
             "manifest.json": manifest}
    for k, ring in rings.items():
        name = (f"flight_rank{k}.json" if isinstance(k, int)
                else f"flight_{k}.json")
        files[name] = ring if ring is not None else {"events": [],
                                                     "missing": True}
    for name, payload in files.items():
        with open(os.path.join(out_dir, name), "w") as f:
            json.dump(payload, f, default=str)
    report = render_report(manifest, rings, telemetry)
    if hang_report:
        # The stuck-cell doctor's assessment (ISSUE 5): per-rank
        # collective positions, the skew table, and stack-dump tails
        # at capture time — a hang that escalated into a death (or a
        # manual capture mid-hang) keeps its diagnosis next to the
        # black boxes.
        manifest["hang_report"] = "hang_report.txt"
        with open(os.path.join(out_dir, "hang_report.txt"), "w") as f:
            f.write(hang_report + "\n")
        report += ("\n(hang diagnosis at capture time: "
                   "hang_report.txt)")
    with open(os.path.join(out_dir, "report.txt"), "w") as f:
        f.write(report + "\n")
    return manifest


def capture(comm, dead_ranks=None, *, out_dir: str | None = None,
            reason: str = "", rank_dumps: dict | None = None,
            rank_faults: dict | None = None,
            hang_report: str | None = None) -> dict | None:
    """High-level capture against a live coordinator: pulls everything
    the coordinator holds (tracer dump, clock offsets, fault-plan
    events, piggybacked telemetry), recovers the rings from the run
    dir, writes a bundle, and returns its manifest.  Never raises —
    returns None on failure (the heal path must not die for a
    postmortem).

    ``rank_dumps`` / ``rank_faults``: optional per-rank ``trace dump``
    payloads for SURVIVING ranks (the dead ones can't answer); the
    magics pass them when a trace session is active.
    """
    try:
        run_d = flightrec.run_dir(create=False)
        dead = sorted(dead_ranks or [])
        ranks = list(range(getattr(comm, "num_workers", 0) or 0))
        telemetry = {}
        for r in ranks:
            hist = None
            get_hist = getattr(comm, "telemetry_history", None)
            if get_hist is not None:
                hist = get_hist(r)
            if hist:
                telemetry[r] = list(hist)
        plan = comm.fault_plan() if hasattr(comm, "fault_plan") else None
        try:
            links = comm.link_stats() if hasattr(comm,
                                                 "link_stats") else None
        except Exception:
            links = None
        out = out_dir or _next_bundle_dir(run_d)
        flightrec.record("postmortem", dir=out, dead=dead, reason=reason)
        manifest = build_bundle(
            out, run_dir=run_d, dead_ranks=dead, ranks=ranks,
            coordinator_dump=(comm.tracer.dump()
                              if getattr(comm, "tracer", None) is not None
                              and len(comm.tracer) else None),
            rank_dumps=rank_dumps,
            offsets=(comm.clock.offsets()
                     if getattr(comm, "clock", None) is not None else {}),
            coordinator_faults=(plan.events() if plan is not None else []),
            rank_faults=rank_faults,
            telemetry=telemetry, hang_report=hang_report,
            link_stats=links, reason=reason)
        return manifest
    except Exception:
        return None
