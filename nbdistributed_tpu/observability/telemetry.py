"""Per-worker live device telemetry (ISSUE 3 tentpole, part 2).

The status probe (``get_status``) answers through the worker's SERIAL
request loop, so it stalls exactly when the operator most wants it —
mid-cell, mid-compile, mid-OOM-death-spiral.  This module is the
*push*-based alternative: a :class:`TelemetrySampler` snapshots device
state off the hot path and the worker's heartbeat thread piggybacks the
compact snapshot on its ping ``data``, giving the coordinator a live
per-rank view (HBM in use / peak, live buffer count, compile activity,
resilience counters) that works while the main thread is busy.

Snapshot shape (compact on purpose — it rides every Nth 2-second
heartbeat)::

    {"ts": unix_s,
     "hbm": [{"id", "in_use", "peak", "limit"}, ...],   # bytes | None
     "bufs": live jax.Array count,
     "compiles": backend_compile count, "compile_s": cumulative seconds,
     ...extra_fn() fields (dedup hits, msgs seen, ...)}

The module imports no JAX at import time (the observability package
stays coordinator-safe); all device access is lazy and fail-soft.
Device memory numbers come from ``Device.memory_stats()`` — the same
source ``runtime/introspect.py:device_status`` reports, refactored here
so the pull path and the push path cannot drift.
"""

from __future__ import annotations

import threading
import time

from . import metrics as obs_metrics

DEFAULT_INTERVAL_S = 4.0


def device_memory(device) -> dict | None:
    """``{"in_use", "peak", "limit"}`` in raw bytes from
    ``Device.memory_stats()``, or None when the backend exposes no
    stats (CPU devices return None).  Shared by the ``get_status``
    pull path (:func:`~nbdistributed_tpu.runtime.introspect
    .device_status`) and the heartbeat push path."""
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    def _get(key):
        v = stats.get(key)
        return int(v) if v is not None else None
    return {"in_use": _get("bytes_in_use"),
            "peak": _get("peak_bytes_in_use"),
            "limit": _get("bytes_limit")}


class _CompileWatch:
    """Counts XLA backend compiles via ``jax.monitoring`` duration
    events — the only compile signal that fires *inside* the blocking
    compile path, which is exactly when the serial loop can't answer a
    status probe.  Process-global (listeners cannot be unregistered);
    instances read deltas off the shared counters."""

    _lock = threading.Lock()
    _installed = False
    count = 0
    seconds = 0.0

    @classmethod
    def install(cls) -> bool:
        with cls._lock:
            if cls._installed:
                return True
            try:
                import jax.monitoring as jmon

                def _on_duration(name: str, secs: float, **kw) -> None:
                    if name.endswith("backend_compile_duration"):
                        with cls._lock:
                            cls.count += 1
                            cls.seconds += secs

                jmon.register_event_duration_secs_listener(_on_duration)
            except Exception:
                return False
            cls._installed = True
            return True

    @classmethod
    def snapshot(cls) -> tuple[int, float]:
        with cls._lock:
            return cls.count, round(cls.seconds, 3)


def compile_seconds() -> float:
    """Cumulative XLA backend-compile seconds observed in this process
    (0.0 until the listener is installed).  The latency observatory's
    worker-side stamps take a delta of this around each handler, so a
    cell's first-run compile shows up as its own stage instead of
    inflating ``execute``."""
    with _CompileWatch._lock:
        return _CompileWatch.seconds


class TelemetrySampler:
    """Samples device state for one worker rank.

    ``sample()`` forces a snapshot; ``maybe_sample()`` respects the
    minimum interval (heartbeats fire every 2 s — resampling device
    stats and walking live arrays on every ping would make the
    liveness signal itself a load source) and returns None between
    samples so unchanged pings stay small.  Every snapshot also feeds
    the process metrics registry so ``%dist_metrics`` exports carry
    the device numbers.
    """

    def __init__(self, rank: int, *,
                 min_interval_s: float = DEFAULT_INTERVAL_S,
                 extra_fn=None):
        self.rank = rank
        self.min_interval_s = min_interval_s
        self._extra_fn = extra_fn
        self._last_ts = 0.0
        self.last: dict | None = None
        self._compile_watch = _CompileWatch.install()

    # ------------------------------------------------------------------

    def maybe_sample(self, now: float | None = None) -> dict | None:
        now = time.time() if now is None else now
        if now - self._last_ts < self.min_interval_s:
            return None
        return self.sample(now)

    def sample(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        self._last_ts = now
        snap: dict = {"ts": round(now, 3)}
        reg = obs_metrics.registry()
        try:
            import jax

            hbm = []
            for d in jax.local_devices():
                mem = device_memory(d)
                if mem is not None:
                    hbm.append({"id": d.id, **mem})
                    for k in ("in_use", "peak"):
                        if mem[k] is not None:
                            reg.gauge(f"nbd_hbm_{k}_bytes",
                                      f"device HBM {k} bytes",
                                      {"device": str(d.id)}).set(mem[k])
            if hbm:
                snap["hbm"] = hbm
            try:
                n_live = len(jax.live_arrays())
                snap["bufs"] = n_live
                reg.gauge("nbd_live_buffers",
                          "live jax.Array count").set(n_live)
            except Exception:
                pass
        except Exception:
            pass
        if self._compile_watch:
            n, secs = _CompileWatch.snapshot()
            snap["compiles"] = n
            snap["compile_s"] = secs
            reg.gauge("nbd_backend_compiles",
                      "XLA backend compiles observed").set(n)
        if self._extra_fn is not None:
            try:
                snap.update(self._extra_fn() or {})
            except Exception:
                pass
        self.last = snap
        return snap


def hbm_totals(snapshot: dict | None) -> dict | None:
    """Sum a snapshot's per-device HBM numbers into one
    ``{"in_use", "peak", "limit", "devices"}`` (bytes) — the per-rank
    figure ``%dist_top`` and the postmortem report show.  A worker may
    own several chips (one process per host on pods); showing only
    device 0 would hide an OOM on any other device.  None when the
    snapshot carries no memory stats (CPU backends)."""
    hbm = (snapshot or {}).get("hbm") or []
    if not hbm:
        return None
    out = {"devices": len(hbm)}
    for key in ("in_use", "peak", "limit"):
        vals = [d.get(key) for d in hbm if d.get(key) is not None]
        out[key] = sum(vals) if vals else None
    return out


def peak_hbm(snapshots) -> dict:
    """Summarize a sequence of snapshots into per-device peak HBM bytes
    (the ``bench.py`` trajectory summary)."""
    peaks: dict[str, int] = {}
    for snap in snapshots:
        for dev in (snap or {}).get("hbm", ()):
            for key in ("peak", "in_use"):
                v = dev.get(key)
                if v is not None:
                    did = str(dev.get("id"))
                    peaks[did] = max(peaks.get(did, 0), v)
                    break
    return peaks
