"""Process-local metrics registry: counters, gauges, histograms.

One :func:`registry` per process (coordinator and each worker) holding
the numbers that used to live scattered across ad-hoc ``get_status``
dicts: wire messages and bytes, retries, dedup hits, cell and
collective durations, fault injections, supervisor transitions.
Exported two ways:

- :meth:`MetricsRegistry.to_json` — the payload of the worker
  ``metrics`` handler and ``%dist_metrics`` (and the bench snapshot);
- :meth:`MetricsRegistry.prometheus_text` — standard Prometheus
  exposition text, so a deployment can be scraped with nothing but a
  file/HTTP shim.

Metrics are keyed by ``(name, labels)``; histogram buckets are FIXED
at creation (cumulative ``le`` semantics, ``+Inf`` implicit) so
``observe`` is O(#buckets) with no allocation.  Everything is
stdlib-only and thread-safe.
"""

from __future__ import annotations

import re
import threading
from typing import Mapping

# Prometheus' classic latency ladder, widened to cover XLA compiles.
DURATION_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Log-scale ladder for the latency observatory (ISSUE 13): stage and
# SLO distributions span ~100 µs (worker dispatch, per-token decode)
# to tens of seconds (cold compiles), so the classic ladder's 1 ms
# floor would fold every sub-millisecond stage into one bucket.
LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                   0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0)


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integers without the trailing .0,
    floats via repr (full precision)."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


def _labels_suffix(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    """Monotonic counter."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up (use a gauge)")
        with self._lock:
            self.value += n


class Gauge:
    """Set-anywhere value (mirrored snapshots, staleness, sizes)."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Fixed-bucket histogram with cumulative Prometheus semantics."""

    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets=DURATION_BUCKETS):
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self.buckets = bs
        self.counts = [0] * (len(bs) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        i = 0
        for b in self.buckets:
            if v <= b:
                break
            i += 1
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def cumulative(self) -> list[tuple[str, int]]:
        """[(le, cumulative_count)] including +Inf."""
        out = []
        acc = 0
        with self._lock:
            counts = list(self.counts)
            for b, c in zip(self.buckets, counts):
                acc += c
                out.append((_fmt(b), acc))
            out.append(("+Inf", acc + counts[-1]))
        return out


class MetricsRegistry:
    """get-or-create metric store keyed by (name, sorted label items)."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, help, {label_items: metric})
        self._metrics: dict[str, tuple[str, str, dict]] = {}

    # ------------------------------------------------------------------
    # registration

    def _get(self, kind: str, name: str, help: str,
             labels: Mapping[str, str] | None, **kw):
        key = tuple(sorted((k, str(v)) for k, v in (labels or {}).items()))
        with self._lock:
            entry = self._metrics.get(name)
            if entry is None:
                entry = (kind, help, {})
                self._metrics[name] = entry
            elif entry[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {entry[0]}, "
                    f"not {kind}")
            series = entry[2]
            m = series.get(key)
            if m is None:
                m = self._KINDS[kind](**kw)
                series[key] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Mapping[str, str] | None = None) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Mapping[str, str] | None = None) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Mapping[str, str] | None = None,
                  buckets=DURATION_BUCKETS) -> Histogram:
        return self._get("histogram", name, help, labels, buckets=buckets)

    # NOTE deliberately no clear(): instrumentation sites (the
    # collectives' decoration-time histograms, the wire hook's counter
    # cache) hold direct references to their metric objects — dropping
    # the registry's entries would orphan those handles, which would
    # keep incrementing invisibly forever.  Tests wanting isolation
    # build a fresh MetricsRegistry.

    def remove_label_series(self, label: str, value: str) -> int:
        """Drop every series whose label set includes
        ``label="value"``; returns how many series were removed.

        The gateway calls this with ``("tenant", name)`` when a tenant
        is EVICTED: per-tenant series otherwise accumulate one entry
        per tenant name for the daemon's lifetime (the PR 8 stated
        limit this closes).  Only safe for series resolved through the
        registry at each use site (the per-tenant counters are); a
        removed series whose handle something cached would keep
        incrementing invisibly — exactly why there is no blanket
        ``clear()``.  Metric names whose last series is removed keep
        their (name, kind, help) registration so a later re-create
        cannot flip kinds."""
        removed = 0
        with self._lock:
            for _name, (_kind, _help, series) in self._metrics.items():
                doomed = [key for key in series
                          if (label, str(value)) in key]
                for key in doomed:
                    del series[key]
                removed += len(doomed)
        return removed

    # ------------------------------------------------------------------
    # export

    def to_json(self) -> dict:
        """``{"counters": {...}, "gauges": {...}, "histograms": {...}}``
        with label-qualified series names (``name{k="v"}``)."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            items = [(n, k, dict(s)) for n, (k, _h, s)
                     in self._metrics.items()]
        for name, kind, series in sorted(items):
            for key, m in sorted(series.items()):
                qname = name + _labels_suffix(key)
                if kind == "counter":
                    out["counters"][qname] = m.value
                elif kind == "gauge":
                    out["gauges"][qname] = m.value
                else:
                    out["histograms"][qname] = {
                        "buckets": dict(m.cumulative()),
                        "sum": m.sum, "count": m.count}
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain version 0.0.4)."""
        lines: list[str] = []
        with self._lock:
            items = [(n, k, h, dict(s)) for n, (k, h, s)
                     in self._metrics.items()]
        for name, kind, help, series in sorted(items):
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")
            for key, m in sorted(series.items()):
                if kind == "histogram":
                    for le, c in m.cumulative():
                        lines.append(
                            f"{name}_bucket"
                            f"{_labels_suffix(key + (('le', le),))} {c}")
                    lines.append(f"{name}_sum{_labels_suffix(key)} "
                                 f"{_fmt(m.sum)}")
                    lines.append(f"{name}_count{_labels_suffix(key)} "
                                 f"{m.count}")
                else:
                    lines.append(f"{name}{_labels_suffix(key)} "
                                 f"{_fmt(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry."""
    return _REGISTRY


# ----------------------------------------------------------------------
# exposition-format validation (the CI scrape check and the golden
# tests share one rule set, so "parses" means the same thing in both)

_SAMPLE_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                      # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"\n]*"'            # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"\n]*")*\})?'       # more labels
    r" [-+]?(?:[0-9.eE+-]+|Inf|NaN)$")                # value


def validate_prometheus_text(text: str) -> list[str]:
    """Structural check of Prometheus exposition text (version 0.0.4
    as :meth:`MetricsRegistry.prometheus_text` emits it).  Returns a
    list of human-readable problems — empty means parseable.  Checks
    line syntax, that every sample's family was TYPE-declared, and
    that histogram families expose ``_bucket``/``_sum``/``_count``."""
    errors: list[str] = []
    typed: dict[str, str] = {}
    sampled: set[str] = set()
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            errors.append(f"line {i}: blank line inside exposition")
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary",
                    "untyped"):
                errors.append(f"line {i}: malformed TYPE line")
            else:
                typed[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue  # HELP / comments: free text
        if not _SAMPLE_LINE.match(line):
            errors.append(f"line {i}: unparseable sample {line!r}")
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in typed:
                base = name[:-len(suffix)]
                break
        sampled.add(base)
        if base not in typed:
            errors.append(f"line {i}: sample {name!r} has no TYPE "
                          f"declaration")
    for name, kind in typed.items():
        if kind != "histogram" or name not in sampled:
            continue
        for suffix in ("_bucket", "_sum", "_count"):
            if f"# TYPE {name} histogram" in text \
                    and f"{name}{suffix}" not in text:
                errors.append(f"histogram {name} is missing its "
                              f"{suffix} series")
    return errors


# ----------------------------------------------------------------------
# wire accounting

_hook_installed = False


def install_wire_hook() -> None:
    """Route the codec's per-frame accounting into the registry:
    ``nbd_wire_messages_total{dir,type}`` and
    ``nbd_wire_bytes_total{dir}``.  Idempotent; called by both ends of
    the control plane at startup.  The hook pre-resolves its counters
    through a tiny cache so the per-frame cost is two dict hits and
    two increments."""
    global _hook_installed
    if _hook_installed:
        return
    from ..messaging import codec

    reg = _REGISTRY
    series: dict[tuple[str, str], Counter] = {}
    bytes_c = {
        "tx": reg.counter("nbd_wire_bytes_total",
                          "control-plane bytes by direction",
                          {"dir": "tx"}),
        "rx": reg.counter("nbd_wire_bytes_total",
                          "control-plane bytes by direction",
                          {"dir": "rx"}),
    }

    def hook(direction: str, msg_type: str, nbytes: int) -> None:
        c = series.get((direction, msg_type))
        if c is None:
            c = reg.counter("nbd_wire_messages_total",
                            "control-plane frames by direction and type",
                            {"dir": direction, "type": msg_type})
            series[(direction, msg_type)] = c
        c.inc()
        bytes_c[direction].inc(nbytes)

    codec.set_wire_hook(hook)
    _hook_installed = True
