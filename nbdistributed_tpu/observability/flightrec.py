"""Always-on, crash-surviving flight recorder (ISSUE 3 tentpole).

The span tracer (:mod:`~nbdistributed_tpu.observability.spans`) and the
metrics registry are *pull*-based and in-process: a worker that is
SIGKILLed mid-cell — exactly the scenario the chaos harness and the
supervisor exist for — takes its spans, counters, and last-known state
to the grave, and the operator gets a ``WorkerDied`` and nothing else.
This module is the black box that survives the crash: every process
(coordinator and each worker) appends small self-delimiting structured
event records to an **mmap-backed ring file** under a shared per-run
directory, so a *reader in another process* can recover the dead
process's last moments from the file alone.

Why this survives SIGKILL: writes go to a shared ``mmap`` of a regular
file, so the dirty pages live in the kernel page cache — the kernel
writes them back regardless of how the owning process died.  Only a
machine crash loses data, and that failure mode takes the coordinator
(and the need for a live postmortem) with it.

Ring format (all integers little-endian)::

    file header (64 bytes):
        magic     8s   b"NBDFRING"
        version   u16
        ringsize  u32  bytes in the ring region (follows the header)
        pid       u32  writer pid (diagnostic only)
        writeoff  u64  next write offset (hint; reader never trusts it)
        seq       u64  next record sequence   (hint, ditto)
    record (anywhere in the ring region):
        magic     4s   REC_MAGIC (binary, cannot appear in JSON text)
        len       u16  payload length
        crc       u32  crc32 over (seq || payload)
        seq       u64  monotonic per-writer sequence, from 0
        payload   len  UTF-8 JSON: {"t": type, "ts": unix_s, ...fields}

Recovery does not trust the header hints (a torn header is exactly as
likely as a torn record): the reader scans the whole ring region for
``REC_MAGIC``, accepts records whose CRC verifies, orders them by
``seq``, and flags a **torn tail** — a candidate whose header names the
next expected sequence but whose payload fails the CRC or runs off the
end of the file (a write cut mid-record by a kill or truncation).

The append path is the hot path (it runs on every control-plane
dispatch): one compact-JSON encode, one CRC, one ``memoryview`` splice
into the mmap under a lock — low single-digit microseconds, measured by
``bench.py`` against control-plane echo latency (< 5 % is the
acceptance bar; the socket round-trip is ~100× slower).  Recording is
**on by default** (``NBD_FLIGHT=0`` is the escape hatch) and every
failure mode degrades to a silent no-op: a black box must never crash
the plane.

Env knobs:

- ``NBD_RUN_DIR`` — the shared per-run directory.  The first process to
  need it (normally the coordinator) creates one under the system temp
  dir and exports it, so spawned workers inherit the same directory.
- ``NBD_FLIGHT_RING_BYTES`` — ring region size (default 1 MiB).
- ``NBD_FLIGHT=0`` — disable recording (files are still not written).
"""

from __future__ import annotations

import json
import mmap
import os
import re
import struct
import tempfile
import threading
import time
import zlib

FILE_MAGIC = b"NBDFRING"
VERSION = 1
_FHDR = struct.Struct("<8sHxxIIxxxxQQ")       # 40 bytes used...
_FILE_HEADER_SIZE = 64
REC_MAGIC = b"\xf1\x1e\xc0\xde"               # binary: never valid UTF-8 JSON
_RHDR = struct.Struct("<4sHIQ")               # magic, len, crc, seq
REC_HEADER_SIZE = _RHDR.size                  # 18 bytes

DEFAULT_RING_BYTES = 1 << 20
MAX_PAYLOAD = 4096

# Hot-path JSON: json.dumps costs several microseconds per call even
# for tiny dicts; the flight payloads are flat dicts of short scalars,
# which a hand-rolled encoder emits ~7× faster.  Values that would need
# escaping (or aren't plain scalars) fall back to json.dumps — the
# output must stay valid JSON for the recovery-side json.loads.
_NEEDS_ESCAPE = re.compile(r'[\x00-\x1f"\\]').search


def _encode_payload(etype: str, ts: float, fields: dict) -> bytes:
    parts = [f'"t":"{etype}","ts":{ts!r}']
    for k, v in fields.items():
        tv = type(v)
        if tv is str and _NEEDS_ESCAPE(v) is None:
            parts.append(f'"{k}":"{v}"')
        elif tv is int or tv is float:
            parts.append(f'"{k}":{v!r}')
        elif tv is bool:
            parts.append(f'"{k}":{"true" if v else "false"}')
        elif v is None:
            parts.append(f'"{k}":null')
        else:
            parts.append(f'"{k}":'
                         + json.dumps(v, separators=(",", ":"),
                                      default=str))
    return ("{" + ",".join(parts) + "}").encode("utf-8")


def _enabled_by_env() -> bool:
    from ..utils import knobs
    return knobs.get_bool("NBD_FLIGHT", True)


def run_dir(create: bool = True) -> str:
    """The shared per-run directory.  Honors ``NBD_RUN_DIR``; otherwise
    mints one and EXPORTS it into this process's environment, so worker
    processes spawned later (their env is a copy of ours,
    ``manager/topology.py``) land their rings next to the
    coordinator's."""
    from ..utils import knobs
    d = knobs.get_str("NBD_RUN_DIR")
    if not d:
        d = os.path.join(tempfile.gettempdir(), "nbd_runs",
                         f"run-{int(time.time())}-{os.getpid()}")
        os.environ["NBD_RUN_DIR"] = d
    if create:
        os.makedirs(d, exist_ok=True)
    return d


class _NullRecorder:
    """Degraded-mode recorder: same surface, records nothing.  Used
    when recording is disabled or the ring file cannot be created."""

    path = None
    enabled = False

    def record(self, etype: str, **fields) -> None:
        pass

    def health(self) -> dict:
        return {"utilization": 0.0, "wraps": 0, "records": 0,
                "overwritten": 0, "truncated": 0, "dropped": 0}

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __len__(self) -> int:
        return 0


class FlightRecorder:
    """One mmap-backed ring writer.  Thread-safe; never raises from
    ``record`` (a failing black box must not take down the process)."""

    def __init__(self, path: str, ring_bytes: int = DEFAULT_RING_BYTES):
        self.path = path
        self.enabled = True
        self._lock = threading.Lock()
        ring_bytes = max(4 * (REC_HEADER_SIZE + MAX_PAYLOAD),
                         int(ring_bytes))
        self._ring_size = ring_bytes
        total = _FILE_HEADER_SIZE + ring_bytes
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, total)
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        # A fresh file every open: one ring belongs to one process
        # lifetime (file names carry the pid, so a respawned rank never
        # clobbers its predecessor's ring).  The whole ring region is
        # zeroed, not just the header — reopening an existing path
        # (pid recycling under a long-lived run dir, or re-init in one
        # process) must not leave the previous generation's CRC-valid
        # records where recovery would merge them into this one's.
        self._pid = os.getpid() & 0xFFFFFFFF  # cached: getpid is a
        # real syscall on every call and shows up on the append path
        self._mm[:total] = b"\0" * total
        _FHDR.pack_into(self._mm, 0, FILE_MAGIC, VERSION, ring_bytes,
                        self._pid, 0, 0)
        self._off = 0
        self._seq = 0
        self.dropped = 0      # records whose encode/write failed
        # Ring-health counters (ISSUE 13 satellite): wraps, records
        # aged out by a wrap (the previous lap is progressively
        # overwritten once a new one starts — counted at the wrap, the
        # moment evidence loss begins), and oversize payloads whose
        # capped body recovery will skip as torn.
        self.wraps = 0
        self.overwritten = 0
        self.truncated = 0
        self._lap_start_seq = 0

    def __len__(self) -> int:
        return self._seq

    # ------------------------------------------------------------------

    def record(self, etype: str, **fields) -> None:
        """Append one event.  ``fields`` must be JSON-able (they come
        from our own instrumentation sites); anything else is dropped,
        never raised."""
        if not self.enabled:
            return
        try:
            payload = _encode_payload(etype, time.time(), fields)
        except Exception:
            self.dropped += 1
            return
        if len(payload) > MAX_PAYLOAD:
            payload = payload[:MAX_PAYLOAD]  # capped: recovery skips it
            self.truncated += 1
        try:
            with self._lock:
                self._append(payload)
        except Exception:
            self.dropped += 1

    def _append(self, payload: bytes) -> None:
        # Lock held.  Records never wrap across the ring seam: if the
        # tail can't hold this record whole, zero the remnant (so a
        # stale record header there can't masquerade as fresh) and
        # start over at offset 0.
        need = REC_HEADER_SIZE + len(payload)
        base = _FILE_HEADER_SIZE
        if self._off + need > self._ring_size:
            self._mm[base + self._off: base + self._ring_size] = \
                b"\0" * (self._ring_size - self._off)
            self._off = 0
            self.wraps += 1
            # The new lap will overwrite every record of the previous
            # one — count them lost NOW, so the health gauge trips
            # before a postmortem discovers the hole.
            self.overwritten += self._seq - self._lap_start_seq
            self._lap_start_seq = self._seq
        seq = self._seq
        crc = zlib.crc32(struct.pack("<Q", seq) + payload)
        pos = base + self._off
        self._mm[pos: pos + need] = \
            _RHDR.pack(REC_MAGIC, len(payload), crc, seq) + payload
        self._off += need
        self._seq = seq + 1
        # Invalidate any stale record that happens to start exactly at
        # the new head, so the reader's "next expected seq" tail check
        # stays meaningful.
        if self._off + 4 <= self._ring_size:
            head = base + self._off
            if self._mm[head: head + 4] == REC_MAGIC:
                self._mm[head: head + 4] = b"\0\0\0\0"
        # Header hints (diagnostics only — recovery rescans).
        _FHDR.pack_into(self._mm, 0, FILE_MAGIC, VERSION,
                        self._ring_size, self._pid,
                        self._off, self._seq)

    def health(self) -> dict:
        """Ring-health snapshot for the metrics satellite:
        ``utilization`` is the fraction of the ring written this lap
        (pinned to 1.0 once it has wrapped — from then on every append
        destroys history), plus the wrap / overwritten / truncated /
        dropped counters."""
        with self._lock:
            util = (1.0 if self.wraps
                    else round(self._off / self._ring_size, 4))
            return {"utilization": util, "wraps": self.wraps,
                    "records": self._seq,
                    "overwritten": self.overwritten,
                    "truncated": self.truncated,
                    "dropped": self.dropped}

    def flush(self) -> None:
        try:
            self._mm.flush()
        except Exception:
            pass

    def close(self) -> None:
        self.enabled = False
        try:
            self._mm.flush()
            self._mm.close()
        except Exception:
            pass


# ----------------------------------------------------------------------
# recovery (runs in the postmortem process, on any ring file)


def read_ring(path: str) -> dict:
    """Recover a ring file — typically one left behind by a SIGKILLed
    process.  Returns::

        {"path", "pid", "events": [...],     # complete, seq-ordered
         "torn_tail": bool,                  # final record cut mid-write
         "recovered": n, "overwritten": n,   # ring-capacity casualties
         "corrupt": n}

    Never trusts the writer's header hints: scans the whole ring region
    for record magic and accepts only CRC-verified records.
    """
    with open(path, "rb") as f:
        blob = f.read()
    pid = None
    if len(blob) >= _FHDR.size and blob[:8] == FILE_MAGIC:
        try:
            _m, _v, _rs, pid, _off, _seq = _FHDR.unpack_from(blob, 0)
        except struct.error:
            pid = None
    region = blob[_FILE_HEADER_SIZE:]
    found: dict[int, tuple[float, dict]] = {}
    partial: list[int] = []   # seqs of candidates that failed the CRC
    corrupt = 0
    pos = region.find(REC_MAGIC)
    while pos != -1:
        ok = False
        if pos + REC_HEADER_SIZE <= len(region):
            _magic, plen, crc, seq = _RHDR.unpack_from(region, pos)
            end = pos + REC_HEADER_SIZE + plen
            if plen <= MAX_PAYLOAD:
                payload = region[pos + REC_HEADER_SIZE: end]
                if (end <= len(region) and len(payload) == plen
                        and zlib.crc32(struct.pack("<Q", seq)
                                       + payload) == crc):
                    try:
                        ev = json.loads(payload)
                    except ValueError:
                        ev = None
                    if isinstance(ev, dict):
                        found.setdefault(seq, (ev.get("ts", 0.0), ev))
                        ok = True
                        pos = region.find(REC_MAGIC, end)
                        continue
                else:
                    # Plausible header, bad body: either the torn final
                    # record of a killed writer, or an old record half
                    # overwritten by the ring — the seq disambiguates.
                    partial.append(seq)
        if not ok:
            corrupt += 1
            pos = region.find(REC_MAGIC, pos + 1)
    events = [ev for _seq, (_ts, ev) in sorted(found.items())]
    max_seq = max(found) if found else -1
    torn = any(s == max_seq + 1 for s in partial)
    min_seq = min(found) if found else 0
    return {
        "path": path,
        "pid": pid,
        "events": events,
        "torn_tail": torn,
        "recovered": len(events),
        "overwritten": min_seq,
        "corrupt": corrupt,
    }


def ring_path(directory: str, proc: str, pid: int | None = None) -> str:
    return os.path.join(directory,
                        f"flight-{proc}.{pid or os.getpid()}.ring")


def find_rings(directory: str, proc: str | None = None) -> list[str]:
    """Ring files in ``directory`` (newest first), optionally filtered
    to one process name (``rank1``, ``coordinator``)."""
    prefix = f"flight-{proc}." if proc else "flight-"
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith(prefix) and n.endswith(".ring")]
    except OSError:
        return []
    paths = [os.path.join(directory, n) for n in names]
    paths.sort(key=lambda p: (os.path.getmtime(p), p), reverse=True)
    return paths


def read_latest(directory: str, proc: str) -> dict | None:
    """Recover the newest ring for ``proc``, or None."""
    for p in find_rings(directory, proc):
        try:
            return read_ring(p)
        except OSError:
            continue
    return None


# ----------------------------------------------------------------------
# process-global recorder

_LOCK = threading.Lock()
_RECORDER: FlightRecorder | _NullRecorder | None = None
_PROC_NAME = None


def init(proc: str, *, directory: str | None = None):
    """Open (or return) this process's recorder as ``proc``
    (``coordinator`` / ``rank{N}``).  Re-initializing under a new name
    opens a new ring — a process that becomes a different actor (tests)
    gets a fresh black box."""
    global _RECORDER, _PROC_NAME
    with _LOCK:
        if _RECORDER is not None and _PROC_NAME == proc:
            return _RECORDER
        if _RECORDER is not None:
            _RECORDER.close()
        _PROC_NAME = proc
        if not _enabled_by_env():
            _RECORDER = _NullRecorder()
            return _RECORDER
        try:
            d = directory or run_dir()
            from ..utils import knobs
            size = knobs.get_int("NBD_FLIGHT_RING_BYTES",
                                 DEFAULT_RING_BYTES)
            _RECORDER = FlightRecorder(ring_path(d, proc), size)
        except Exception:
            _RECORDER = _NullRecorder()
        return _RECORDER


def recorder():
    """The process recorder; a no-op recorder until :func:`init` names
    this process (so library code can record unconditionally)."""
    r = _RECORDER
    if r is None:
        return _NULL
    return r


def record(etype: str, **fields) -> None:
    """Module-level append on the process recorder (no-op before
    :func:`init`)."""
    r = _RECORDER
    if r is not None:
        r.record(etype, **fields)


def export_health(registry=None) -> dict:
    """Mirror the process recorder's ring health into gauges
    (``nbd_flight_*``) so silent evidence loss — a wrapped ring, a
    dropped or truncated record — is scrapeable before a postmortem
    needs the evidence.  Returns the health dict it exported.  Called
    from the worker's ``metrics`` handler, ``%dist_metrics``, and the
    scrape endpoint's collector (never the hot append path)."""
    from . import metrics as obs_metrics
    reg = registry or obs_metrics.registry()
    h = recorder().health()
    reg.gauge("nbd_flight_ring_utilization",
              "flight-recorder ring fill fraction this lap (1.0 = "
              "wrapped: appends now destroy history)"
              ).set(h["utilization"])
    reg.gauge("nbd_flight_ring_wraps",
              "flight-recorder ring wraps").set(h["wraps"])
    reg.gauge("nbd_flight_records",
              "flight-recorder records appended").set(h["records"])
    reg.gauge("nbd_flight_records_overwritten",
              "flight records aged out by ring wraps (no longer "
              "recoverable)").set(h["overwritten"])
    reg.gauge("nbd_flight_records_truncated",
              "flight records whose oversize payload was capped "
              "(recovery skips them as torn)").set(h["truncated"])
    reg.gauge("nbd_flight_records_dropped",
              "flight records lost to encode/write failures"
              ).set(h["dropped"])
    return h


def reset_for_tests() -> None:
    global _RECORDER, _PROC_NAME
    with _LOCK:
        if _RECORDER is not None:
            _RECORDER.close()
        _RECORDER = None
        _PROC_NAME = None


_NULL = _NullRecorder()
