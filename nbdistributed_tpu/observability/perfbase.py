"""Perf-regression sentinel core (ISSUE 18): score a loadgen report
(+ optional serving stage summary) against a checked-in baseline with
per-metric noise bands.

``BENCH_BASELINES.json`` at the repo root pins what the CI smoke is
expected to deliver; :func:`score` compares a fresh report against it
and names every metric that moved outside its band in the worse
direction.  The contract is deliberately simple so the gate is
auditable:

* Each watched metric has a DIRECTION (``higher``/``lower`` is
  better) and a NOISE BAND (fractional, e.g. ``0.25`` = 25%).  A
  regression is a move past the band in the worse direction;
  improvements and in-band noise pass.
* Bands live IN the baseline file — the checked-in artifact is the
  complete contract, and re-seeding (``nbd_perfwatch.py --update``)
  preserves any hand-tuned band.
* The diff is machine-readable (one dict per metric: baseline,
  current, delta fraction, band, verdict) so CI can upload it as an
  artifact and a human can read why the build failed without
  re-running anything.

``NBD_PERFWATCH_BASELINE`` points elsewhere for local experiments;
``NBD_PERFWATCH_BAND_SCALE`` widens/narrows every band uniformly
(e.g. ``2.0`` on a noisy shared runner).

Pure host-side arithmetic on purpose: no jax, no subprocess, no
clock — ``tools/nbd_perfwatch.py`` owns IO and process exit codes,
bench.py and the unit tests drive these functions directly.
"""

from __future__ import annotations

import json

BASELINE_SCHEMA_VERSION = 1

# metric name -> (direction, default noise band fraction).
# Bands are sized so a real regression (the ISSUE 18 acceptance pins
# tokens/s -30% and p99 TTFT +3x) always trips while honest run-to-run
# CPU-runner noise does not.  Latency tails get wider bands than
# throughput: p99 on a small smoke is inherently jumpier.
DEFAULT_BANDS: dict[str, tuple[str, float]] = {
    "tokens_per_s": ("higher", 0.25),
    "completed": ("higher", 0.15),
    "shed_rate": ("lower", 0.10),       # absolute band (rate in [0,1])
    "ttft_ms_p99": ("lower", 1.00),
    "ttft_ms_p50": ("lower", 1.00),
    "tpot_ms_p99": ("lower", 1.00),
    "e2e_ms_p99": ("lower", 1.00),
    "stage_decode_ms_p95": ("lower", 1.50),
    "stage_queue_ms_p95": ("lower", 1.50),
}

# Metrics whose band is ABSOLUTE (same units as the metric) rather
# than a fraction of the baseline — rates near zero have no sensible
# relative band.
ABSOLUTE_BAND = frozenset({"shed_rate"})


def extract_metrics(report: dict,
                    stage_summary: dict | None = None) -> dict:
    """Flatten the watched metrics out of a pinned loadgen report
    (:mod:`~..serving_fast.loadgen`) and an optional
    :meth:`~.servingobs.ServingObservatory.summary` block.  Missing
    pieces are skipped, never invented — a baseline seeded without
    stage data simply does not gate stages."""
    out: dict[str, float] = {}
    for k in ("tokens_per_s", "completed", "shed_rate"):
        v = report.get(k)
        if v is not None:
            out[k] = float(v)
    client = report.get("client") or {}
    for src, pfx in (("ttft_ms", "ttft_ms"), ("tpot_ms", "tpot_ms"),
                     ("e2e_ms", "e2e_ms")):
        block = client.get(src) or {}
        for q in ("p50", "p99"):
            if block.get(q) is not None:
                out[f"{pfx}_{q}"] = float(block[q])
    stages = (stage_summary or {}).get("stages") or {}
    for s in ("decode", "queue"):
        st = stages.get(s) or {}
        if st.get("p95") is not None:
            out[f"stage_{s}_ms_p95"] = float(st["p95"])
    return out


def make_baseline(metrics: dict, *, source: str = "",
                  bands: dict | None = None) -> dict:
    """Build one baseline entry: watched metrics that have a known
    direction, each with its band pinned alongside the value."""
    entry: dict = {"source": source, "metrics": {}}
    for name, value in sorted(metrics.items()):
        spec = DEFAULT_BANDS.get(name)
        if spec is None:
            continue
        direction, band = spec
        if bands and name in bands:
            band = float(bands[name])
        entry["metrics"][name] = {
            "value": round(float(value), 4),
            "direction": direction,
            "band": band,
        }
    return entry


def load_baselines(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != BASELINE_SCHEMA_VERSION:
        raise ValueError(
            f"baseline schema {doc.get('schema')!r} != "
            f"{BASELINE_SCHEMA_VERSION} — re-seed with "
            f"tools/nbd_perfwatch.py --update")
    return doc


def save_baselines(path: str, doc: dict) -> None:
    doc = dict(doc, schema=BASELINE_SCHEMA_VERSION)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def score(baseline_entry: dict, metrics: dict, *,
          band_scale: float = 1.0) -> dict:
    """Compare current ``metrics`` against one baseline entry.

    Returns ``{"pass": bool, "regressions": [names...],
    "metrics": {name: {"baseline", "current", "delta", "band",
    "direction", "verdict"}}}`` where ``delta`` is the relative move
    (absolute for :data:`ABSOLUTE_BAND` metrics) SIGNED so that
    positive always means "worse".  Metrics present in the baseline
    but missing from the run are verdict ``missing`` and FAIL — a
    report that silently stopped carrying a gated number must not
    pass the gate."""
    out: dict = {"pass": True, "regressions": [], "metrics": {}}
    base_metrics = baseline_entry.get("metrics") or {}
    for name, spec in sorted(base_metrics.items()):
        base = float(spec["value"])
        band = float(spec["band"]) * max(0.0, float(band_scale))
        direction = spec.get("direction", "lower")
        cur = metrics.get(name)
        if cur is None:
            out["metrics"][name] = {
                "baseline": base, "current": None, "delta": None,
                "band": band, "direction": direction,
                "verdict": "missing"}
            out["regressions"].append(name)
            out["pass"] = False
            continue
        cur = float(cur)
        if name in ABSOLUTE_BAND:
            delta = cur - base
        elif base != 0:
            delta = (cur - base) / abs(base)
        else:
            # Baseline of zero: any nonzero current is an infinite
            # relative move; judge it absolutely against the band.
            delta = cur
        if direction == "higher":
            delta = -delta        # positive always = worse
        if delta > band:
            verdict = "regressed"
            out["regressions"].append(name)
            out["pass"] = False
        elif delta < -band:
            verdict = "improved"
        else:
            verdict = "ok"
        out["metrics"][name] = {
            "baseline": base, "current": round(cur, 4),
            "delta": round(delta, 4), "band": band,
            "direction": direction, "verdict": verdict}
    return out


def format_diff(result: dict) -> str:
    """One line per gated metric, worst first — the human half of the
    machine-readable diff."""
    order = {"regressed": 0, "missing": 1, "improved": 2, "ok": 3}
    lines = []
    items = sorted((result.get("metrics") or {}).items(),
                   key=lambda kv: (order.get(kv[1]["verdict"], 9),
                                   kv[0]))
    for name, m in items:
        mark = {"regressed": "✗", "missing": "?", "improved": "✓",
                "ok": "·"}.get(m["verdict"], "·")
        cur = ("—" if m["current"] is None
               else f"{m['current']:g}")
        delta = ("" if m["delta"] is None
                 else f" ({m['delta'] * 100:+.1f}% worse-direction, "
                      f"band ±{m['band'] * 100:.0f}%)")
        lines.append(f" {mark} {name}: {m['baseline']:g} -> {cur}"
                     f"{delta} [{m['verdict']}]")
    verdict = "PASS" if result.get("pass") else "REGRESSION"
    lines.append(f" => {verdict}"
                 + (f": {', '.join(result['regressions'])}"
                    if result.get("regressions") else ""))
    return "\n".join(lines)
