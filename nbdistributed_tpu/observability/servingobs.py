"""Serving observatory (ISSUE 18): per-request decode lifecycle
attribution + KV/batching utilization telemetry for the serving fast
path.

The latency observatory (:mod:`.latency`) gives every ``execute`` an
exact eight-stage decomposition; this module extends the same "every
millisecond has an address" guarantee to served tokens.  Every request
the :class:`~..gateway.serving.ServingManager` completes gets a
CONTIGUOUS stage decomposition::

    admit -> queue -> kv_alloc -> prefill -> decode_wait -> decode
          -> emit -> deliver

that sums to the observed end-to-end latency *by construction*, under
the same clock discipline latency.py pins down:

* Interval boundaries are GATEWAY wall-clock anchors (submit entry,
  ticket grant, placement, first/last emission arrival, finish), so
  adjacent stages share their boundary and the telescoping sum is
  exact — no cross-clock subtraction ever enters the sum.
* Worker-side durations (decode compute per tick, gateway emit
  handling) only SPLIT the span they live in: ``decode`` and ``emit``
  are capped to the ``[first_tok, last_emit]`` span and
  ``decode_wait`` is the remainder, so every stage is >= 0 and the
  three still sum to the span exactly (the proportional-split
  discipline latency.py uses for the wire/reply pair).
* TTFT decomposes as ``admit + queue + kv_alloc + prefill`` — again
  telescoping, so the identity is exact, not approximate.
* TPOT uses WORKER emission timestamps corrected by the NTP-style
  per-rank offset estimator (:mod:`.clock`) when stamps are present
  (cross-rank decode ticks must not mix clocks), clamped >= 0 like
  every latency.py stage, with the gateway arrival times as the
  fallback.

Records land in ``nbd_serve_stage_seconds{stage,tenant}`` histograms
(resolved through the registry at every use so tenant eviction's
``remove_label_series`` really retires them), a bounded ring behind
``%dist_serve lat`` (``NBD_SERVE_LAT`` / ``NBD_SERVE_LAT_RING``), and
``stage/*`` tracer spans that fold into the Perfetto merged trace with
per-request named tracks (``attrs["serve_rid"]``).

The second half is per-tick utilization: the serving driver feeds one
sample per decode tick (batch fill ratio, prefill-vs-decode token
split, per-rank KV block occupancy / fragmentation / defer depth) into
a time-series ring rendered by ``%dist_serve status`` and
``/latency.json``, and mirrored into gauges for scrapes.
"""

from __future__ import annotations

import threading
from collections import deque

from . import metrics as obs_metrics
from .latency import _ms, percentile
from ..utils import knobs

SERVE_STAGES = ("admit", "queue", "kv_alloc", "prefill",
                "decode_wait", "decode", "emit", "deliver")

DEFAULT_RING = 256


def largest_free_run(free_ids) -> int:
    """Longest contiguous run of block ids in ``free_ids`` — the
    fragmentation number next to the free count: a pool with 40 free
    blocks in runs of 1 behaves very differently from one 40-block
    run.  Accepts any iterable; ids need not be sorted."""
    ids = sorted(set(int(b) for b in free_ids))
    best = run = 0
    prev = None
    for b in ids:
        run = run + 1 if prev is not None and b == prev + 1 else 1
        best = max(best, run)
        prev = b
    return best


class _PendingServe:
    """Accumulating stamps for one in-flight served request.  Written
    only under the observatory lock."""

    __slots__ = ("rid", "tenant", "t_submit", "t_admit", "t_placed",
                 "rank", "kv_alloc_s", "need_blocks", "t_first",
                 "t_last", "decode_s", "emit_s", "worker_ts",
                 "n_tokens", "pf_done", "pf_total")

    def __init__(self, rid: str, tenant: str, t_submit: float):
        self.rid = rid
        self.tenant = tenant
        self.t_submit = t_submit
        self.t_admit: float | None = None
        self.t_placed: float | None = None
        self.rank: int | None = None
        self.kv_alloc_s = 0.0
        self.need_blocks = 0
        self.t_first: float | None = None
        self.t_last: float | None = None
        self.decode_s = 0.0      # worker tick compute while active
        self.emit_s = 0.0        # gateway emission-handling time
        # (corrected worker ts, cumulative token count) per emission —
        # the clock-corrected TPOT source (satellite: cross-rank
        # decode ticks must not mix clocks).
        self.worker_ts: list[tuple[float, int]] = []
        self.n_tokens = 0
        self.pf_done = 0         # prefill chunks written
        self.pf_total = 0        # prefill chunks planned


class ServingObservatory:
    """Stage attribution + utilization telemetry for one serving
    plane.  All note_* calls are cheap dict/deque writes under one
    lock; the driver calls them from its tick loop and ``submit``
    threads call begin/admit/drop — the lock is never held across IO.
    """

    def __init__(self, *, clock=None, now=None):
        self.enabled = knobs.get_bool("NBD_SERVE_LAT", True)
        ring = knobs.get_int("NBD_SERVE_LAT_RING", DEFAULT_RING)
        self._clock = clock                    # ClockEstimator | None
        import time
        self._now = now or time.time
        self._lock = threading.Lock()
        self._pending: dict[str, _PendingServe] = {}
        self._ring: deque = deque(maxlen=max(8, ring))
        self._util: deque = deque(maxlen=max(8, ring))
        self.completed = 0
        self.dropped = 0

    # ------------------------------------------------------------------
    # request lifecycle (driven by ServingManager)

    def begin(self, rid: str, tenant: str,
              t_submit: float | None = None) -> None:
        if not self.enabled:
            return
        t = self._now() if t_submit is None else t_submit
        with self._lock:
            self._pending[rid] = _PendingServe(rid, tenant, t)

    def note_admit(self, rid: str, t: float | None = None) -> None:
        """Verdict issued: journal accepted + scheduler ticket held."""
        with self._lock:
            p = self._pending.get(rid)
            if p is not None and p.t_admit is None:
                p.t_admit = self._now() if t is None else t

    def note_placed(self, rid: str, rank: int, *,
                    kv_alloc_s: float = 0.0, need_blocks: int = 0,
                    pf_total: int = 0,
                    t: float | None = None) -> None:
        """Placed on a decode rank; ``kv_alloc_s`` is the measured
        block-reservation time inside placement.  Failover re-places
        a request — only the FIRST placement ends its queue stage
        (matching ``_Req.placed_ts``), but the rank always updates so
        the record names where it finished."""
        with self._lock:
            p = self._pending.get(rid)
            if p is None:
                return
            p.rank = rank
            p.kv_alloc_s += max(0.0, kv_alloc_s)
            if need_blocks:
                p.need_blocks = need_blocks
            if pf_total:
                p.pf_total = pf_total
            if p.t_placed is None:
                p.t_placed = self._now() if t is None else t

    def note_emission(self, rid: str, rank: int, n_toks: int, *,
                      t_recv: float | None = None,
                      t_worker: float | None = None,
                      emit_s: float = 0.0) -> None:
        """Tokens arrived from a decode rank.  ``t_worker`` is the
        worker's wall clock when the tick replied; it is corrected by
        the per-rank offset estimate HERE, so every stored stamp is
        already on the gateway clock."""
        with self._lock:
            p = self._pending.get(rid)
            if p is None:
                return
            t = self._now() if t_recv is None else t_recv
            if p.t_first is None:
                p.t_first = t
            p.t_last = t
            p.n_tokens += max(0, n_toks)
            p.emit_s += max(0.0, emit_s)
            if t_worker is not None:
                off = 0.0
                if self._clock is not None:
                    try:
                        off = float(self._clock.offset(rank))
                    except Exception:
                        off = 0.0
                p.worker_ts.append((t_worker - off, p.n_tokens))

    def note_decode(self, rid: str, step_s: float) -> None:
        """Attribute one tick's decode compute to an active request.
        Continuous batching shares the forward, so every active
        request's wall time during the tick IS the whole tick — the
        per-request decode stage accumulates tick compute, and
        ``decode_wait`` absorbs the scheduling/wire remainder."""
        with self._lock:
            p = self._pending.get(rid)
            if p is not None:
                p.decode_s += max(0.0, step_s)

    def note_prefill_progress(self, rid: str, done: int,
                              total: int) -> None:
        with self._lock:
            p = self._pending.get(rid)
            if p is not None:
                p.pf_done = max(p.pf_done, int(done))
                p.pf_total = max(p.pf_total, int(total))

    def drop(self, rid: str) -> None:
        """Forget a request that will never complete here (shed,
        rejected, failed before any stage worth recording)."""
        with self._lock:
            if self._pending.pop(rid, None) is not None:
                self.dropped += 1

    def complete(self, rid: str, status: str,
                 t_finish: float | None = None,
                 tracer=None) -> dict | None:
        """Close the record: compute the contiguous stage split, push
        it onto the ring + histograms, mirror tracer spans.  Returns
        the record (``None`` when the request was never begun)."""
        with self._lock:
            p = self._pending.pop(rid, None)
        if p is None:
            return None
        t_finish = self._now() if t_finish is None else t_finish

        def pos(x: float) -> float:
            return x if x > 0.0 else 0.0

        t_admit = p.t_admit if p.t_admit is not None else p.t_submit
        t_placed = p.t_placed if p.t_placed is not None else t_admit
        t_first = p.t_first if p.t_first is not None else t_placed
        t_last = p.t_last if p.t_last is not None else t_first

        stages: dict[str, float] = {}
        stages["admit"] = pos(t_admit - p.t_submit)
        stages["queue"] = pos(t_placed - t_admit)
        # TTFT tail: [placed, first_tok] = kv_alloc + prefill.  The
        # measured allocation time is capped to the span and prefill
        # is the remainder, so ttft == admit + queue + kv_alloc +
        # prefill EXACTLY (telescoping gateway anchors).
        ttft_tail = pos(t_first - t_placed)
        stages["kv_alloc"] = min(pos(p.kv_alloc_s), ttft_tail)
        stages["prefill"] = ttft_tail - stages["kv_alloc"]
        # Decode span: worker-attributed compute and gateway emit
        # handling are capped to it; decode_wait is the remainder
        # (rank scheduling, wire, other tenants' ticks).
        span = pos(t_last - t_first)
        stages["decode"] = min(pos(p.decode_s), span)
        stages["emit"] = min(pos(p.emit_s), span - stages["decode"])
        stages["decode_wait"] = (span - stages["decode"]
                                 - stages["emit"])
        stages["deliver"] = pos(t_finish - t_last)

        e2e = pos(t_finish - p.t_submit)
        ttft = (stages["admit"] + stages["queue"]
                + stages["kv_alloc"] + stages["prefill"])
        tpot = self._tpot(p)

        rec = {
            "rid": rid,
            "tenant": p.tenant,
            "rank": p.rank,
            "status": status,
            "ts": round(t_finish, 6),
            "e2e_s": round(e2e, 6),
            "ttft_s": round(ttft, 6),
            "tpot_s": round(tpot, 6) if tpot is not None else None,
            "n_tokens": p.n_tokens,
            "need_blocks": p.need_blocks,
            "prefill_chunks": [p.pf_done, p.pf_total],
            "stages": {s: round(stages[s], 6) for s in SERVE_STAGES},
        }
        with self._lock:
            self._ring.append(rec)
            self.completed += 1

        if self.enabled:
            reg = obs_metrics.registry()
            for s in SERVE_STAGES:
                # Resolved fresh each time: tenant eviction's
                # remove_label_series must really retire these.
                reg.histogram(
                    "nbd_serve_stage_seconds",
                    "per-request serving stage durations (contiguous "
                    "decomposition summing to e2e)",
                    {"stage": s, "tenant": p.tenant},
                    buckets=obs_metrics.LATENCY_BUCKETS,
                ).observe(stages[s])
        if tracer is not None and getattr(tracer, "enabled", False):
            self._mirror_spans(tracer, p, stages, t_finish)
        return rec

    def _tpot(self, p: _PendingServe) -> float | None:
        """Mean inter-token time AFTER the first emission, from
        clock-corrected worker stamps when available (two or more
        emissions carried them), else gateway arrival times.  Clamped
        >= 0: an offset-estimate error must never surface as negative
        time."""
        stamps = p.worker_ts
        if len(stamps) >= 2:
            (t0, n0), (t1, n1) = stamps[0], stamps[-1]
            if n1 > n0:
                return max(0.0, (t1 - t0) / (n1 - n0))
        if (p.t_first is not None and p.t_last is not None
                and p.n_tokens > 1):
            return max(0.0, (p.t_last - p.t_first) / (p.n_tokens - 1))
        return None

    def _mirror_spans(self, tracer, p: _PendingServe,
                      stages: dict, t_finish: float) -> None:
        """Stage child spans for the Perfetto merged trace.  The
        ``serve_rid`` attr keys per-request named tracks in
        export.py's merge (tenant tracks already exist; request
        tracks ride the same mechanism one level finer)."""
        attrs = {"serve_rid": p.rid, "tenant": p.tenant}
        if p.rank is not None:
            attrs["rank"] = p.rank
        t = p.t_submit
        for s in SERVE_STAGES:
            dur = stages[s]
            if dur > 0:
                tracer.add_span(f"stage/{s}", "serving", t, dur,
                                attrs=attrs)
            t += dur

    # ------------------------------------------------------------------
    # utilization telemetry (per decode tick)

    def note_util(self, *, ranks: dict, prefill_toks: int = 0,
                  decode_toks: int = 0, backlog: int = 0,
                  tenant: str = "", t: float | None = None) -> None:
        """One per-tick utilization sample.  ``ranks`` maps rank ->
        ``{"placed", "slots", "kv_used", "kv_free", "frag",
        "pending"}`` (gateway-side allocator mirrors + worker-reported
        defer depth); token counts are the tick's prefill/decode
        split summed across ranks."""
        slots = sum(int(v.get("slots") or 0) for v in ranks.values())
        placed = sum(int(v.get("placed") or 0) for v in ranks.values())
        fill = (placed / slots) if slots else 0.0
        sample = {
            "ts": round(self._now() if t is None else t, 3),
            "fill": round(fill, 4),
            "prefill_toks": int(prefill_toks),
            "decode_toks": int(decode_toks),
            "backlog": int(backlog),
            "ranks": {str(r): dict(v) for r, v in ranks.items()},
        }
        with self._lock:
            self._util.append(sample)
        if not self.enabled:
            return
        reg = obs_metrics.registry()
        labels = {"tenant": tenant} if tenant else {}
        reg.gauge("nbd_serve_batch_fill_ratio",
                  "decode-slot occupancy across open ranks, last tick",
                  labels).set(round(fill, 4))
        reg.gauge("nbd_serve_tick_prefill_tokens",
                  "prompt tokens prefilled during the last decode "
                  "tick (chunked-prefill share of the tick)",
                  labels).set(int(prefill_toks))
        reg.gauge("nbd_serve_tick_decode_tokens",
                  "tokens decoded during the last decode tick",
                  labels).set(int(decode_toks))
        for r, v in ranks.items():
            rl = dict(labels, rank=str(r))
            if v.get("frag") is not None:
                reg.gauge("nbd_kv_frag_largest_run",
                          "largest contiguous free KV-block run on "
                          "this decode rank (fragmentation: compare "
                          "with nbd_kv_blocks_free)", rl
                          ).set(int(v["frag"]))
            if v.get("pending") is not None:
                reg.gauge("nbd_serve_defer_depth",
                          "requests deferred worker-side (admitted "
                          "but pending on KV blocks) on this rank",
                          rl).set(int(v["pending"]))

    # ------------------------------------------------------------------
    # readers

    def records(self, last: int | None = None) -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        return recs[-last:] if last else recs

    def util_samples(self, last: int | None = None) -> list[dict]:
        with self._lock:
            recs = list(self._util)
        return recs[-last:] if last else recs

    def summary(self) -> dict:
        """Percentile table over the ring, milliseconds:
        ``{"count", "dropped", "e2e_ms": {...}, "ttft_ms": {...},
        "tpot_ms": {...}, "stages": {stage: {p50,p95,p99,mean,
        share}}}``."""
        recs = self.records()
        out: dict = {"count": len(recs), "dropped": self.dropped}
        if not recs:
            return out

        def _stats(vals: list[float]) -> dict:
            sv = sorted(vals)
            return {"p50": _ms(percentile(sv, 0.50)),
                    "p95": _ms(percentile(sv, 0.95)),
                    "p99": _ms(percentile(sv, 0.99)),
                    "mean": _ms(sum(sv) / len(sv))}

        e2e = [r["e2e_s"] for r in recs]
        out["e2e_ms"] = _stats(e2e)
        out["ttft_ms"] = _stats([r["ttft_s"] for r in recs])
        tpots = [r["tpot_s"] for r in recs if r["tpot_s"] is not None]
        if tpots:
            out["tpot_ms"] = _stats(tpots)
        mean_e2e = sum(e2e) / len(e2e)
        stages: dict[str, dict] = {}
        for s in SERVE_STAGES:
            vals = [r["stages"][s] for r in recs]
            st = _stats(vals)
            st["share"] = (round((sum(vals) / len(vals)) / mean_e2e, 4)
                           if mean_e2e > 0 else 0.0)
            stages[s] = st
        out["stages"] = stages
        return out

    def util_summary(self, window: int = 32) -> dict:
        """Recent utilization aggregate for status surfaces: mean/max
        batch fill, prefill-vs-decode token split, newest per-rank
        occupancy/fragmentation/defer sample."""
        recs = self.util_samples(window)
        if not recs:
            return {"count": 0}
        fills = [r["fill"] for r in recs]
        pf = sum(r["prefill_toks"] for r in recs)
        dc = sum(r["decode_toks"] for r in recs)
        return {
            "count": len(recs),
            "fill_mean": round(sum(fills) / len(fills), 4),
            "fill_max": round(max(fills), 4),
            "prefill_toks": pf,
            "decode_toks": dc,
            "prefill_share": (round(pf / (pf + dc), 4)
                              if (pf + dc) else 0.0),
            "ranks": recs[-1]["ranks"],
        }

    def status_block(self, records: int = 0) -> dict:
        """The machine-readable serving block for ``/latency.json``
        and ``serve_status`` replies."""
        out = {"enabled": self.enabled, "summary": self.summary(),
               "util": self.util_summary()}
        if records:
            out["records"] = self.records(records)
        return out


# ----------------------------------------------------------------------
# renderers (%dist_serve lat)


def format_serve_stage_table(summary: dict) -> str:
    """Fixed-width per-stage percentile table (milliseconds)."""
    stages = summary.get("stages") or {}
    if not stages:
        return "(no completed serving records yet)"
    lines = [f"{'stage':<12} {'p50':>9} {'p95':>9} {'p99':>9} "
             f"{'mean':>9} {'share':>7}"]
    for s in SERVE_STAGES:
        st = stages.get(s)
        if not st:
            continue
        lines.append(
            f"{s:<12} {st['p50']:>9.2f} {st['p95']:>9.2f} "
            f"{st['p99']:>9.2f} {st['mean']:>9.2f} "
            f"{st['share'] * 100:>6.1f}%")
    e2e = summary.get("e2e_ms") or {}
    ttft = summary.get("ttft_ms") or {}
    if e2e:
        lines.append(
            f"{'e2e':<12} {e2e['p50']:>9.2f} {e2e['p95']:>9.2f} "
            f"{e2e['p99']:>9.2f} {e2e['mean']:>9.2f} {'100%':>7}")
    if ttft:
        lines.append(
            f"{'ttft':<12} {ttft['p50']:>9.2f} {ttft['p95']:>9.2f} "
            f"{ttft['p99']:>9.2f} {ttft['mean']:>9.2f} {'':>7}")
    return "\n".join(lines)


def format_serve_waterfall(records: list[dict],
                           width: int = 44) -> str:
    """ASCII per-request waterfall of the stage decomposition —
    one row per record, bars proportional to stage duration within
    the longest e2e shown."""
    if not records:
        return "(no completed serving records yet)"
    glyphs = {"admit": "a", "queue": "·", "kv_alloc": "k",
              "prefill": "▒", "decode_wait": "-", "decode": "█",
              "emit": "e", "deliver": "d"}
    t_max = max(r["e2e_s"] for r in records) or 1e-9
    scale = width / t_max
    lines = ["  " + " ".join(f"{glyphs[s]}={s}"
                             for s in SERVE_STAGES)]
    for r in records:
        bar = ""
        for s in SERVE_STAGES:
            n = int(round(r["stages"][s] * scale))
            bar += glyphs[s] * n
        bar = bar[:width]
        rk = f"r{r['rank']}" if r.get("rank") is not None else "r?"
        lines.append(
            f"{r['rid']:>8} {rk:>3} {bar:<{width}} "
            f"{_ms(r['e2e_s']):>8.1f}ms "
            f"ttft {_ms(r['ttft_s']):>7.1f}ms "
            f"{r['n_tokens']:>4}tok")
    return "\n".join(lines)
