"""Merge per-process span dumps into one Chrome-trace-event JSON.

The output loads directly in Perfetto (ui.perfetto.dev → "Open trace
file") or ``chrome://tracing``: one *process* row per rank (``pid`` =
rank; the coordinator is ``pid`` −1, matching its sentinel rank in the
wire protocol), one *thread* track per recording thread, spans as
complete events (``ph: "X"``), and :class:`FaultPlan` decisions folded
in as instant events (``ph: "i"``) so a chaos run shows *where* the
drops and duplicates landed relative to the requests they afflicted.

Worker timestamps are corrected by the per-rank clock offset estimated
from request RTTs (:mod:`~nbdistributed_tpu.observability.clock`), and
the whole merge is rebased to the earliest event so timestamps stay
small.  Span/parent ids travel in ``args`` — Perfetto surfaces them in
the detail pane, which is how a worker handler span is tied back to
the coordinator send span that caused it.
"""

from __future__ import annotations

import json
from typing import Any

COORDINATOR_PID = -1


# Tenant-tagged records (gateway pools) are rehomed onto a dedicated
# per-tenant thread track inside their process row, named
# ``tenant:<name>`` — a multi-tenant postmortem then reads as one lane
# per notebook instead of interleaved anonymous thread ids.  The base
# offset keeps tenant tids clear of real recording-thread ids.
_TENANT_TID_BASE = 1 << 20

# Served requests (ISSUE 18) go one level finer: records whose attrs
# carry a ``serve_rid`` (the serving observatory's stage spans) land
# on a per-request named track ``serve:<rid>`` — a request's whole
# lifecycle reads as one lane.  The base keeps them clear of both
# thread ids and tenant tids.
_SERVE_TID_BASE = 1 << 21


def _tenant_tid(ev_attrs: dict | None,
                tenant_tids: dict[str, int] | None,
                serve_tids: dict[str, int] | None = None
                ) -> int | None:
    if not ev_attrs:
        return None
    rid = ev_attrs.get("serve_rid")
    if rid and serve_tids:
        tid = serve_tids.get(str(rid))
        if tid is not None:
            return tid
    if not tenant_tids:
        return None
    name = ev_attrs.get("tenant")
    return tenant_tids.get(name) if name else None


def _span_event(span: dict, pid: int, offset_s: float,
                base_s: float,
                tenant_tids: dict[str, int] | None = None,
                serve_tids: dict[str, int] | None = None) -> dict:
    args: dict[str, Any] = dict(span.get("attrs") or {})
    args["trace_id"] = span.get("trace_id")
    args["span_id"] = span.get("span_id")
    if span.get("parent_id"):
        args["parent_id"] = span["parent_id"]
    tid = _tenant_tid(span.get("attrs"), tenant_tids, serve_tids)
    return {
        "name": span["name"],
        "cat": span.get("kind") or "span",
        "ph": "X",
        "ts": (span["t0"] - offset_s - base_s) * 1e6,
        "dur": max(0.0, span.get("dur", 0.0)) * 1e6,
        "pid": pid,
        "tid": span.get("tid", 0) if tid is None else tid,
        "args": args,
    }


def _instant_event(ev: dict, pid: int, offset_s: float,
                   base_s: float,
                   tenant_tids: dict[str, int] | None = None,
                   serve_tids: dict[str, int] | None = None) -> dict:
    tid = _tenant_tid(ev.get("attrs"), tenant_tids, serve_tids)
    return {
        "name": ev["name"],
        "cat": ev.get("kind") or "instant",
        "ph": "i",
        "s": "t",
        "ts": (ev["t0"] - offset_s - base_s) * 1e6,
        "pid": pid,
        "tid": ev.get("tid", 0) if tid is None else tid,
        "args": dict(ev.get("attrs") or {}),
    }


def _collect_tenants(*dumps: dict | None) -> dict[str, int]:
    """Stable tenant → tid assignment across every process dump (the
    same tenant gets the same tid offset in every pid row)."""
    names: set[str] = set()
    for dump in dumps:
        for s in (dump or {}).get("spans", []):
            t = (s.get("attrs") or {}).get("tenant")
            if t:
                names.add(str(t))
        for ev in (dump or {}).get("instants", []):
            t = (ev.get("attrs") or {}).get("tenant")
            if t:
                names.add(str(t))
    return {n: _TENANT_TID_BASE + i
            for i, n in enumerate(sorted(names))}


def _collect_serve_rids(*dumps: dict | None) -> dict[str, int]:
    """Stable serve_rid → tid assignment across every process dump
    (the serving observatory's per-request stage spans, ISSUE 18)."""
    rids: set[str] = set()
    for dump in dumps:
        for s in (dump or {}).get("spans", []):
            rid = (s.get("attrs") or {}).get("serve_rid")
            if rid:
                rids.add(str(rid))
    return {r: _SERVE_TID_BASE + i
            for i, r in enumerate(sorted(rids))}


def _tenant_thread_meta(tenant_tids: dict[str, int],
                        pids: list[int],
                        serve_tids: dict[str, int] | None = None
                        ) -> list[dict]:
    out = []
    named = [(f"tenant:{n}", tid)
             for n, tid in sorted(tenant_tids.items(),
                                  key=lambda kv: kv[1])]
    named += [(f"serve:{r}", tid)
              for r, tid in sorted((serve_tids or {}).items(),
                                   key=lambda kv: kv[1])]
    for name, tid in named:
        for pid in pids:
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid,
                        "args": {"name": name}})
            out.append({"name": "thread_sort_index", "ph": "M",
                        "pid": pid, "tid": tid,
                        "args": {"sort_index": tid}})
    return out


def _fault_events(events: list[dict], pid: int, offset_s: float,
                  base_s: float) -> list[dict]:
    out = []
    for ev in events or []:
        for action in ev.get("actions", ()):
            out.append({
                "name": f"fault:{action}",
                "cat": "fault",
                "ph": "i",
                "s": "p",  # process scope: a full-height marker
                "ts": (ev["ts"] - offset_s - base_s) * 1e6,
                "pid": pid,
                "tid": 0,
                "args": {"frame_kind": ev.get("kind")},
            })
    return out


def _meta(pid: int, label: str, sort_index: int) -> list[dict]:
    return [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": label}},
        {"name": "process_sort_index", "ph": "M", "pid": pid, "tid": 0,
         "args": {"sort_index": sort_index}},
    ]


def merge_trace(coordinator: dict | None,
                ranks: dict[int, dict] | None = None,
                offsets: dict[int, float] | None = None,
                coordinator_faults: list[dict] | None = None,
                rank_faults: dict[int, list[dict]] | None = None) -> dict:
    """Build the merged Chrome trace object.

    ``coordinator`` / ``ranks[r]`` are ``Tracer.dump()`` payloads;
    ``offsets[r]`` is the estimated ``worker_clock − coordinator_clock``
    for rank ``r`` (applied as a subtraction, so every event lands on
    the coordinator's timebase); the fault lists are
    ``FaultPlan.events()``.
    """
    ranks = ranks or {}
    offsets = offsets or {}
    rank_faults = rank_faults or {}

    # Rebase to the earliest (corrected) timestamp in the merge.
    t_candidates: list[float] = []
    for dump, off in ([(coordinator, 0.0)] if coordinator else []) + [
            (ranks[r], offsets.get(r, 0.0)) for r in ranks]:
        for s in (dump or {}).get("spans", []):
            t_candidates.append(s["t0"] - off)
        for ev in (dump or {}).get("instants", []):
            t_candidates.append(ev["t0"] - off)
    for ev in coordinator_faults or []:
        t_candidates.append(ev["ts"])
    for r, evs in rank_faults.items():
        off = offsets.get(r, 0.0)
        t_candidates.extend(ev["ts"] - off for ev in evs or [])
    base_s = min(t_candidates) if t_candidates else 0.0

    # Tenant lanes (gateway pools): records whose attrs carry a
    # ``tenant`` land on a per-tenant named thread track.
    tenant_tids = _collect_tenants(coordinator,
                                   *[ranks[r] for r in ranks])
    serve_tids = _collect_serve_rids(coordinator,
                                     *[ranks[r] for r in ranks])

    events: list[dict] = []
    dropped = 0
    if coordinator:
        events += _meta(COORDINATOR_PID, "coordinator", -1)
        events += [_span_event(s, COORDINATOR_PID, 0.0, base_s,
                               tenant_tids, serve_tids)
                   for s in coordinator.get("spans", [])]
        events += [_instant_event(ev, COORDINATOR_PID, 0.0, base_s,
                                  tenant_tids, serve_tids)
                   for ev in coordinator.get("instants", [])]
        dropped += coordinator.get("dropped", 0)
    events += _fault_events(coordinator_faults or [], COORDINATOR_PID,
                            0.0, base_s)
    for r in sorted(ranks):
        off = offsets.get(r, 0.0)
        dump = ranks[r] or {}
        events += _meta(r, f"rank {r}", r)
        events += [_span_event(s, r, off, base_s, tenant_tids,
                               serve_tids)
                   for s in dump.get("spans", [])]
        events += [_instant_event(ev, r, off, base_s, tenant_tids,
                                  serve_tids)
                   for ev in dump.get("instants", [])]
        dropped += dump.get("dropped", 0)
    if tenant_tids or serve_tids:
        pids = ([COORDINATOR_PID] if coordinator else []) \
            + sorted(ranks)
        events += _tenant_thread_meta(tenant_tids, pids, serve_tids)
    for r in sorted(rank_faults):
        events += _fault_events(rank_faults[r], r,
                                offsets.get(r, 0.0), base_s)

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "nbdistributed_tpu %dist_trace",
            "base_unix_s": base_s,
            "clock_offsets_s": {str(r): offsets.get(r, 0.0)
                                for r in sorted(ranks)},
            "spans_dropped": dropped,
            "tenant_tracks": {n: t for n, t in
                              sorted(tenant_tids.items())},
            "serve_tracks": {n: t for n, t in
                             sorted(serve_tids.items())},
        },
    }


def save_trace(path: str, merged: dict) -> int:
    """Write the merged trace; returns the number of non-metadata
    events (the useful-content count surfaced by ``%dist_trace
    save``)."""
    with open(path, "w") as f:
        json.dump(merged, f)
    return sum(1 for e in merged["traceEvents"] if e.get("ph") != "M")
