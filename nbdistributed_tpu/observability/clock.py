"""NTP-style per-rank clock-offset estimation from request RTTs.

Every process stamps spans with its own ``time.time()``; on a pod the
hosts' clocks can disagree by milliseconds — enough to render a
worker's handler span *outside* the coordinator send span that caused
it.  The coordinator therefore estimates each rank's offset the way
NTP does, from the request/response timestamps it already has:

    t_send   coordinator clock, request handed to the transport
    t_remote worker clock, reply envelope stamped (codec ``ts``)
    t_recv   coordinator clock, reply arrived

    rtt    = t_recv - t_send
    offset = t_remote - (t_send + t_recv) / 2

A single sample is noisy — the worker stamp is not at the wire
midpoint (handler time skews it late) and queueing inflates RTT — so
the estimator applies the classic NTP filter: keep the K lowest-RTT
samples per rank (minimal queueing ⇒ minimal midpoint error) and
report the median of their offsets.  Fast requests (status probes,
trace control messages) dominate the minimum, which is exactly what we
want.  Corrected worker time = worker wall clock − offset.
"""

from __future__ import annotations

import threading


class ClockEstimator:
    """Accumulates ``(rtt, offset)`` samples per rank; thread-safe
    (fed from the coordinator IO thread)."""

    def __init__(self, keep: int = 16):
        # Per rank: the `keep` lowest-RTT samples seen so far, sorted
        # ascending by RTT.
        self.keep = keep
        self._lock = threading.Lock()
        self._best: dict[int, list[tuple[float, float]]] = {}
        self._count: dict[int, int] = {}

    def add(self, rank: int, t_send: float, t_remote: float,
            t_recv: float) -> None:
        rtt = t_recv - t_send
        if rtt < 0:  # clock stepped mid-request; unusable sample
            return
        offset = t_remote - (t_send + t_recv) / 2.0
        with self._lock:
            best = self._best.setdefault(rank, [])
            self._count[rank] = self._count.get(rank, 0) + 1
            if len(best) < self.keep or rtt < best[-1][0]:
                best.append((rtt, offset))
                best.sort(key=lambda s: s[0])
                del best[self.keep:]

    def offset(self, rank: int) -> float:
        """Estimated ``worker_clock - coordinator_clock`` in seconds
        (0.0 with no samples: an uncorrected merge beats no merge)."""
        with self._lock:
            best = self._best.get(rank)
            if not best:
                return 0.0
            offs = sorted(off for _, off in best)
        mid = len(offs) // 2
        if len(offs) % 2:
            return offs[mid]
        return (offs[mid - 1] + offs[mid]) / 2.0

    def offsets(self) -> dict[int, float]:
        with self._lock:
            ranks = list(self._best)
        return {r: self.offset(r) for r in ranks}

    def stats(self) -> dict[int, dict]:
        """Per-rank diagnostics for status surfaces: sample count, best
        RTT, current estimate."""
        out: dict[int, dict] = {}
        with self._lock:
            items = {r: list(b) for r, b in self._best.items()}
            counts = dict(self._count)
        for r, best in items.items():
            out[r] = {"samples": counts.get(r, 0),
                      "min_rtt_s": best[0][0] if best else None,
                      "offset_s": self.offset(r)}
        return out
