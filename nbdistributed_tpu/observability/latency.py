"""The latency observatory: per-cell stage attribution (ISSUE 13).

The speed arc's claims — "the ~2 ms/cell dispatch overhead amortizes
to <0.1 ms/step", "serving meets its SLO under load" — are only
claims until wall-clock can be decomposed.  This module carries the
one record that makes them measurable: for every completed ``execute``
request, WHERE its end-to-end latency went, as eight contiguous
stages::

    vet      │ pre-submit analysis (cell vetting / effects classify)
    queue    │ scheduler wait (submit → mesh-slot grant)
    wire     │ grant → worker dequeue (encode + send + loop wait)
    dispatch │ worker dequeue → handler entry (replay cache, spans,
             │ busy bookkeeping)
    compile  │ XLA backend-compile seconds inside the handler (from
             │ the existing jax.monitoring listener, telemetry.py)
    execute  │ handler wall time minus compile
    reply    │ handler exit → coordinator reply arrival (wire back)
    deliver  │ last reply arrival → result handed to the caller

The coordinator stamps submit / grant / deliver on its own clock; the
worker stamps dequeue / handler-entry / handler-exit / reply-build on
ITS clock and the stamps ride home in the reply's optional ``lt``
header (:mod:`..messaging.codec` ``WIRE_EXTENSIONS``).  Worker stamps
are corrected onto the coordinator timebase with the per-rank offset
the NTP-style estimator already maintains (:mod:`.clock`) — the same
correction the Chrome-trace merge applies — so the stage chain is
monotone even across skewed host clocks.  Every stage is clamped at
zero: residual correction error may only shrink a stage, never
produce a negative duration.

Costs nothing when off: the coordinator pays one flag check per
request, the worker pays one flag check per message, and **no wire
header is emitted unless the observatory is enabled**
(``NBD_LAT=0`` — the same absent-when-off contract as ``tr``/``at``/
``ep``).

Completed records feed per-stage log-scale histograms
(``nbd_stage_seconds{stage=…}``, :data:`~.metrics.LATENCY_BUCKETS`)
plus a bounded ring of raw records (``NBD_LAT_RING``) that backs
``%dist_lat`` (per-stage p50/p95/p99 table, ``--last N`` waterfall),
``GET /latency.json`` on the scrape endpoint (:mod:`.httpd`), and the
``bench.py`` ``extra.latency_stages`` snapshot.  While a
``%dist_trace`` session is active, each record is also mirrored into
the trace as ``stage/<name>`` child spans of the request's send span,
so the Perfetto view shows the same decomposition inline.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils import knobs
from . import metrics as obs_metrics

# Stage names, in waterfall order.  The eight stages are CONTIGUOUS by
# construction (each starts where the previous ended), so their sum
# equals the end-to-end latency up to clock-correction clamping — the
# property the integration test pins at 10%.
STAGES = ("vet", "queue", "wire", "dispatch", "compile", "execute",
          "reply", "deliver")

DEFAULT_RING = 256


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list (shared by
    the observatory summary and the serving SLO block)."""
    if not sorted_vals:
        return 0.0
    i = int(round(q * (len(sorted_vals) - 1)))
    return sorted_vals[min(i, len(sorted_vals) - 1)]


def _ms(v: float) -> float:
    return round(v * 1e3, 3)


class _PendingLat:
    __slots__ = ("msg_id", "msg_type", "tenant", "t_vet", "t_submit",
                 "t_grant")

    def __init__(self, msg_id: str, msg_type: str, tenant: str | None,
                 now: float, vet_s: float | None):
        self.msg_id = msg_id
        self.msg_type = msg_type
        self.tenant = tenant
        self.t_submit = now
        # The vet stage is what the CALLER did before submit (cell
        # vetting, effects classification) — reported as a pre-duration
        # because the vetting layers don't know the msg_id yet.
        self.t_vet = now - max(0.0, vet_s or 0.0)
        self.t_grant = now  # overwritten by note_grant


class LatencyObservatory:
    """Coordinator-side stage-attribution recorder.

    One per :class:`~..messaging.coordinator.CommunicationManager`.
    Thread-safe: ``begin``/``note_grant`` run on submitter threads,
    ``complete`` on whichever thread finishes the dispatch, readers
    (``%dist_lat``, the scrape endpoint) on theirs.
    """

    def __init__(self, *, enabled: bool | None = None,
                 ring: int | None = None, registry=None,
                 now=time.time):
        self.enabled = (knobs.get_bool("NBD_LAT", True)
                        if enabled is None else bool(enabled))
        self._now = now
        self._reg = registry or obs_metrics.registry()
        self._lock = threading.Lock()
        self._pending: dict[str, _PendingLat] = {}
        n = ring if ring is not None else knobs.get_int("NBD_LAT_RING",
                                                        DEFAULT_RING)
        self._ring: deque = deque(maxlen=max(8, n))
        self.completed = 0
        self.dropped = 0  # begun but never completed (timeout, shed,
        # rejected, worker death, stamp-less replies)

    # ------------------------------------------------------------------
    # submit-side stamps (coordinator clock)

    def begin(self, msg_id: str, msg_type: str,
              tenant: str | None = None,
              vet_s: float | None = None) -> None:
        if not self.enabled:
            return
        p = _PendingLat(msg_id, msg_type, tenant, self._now(), vet_s)
        with self._lock:
            self._pending[msg_id] = p

    def note_grant(self, msg_id: str) -> None:
        """The scheduler granted the mesh slot (immediately on an idle
        mesh; after the queued wait otherwise) — the queue stage's end."""
        with self._lock:
            p = self._pending.get(msg_id)
        if p is not None:
            p.t_grant = self._now()

    def note_worker_free(self, msg_id: str,
                         t: float | None = None) -> None:
        """Overlap-aware attribution for pipelined cells (ISSUE 14):
        an async-windowed cell is transmitted while its predecessor
        still runs, so the serial worker loop only *reaches* it when
        the predecessor's reply lands.  The executor calls this at
        each predecessor completion for every still-in-flight
        successor, advancing the grant stamp to "the worker became
        free now" — the predecessor wait books as ``queue`` (what it
        is) instead of inflating ``wire``, and pipelined cells never
        double-count the overlapped time.  Monotone: the stamp only
        moves forward, and never past a completion."""
        if not self.enabled:
            return
        t = self._now() if t is None else t
        with self._lock:
            p = self._pending.get(msg_id)
        if p is not None and t > p.t_grant:
            p.t_grant = t

    def drop(self, msg_id: str) -> None:
        """Forget a request that will never complete normally
        (rejected / shed / timed out / worker died).  No-op after
        :meth:`complete` — callers put this in their ``finally``."""
        with self._lock:
            if self._pending.pop(msg_id, None) is not None:
                self.dropped += 1

    # ------------------------------------------------------------------
    # completion

    def complete(self, msg_id: str, replies: dict, offset,
                 t_deliver: float | None = None,
                 tracer=None, parent: dict | None = None) -> dict | None:
        """Close the record for a completed request.

        ``replies`` maps rank → reply Message; per-rank worker stamps
        are read from each reply's ``latency`` header and its
        coordinator-side arrival time from the ``recv_ts`` attribute
        the IO thread stamped.  ``offset(rank)`` is the estimated
        ``worker_clock − coordinator_clock`` (``ClockEstimator.offset``)
        applied as a subtraction.  Returns the record dict (also pushed
        onto the ring and into the histograms), or None when the
        request was never begun or no reply carried stamps.
        """
        with self._lock:
            p = self._pending.pop(msg_id, None)
        if p is None:
            return None
        t_deliver = self._now() if t_deliver is None else t_deliver

        per_rank: dict[int, dict] = {}
        recv_max = None
        crit_rank = None
        for r, msg in replies.items():
            st = getattr(msg, "latency", None)
            recv = getattr(msg, "recv_ts", None)
            if not isinstance(st, dict) or recv is None:
                continue
            try:
                off = float(offset(r))
                dq = float(st["dq"]) - off
                xs = float(st["xs"]) - off
                xe = float(st["xe"]) - off
                rs = float(st.get("rs") or st["xe"]) - off
                cs = max(0.0, float(st.get("cs") or 0.0))
            except (KeyError, TypeError, ValueError):
                continue
            # Worker-side durations are SAME-CLOCK differences — exact
            # regardless of the offset estimate: dispatch (dq→xs),
            # the handler (xs→xe), and reply BUILD (xe→rs: stamping,
            # epoch, replay-cache insert).  Likewise the total wire
            # budget (grant → recv minus the worker's residency) is a
            # coordinator-clock difference.  Only the SPLIT of that
            # budget into outbound wire vs reply wire needs the
            # offset-corrected anchors, so estimation error can skew
            # the split but never the sum — for sub-millisecond cells
            # a few hundred µs of offset error would otherwise clamp
            # one side to zero and inflate the other past e2e.
            handler = max(0.0, xe - xs)
            dispatch = max(0.0, xs - dq)
            build = max(0.0, rs - xe)
            both_wires = max(0.0, recv - p.t_grant
                             - (handler + dispatch + build))
            wire_raw = max(0.0, dq - p.t_grant)
            reply_raw = max(0.0, recv - rs)
            denom = wire_raw + reply_raw
            wire = (both_wires * wire_raw / denom if denom > 0
                    else both_wires / 2.0)
            per_rank[r] = {
                "wire": wire,
                "dispatch": dispatch,
                "compile": min(cs, handler),
                "execute": max(0.0, handler - cs),
                # The reply stage is handler exit → reply arrival:
                # worker-side build plus the wire back.
                "reply": build + (both_wires - wire),
            }
            if recv_max is None or recv > recv_max:
                recv_max = recv
                crit_rank = r
        if not per_rank:
            with self._lock:
                self.dropped += 1
            return None

        stages = {
            "vet": max(0.0, p.t_submit - p.t_vet),
            "queue": max(0.0, p.t_grant - p.t_submit),
            "deliver": max(0.0, t_deliver - recv_max),
        }
        # Worker-side stages summarize as the CRITICAL-PATH rank's
        # chain — the rank whose reply arrived last, i.e. the one the
        # caller actually waited on.  Mixing per-stage maxima across
        # ranks would over-count (rank A's slow execute plus rank B's
        # slow wire never happened in sequence) and break the
        # stages-sum-to-e2e contract.  Per-rank detail stays in the
        # record for the waterfall.
        stages.update(per_rank[crit_rank])
        e2e = max(0.0, t_deliver - p.t_vet)

        rec = {
            "msg_id": msg_id,
            "type": p.msg_type,
            "tenant": p.tenant,
            "ts": t_deliver,
            "e2e": e2e,
            "stages": stages,
            "ranks": {str(r): {k: round(v, 6) for k, v in d.items()}
                      for r, d in sorted(per_rank.items())},
        }

        reg = self._reg
        for s in STAGES:
            reg.histogram(
                "nbd_stage_seconds",
                "per-cell latency by attribution stage (vet/queue/"
                "wire/dispatch/compile/execute/reply/deliver)",
                {"stage": s},
                buckets=obs_metrics.LATENCY_BUCKETS).observe(stages[s])
        labels = ({"tenant": p.tenant} if p.tenant is not None else None)
        reg.histogram("nbd_cell_e2e_seconds",
                      "end-to-end cell latency (vet start → result "
                      "delivered)", labels,
                      buckets=obs_metrics.LATENCY_BUCKETS).observe(e2e)

        with self._lock:
            self._ring.append(rec)
            self.completed += 1

        if tracer is not None and getattr(tracer, "enabled", False):
            self._mirror_spans(tracer, parent, p, stages, recv_max,
                               t_deliver)
        return rec

    def _mirror_spans(self, tracer, parent, p: _PendingLat,
                      stages: dict, recv_max: float,
                      t_deliver: float) -> None:
        """Stage child spans under the request's send span: the
        Perfetto view of the same waterfall %dist_lat prints."""
        ctx = parent or {}
        t = p.t_vet
        bounds = []
        for s in ("vet", "queue", "wire", "dispatch", "compile",
                  "execute", "reply"):
            bounds.append((s, t, stages[s]))
            t += stages[s]
        bounds.append(("deliver", recv_max, t_deliver - recv_max))
        attrs = {"msg_id": p.msg_id}
        if p.tenant is not None:
            attrs["tenant"] = p.tenant
        for s, t0, dur in bounds:
            if dur <= 0:
                continue
            tracer.add_span(f"stage/{s}", "latency", t0, dur,
                            trace_id=ctx.get("tid"),
                            parent_id=ctx.get("sid"),
                            attrs=attrs)

    # ------------------------------------------------------------------
    # readers

    def records(self, last: int | None = None) -> list[dict]:
        with self._lock:
            recs = list(self._ring)
        return recs[-last:] if last else recs

    def summary(self) -> dict:
        """Percentile table over the ring, in milliseconds:
        ``{"count", "dropped", "e2e_ms": {p50,p95,p99,mean},
        "stages": {stage: {p50,p95,p99,mean,share}}}`` — ``share`` is
        the stage's mean as a fraction of the mean end-to-end."""
        recs = self.records()
        out: dict = {"count": len(recs), "dropped": self.dropped}
        if not recs:
            return out

        def _stats(vals: list[float]) -> dict:
            sv = sorted(vals)
            return {"p50": _ms(percentile(sv, 0.50)),
                    "p95": _ms(percentile(sv, 0.95)),
                    "p99": _ms(percentile(sv, 0.99)),
                    "mean": _ms(sum(sv) / len(sv))}

        e2e = [r["e2e"] for r in recs]
        e2e_mean = sum(e2e) / len(e2e)
        out["e2e_ms"] = _stats(e2e)
        out["stages"] = {}
        for s in STAGES:
            vals = [r["stages"].get(s, 0.0) for r in recs]
            st = _stats(vals)
            st["share"] = (round((sum(vals) / len(vals)) / e2e_mean, 4)
                           if e2e_mean > 0 else 0.0)
            out["stages"][s] = st
        return out

    def status_block(self, *, records: int = 32) -> dict:
        """The pool-status / latency.json payload: summary + the last
        few raw records (JSON-safe)."""
        return {"summary": self.summary(),
                "records": self.records(records)}


# ----------------------------------------------------------------------
# rendering (%dist_lat, shared by single-kernel and tenant mode)


def format_stage_table(summary: dict) -> str:
    """The ``%dist_lat`` per-stage percentile table."""
    n = summary.get("count", 0)
    if not n:
        return ("(no completed cells recorded yet — run a cell, or "
                "check NBD_LAT)")
    lines = [f"⏱ latency observatory · {n} cell(s) recorded"
             + (f" · {summary.get('dropped', 0)} dropped"
                if summary.get("dropped") else "")]
    hdr = (f"{'stage':<10}{'p50 ms':>9}{'p95 ms':>9}{'p99 ms':>9}"
           f"{'mean ms':>9}{'share':>8}")
    lines.append(hdr)
    lines.append("─" * len(hdr))
    for s in STAGES:
        st = (summary.get("stages") or {}).get(s) or {}
        lines.append(f"{s:<10}{st.get('p50', 0):>9}{st.get('p95', 0):>9}"
                     f"{st.get('p99', 0):>9}{st.get('mean', 0):>9}"
                     f"{st.get('share', 0) * 100:>7.1f}%")
    e = summary.get("e2e_ms") or {}
    lines.append(f"{'e2e':<10}{e.get('p50', 0):>9}{e.get('p95', 0):>9}"
                 f"{e.get('p99', 0):>9}{e.get('mean', 0):>9}")
    return "\n".join(lines)


def format_waterfall(records: list[dict], width: int = 44) -> str:
    """ASCII waterfall, one block per record: each stage as an offset
    bar on a shared scale, so WHERE the cell's wall-clock went is
    visible without Perfetto."""
    if not records:
        return "(no records)"
    blocks = []
    for rec in records:
        e2e = rec.get("e2e") or 0.0
        scale = width / e2e if e2e > 0 else 0.0
        who = f" · tenant {rec['tenant']}" if rec.get("tenant") else ""
        blocks.append(f"▼ {rec.get('msg_id', '?')[:12]} "
                      f"{rec.get('type')}{who} · "
                      f"e2e {_ms(e2e)} ms")
        t = 0.0
        stages = rec.get("stages") or {}
        for s in STAGES:
            v = stages.get(s, 0.0)
            pad = int(t * scale)
            bar = max(1, int(v * scale)) if v > 0 else 0
            blocks.append(f"  {s:<10}{_ms(v):>9} ms  "
                          f"{' ' * pad}{'█' * bar}")
            t += v
    return "\n".join(blocks)


# ----------------------------------------------------------------------
# clock-skew surfacing (satellite: the estimator's offsets as gauges +
# the %dist_status warning)


def export_clock_metrics(clock, registry=None) -> None:
    """Mirror the clock estimator's per-rank offset / min-RTT into
    gauges (``nbd_clock_offset_seconds{rank=}`` /
    ``nbd_clock_min_rtt_seconds{rank=}``) — skew silently degrades
    merged traces and stage attribution; this makes it scrapeable."""
    reg = registry or obs_metrics.registry()
    for r, st in clock.stats().items():
        reg.gauge("nbd_clock_offset_seconds",
                  "estimated worker−coordinator clock offset",
                  {"rank": str(r)}).set(st.get("offset_s") or 0.0)
        rtt = st.get("min_rtt_s")
        if rtt is not None:
            reg.gauge("nbd_clock_min_rtt_seconds",
                      "lowest observed request RTT (clock-sample "
                      "quality)", {"rank": str(r)}).set(rtt)


def skew_warnings(clock_stats: dict,
                  threshold_ms: float | None = None) -> list[str]:
    """Human warnings for ranks whose |offset| exceeds the
    ``NBD_LAT_SKEW_WARN_MS`` threshold — rendered by ``%dist_status``."""
    if threshold_ms is None:
        threshold_ms = knobs.get_float("NBD_LAT_SKEW_WARN_MS", 50.0)
    if threshold_ms <= 0:
        return []
    out = []
    for r, st in sorted(clock_stats.items()):
        off_ms = (st.get("offset_s") or 0.0) * 1e3
        if abs(off_ms) > threshold_ms:
            out.append(
                f"⚠ rank {r} clock offset {off_ms:+.1f} ms exceeds "
                f"{threshold_ms:.0f} ms (NBD_LAT_SKEW_WARN_MS) — "
                f"merged traces and stage attribution degrade with "
                f"skew; check host NTP")
    return out
