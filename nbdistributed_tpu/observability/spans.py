"""Lightweight span tracing with cross-process id propagation.

One :class:`Tracer` per process (module singleton, :func:`tracer`),
**disabled by default**: every instrumentation site first checks
``tracer.enabled`` — a single attribute read — so the framework pays
near-zero overhead until ``%dist_trace start`` flips it on.

A span is ``(name, kind, trace_id, span_id, parent_id, t0, dur, tid,
attrs)``.  ``trace_id`` names the tracing *session* (minted by
``Tracer.start`` on the coordinator and adopted by workers from the
wire context), ``span_id`` is unique per span, and ``parent_id`` links
children — either to the thread-local *current* span in this process,
or, for worker handler spans, to the coordinator's send span whose ids
rode the request envelope (the ``tr`` codec header;
see :mod:`nbdistributed_tpu.messaging.codec`).

Timestamps are ``time.time()`` wall clock — deliberately, so the
coordinator can merge per-process dumps onto one timeline after
correcting each rank by its estimated clock offset
(:mod:`~nbdistributed_tpu.observability.clock`).  ``tid`` is a small
per-process thread ordinal so overlapping spans from different threads
(e.g. the magic's send helper vs the cell wrapper) render on separate
tracks instead of producing an invalid stack.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any

# Bound on retained spans: a runaway traced loop must not grow the
# coordinator without limit.  At ~200 bytes/span this is ~10 MB.
MAX_SPANS = 50_000


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    __slots__ = ("name", "kind", "trace_id", "span_id", "parent_id",
                 "t0", "dur", "tid", "attrs")

    def __init__(self, name: str, kind: str, trace_id: str,
                 parent_id: str | None, tid: int,
                 attrs: dict[str, Any] | None = None):
        self.name = name
        self.kind = kind
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.t0 = time.time()
        self.dur = 0.0
        self.tid = tid
        self.attrs = attrs or {}

    def as_dict(self) -> dict:
        d = {"name": self.name, "kind": self.kind, "tid": self.tid,
             "trace_id": self.trace_id, "span_id": self.span_id,
             "t0": self.t0, "dur": self.dur}
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _NullCtx:
    """Shared no-op context manager: the disabled-tracing fast path of
    :func:`maybe_span` must not allocate."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _SpanCtx:
    """Context manager returned by ``Tracer.span``: activates the span
    for the duration (children parent to it) and ends it on exit."""

    __slots__ = ("_tracer", "_span", "_prev")

    def __init__(self, tr: "Tracer", span: "Span"):
        self._tracer = tr
        self._span = span

    def __enter__(self):
        tls = self._tracer._tls
        self._prev = getattr(tls, "current", None)
        tls.current = self._span
        return self._span

    def __exit__(self, exc_type, exc, tb):
        self._tracer._tls.current = self._prev
        if exc_type is not None:
            self._span.attrs.setdefault("error", exc_type.__name__)
        self._tracer.end(self._span)
        return False


class _ActivateCtx:
    """Make an already-open span the thread-local current WITHOUT
    ending it on exit — how a span opened on one thread (the cell
    wrapper) becomes the parent for work on another (the send helper
    thread; thread-locals don't cross threads by themselves)."""

    __slots__ = ("_tracer", "_span", "_prev")

    def __init__(self, tr: "Tracer", span: "Span | None"):
        self._tracer = tr
        self._span = span

    def __enter__(self):
        tls = self._tracer._tls
        self._prev = getattr(tls, "current", None)
        if self._span is not None:
            tls.current = self._span
        return self._span

    def __exit__(self, *exc):
        self._tracer._tls.current = self._prev
        return False


class Tracer:
    """Process-local span recorder.  Thread-safe; all record paths are
    no-ops while ``enabled`` is False."""

    def __init__(self):
        self.enabled = False
        self.trace_id: str | None = None
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self._instants: list[dict] = []
        self._dropped = 0
        self._tls = threading.local()
        self._thread_ids: dict[int, int] = {}

    # ------------------------------------------------------------------
    # lifecycle

    def start(self, trace_id: str | None = None) -> str:
        """Begin a tracing session: clears prior spans, mints (or
        adopts) the session trace id, enables recording."""
        with self._lock:
            self.trace_id = trace_id or _new_id()
            self._spans = []
            self._instants = []
            self._dropped = 0
            self._thread_ids = {}
            self.enabled = True
            return self.trace_id

    def stop(self) -> int:
        """Disable recording; spans stay buffered for ``dump``."""
        self.enabled = False
        with self._lock:
            return len(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans = []
            self._instants = []
            self._dropped = 0

    # ------------------------------------------------------------------
    # recording

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._thread_ids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._thread_ids.setdefault(ident,
                                                  len(self._thread_ids))
        return tid

    def begin(self, name: str, kind: str = "", *,
              trace_id: str | None = None, parent_id: str | None = None,
              attrs: dict | None = None) -> Span | None:
        """Open a span (None when disabled).  With no explicit
        ``parent_id`` the thread-local current span is the parent; an
        explicit one (from a wire context) wins and its ``trace_id``
        should come with it."""
        if not self.enabled:
            return None
        if parent_id is None:
            cur = getattr(self._tls, "current", None)
            if cur is not None:
                parent_id = cur.span_id
                trace_id = trace_id or cur.trace_id
        return Span(name, kind, trace_id or self.trace_id or _new_id(),
                    parent_id, self._tid(), attrs)

    def end(self, span: Span | None) -> None:
        if span is None:
            return
        span.dur = time.time() - span.t0
        with self._lock:
            if len(self._spans) >= MAX_SPANS:
                self._dropped += 1
                return
            self._spans.append(span)

    def span(self, name: str, kind: str = "", *,
             trace_id: str | None = None, parent_id: str | None = None,
             attrs: dict | None = None):
        """``with tracer.span("x") as s:`` — begin + activate + end.
        Returns a no-op context when disabled."""
        sp = self.begin(name, kind, trace_id=trace_id,
                        parent_id=parent_id, attrs=attrs)
        if sp is None:
            return _NULL_CTX
        return _SpanCtx(self, sp)

    def activate(self, span: Span | None):
        """Adopt ``span`` as this thread's current (no end on exit)."""
        if span is None:
            return _NULL_CTX
        return _ActivateCtx(self, span)

    def add_span(self, name: str, kind: str, t0: float, dur: float, *,
                 trace_id: str | None = None,
                 parent_id: str | None = None,
                 attrs: dict | None = None) -> None:
        """Append an already-timed span (explicit ``t0``/``dur``).
        The latency observatory computes its stage decomposition only
        AFTER a request completes, so its ``stage/*`` child spans
        cannot be opened live — they are reconstructed here under the
        request's send span."""
        if not self.enabled:
            return
        sp = Span(name, kind, trace_id or self.trace_id or _new_id(),
                  parent_id, self._tid(), attrs)
        sp.t0 = t0
        sp.dur = max(0.0, dur)
        with self._lock:
            if len(self._spans) >= MAX_SPANS:
                self._dropped += 1
                return
            self._spans.append(sp)

    def instant(self, name: str, kind: str = "",
                attrs: dict | None = None) -> None:
        """Record a zero-duration event (Chrome ``ph: "i"``)."""
        if not self.enabled:
            return
        ev = {"name": name, "kind": kind, "t0": time.time(),
              "tid": self._tid()}
        if attrs:
            ev["attrs"] = attrs
        with self._lock:
            if len(self._instants) < MAX_SPANS:
                self._instants.append(ev)

    # ------------------------------------------------------------------
    # propagation / export

    def context(self) -> dict | None:
        """Wire context for the current span — the value of the codec's
        ``tr`` header — or None when disabled (no header emitted, the
        acceptance bar for zero-overhead-off)."""
        if not self.enabled:
            return None
        cur = getattr(self._tls, "current", None)
        if cur is not None:
            return {"tid": cur.trace_id, "sid": cur.span_id}
        return {"tid": self.trace_id or _new_id()}

    def context_for(self, span: Span | None) -> dict | None:
        if span is None:
            return None
        return {"tid": span.trace_id, "sid": span.span_id}

    def dump(self) -> dict:
        """JSON-able session dump: spans + instants (+ drop count)."""
        with self._lock:
            return {"trace_id": self.trace_id,
                    "spans": [s.as_dict() for s in self._spans],
                    "instants": list(self._instants),
                    "dropped": self._dropped}

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process-global tracer (coordinator and each worker process
    own exactly one)."""
    return _TRACER


def maybe_span(name: str, kind: str = "", attrs: dict | None = None):
    """Module-level ``with maybe_span("collective/all_reduce"):`` for
    instrumentation sites — one flag check, zero allocation when
    tracing is off."""
    t = _TRACER
    if not t.enabled:
        return _NULL_CTX
    return t.span(name, kind, attrs=attrs)
