"""Unified observability: cross-rank tracing + metrics (L2.5, ISSUE 2).

SURVEY §5.5 flags metrics/logging/observability as the reference's
biggest operational gap, and the resilience layer (PR 1) made it acute:
retry, dedup, and supervisor counters exist but are scattered across
ad-hoc ``get_status`` dicts with no history, no cross-rank view, and no
export.  This package is the one coherent place where traces and
metrics from the coordinator and every rank land:

- :mod:`~nbdistributed_tpu.observability.spans` — lightweight span
  tracing.  A process-local :class:`Tracer` (off by default, one
  attribute check when disabled) records named spans with
  ``trace_id``/``span_id``/``parent_id``; the ids propagate across the
  control plane in an optional codec header field (mirroring the
  resilience layer's ``attempt``), so a worker's handler span is a
  *child* of the coordinator's send span in one merged timeline.
- :mod:`~nbdistributed_tpu.observability.clock` — NTP-style per-rank
  clock-offset estimation from request/response RTTs, so merged
  timelines align even though every process stamps its own wall clock.
- :mod:`~nbdistributed_tpu.observability.metrics` — a process-local
  registry of counters / gauges / fixed-bucket histograms (wire
  messages and bytes, retries, dedup hits, cell and collective
  durations, fault injections, supervisor transitions) with JSON and
  Prometheus-text export.
- :mod:`~nbdistributed_tpu.observability.export` — merge coordinator +
  all-rank span dumps into one Chrome-trace-event JSON
  (Perfetto-loadable, ``pid`` = rank) with :class:`FaultPlan` decisions
  folded in as instant events, so chaos runs are visually debuggable.
- :mod:`~nbdistributed_tpu.observability.flightrec` — the ISSUE 3
  layer the above lack: an **always-on, crash-surviving flight
  recorder**.  Every process appends self-delimiting event records to
  an mmap-backed ring file under the shared run directory
  (``NBD_RUN_DIR``); a reader recovers the ring — including a torn
  final record — from the file of a SIGKILLed process.
- :mod:`~nbdistributed_tpu.observability.telemetry` — per-worker
  device telemetry (HBM in-use/peak, live buffers, compile activity)
  sampled off the hot path and piggybacked on heartbeat pings, so the
  coordinator holds a push-based live view that works mid-cell.
- :mod:`~nbdistributed_tpu.observability.postmortem` — assembles the
  flight rings, last telemetry, coordinator spans, and fault events
  into a postmortem bundle (merged Chrome trace + human report) when a
  worker dies.
- :mod:`~nbdistributed_tpu.observability.latency` — the latency
  observatory (ISSUE 13): per-cell eight-stage attribution
  (vet/queue/wire/dispatch/compile/execute/reply/deliver) from
  coordinator + worker stage stamps riding the optional ``lt`` reply
  header, clock-corrected, feeding log-scale histograms, the
  ``%dist_lat`` table/waterfall, and the scrape endpoint.
- :mod:`~nbdistributed_tpu.observability.httpd` — the live scrape
  endpoint: a stdlib ``ThreadingHTTPServer`` serving ``GET /metrics``
  (Prometheus text), ``/healthz``, and ``/latency.json``
  (``NBD_METRICS_PORT``; token-gated on gateway pools).

Surfaced via ``%dist_trace start|stop|save``, ``%dist_metrics``,
``%dist_top``, and ``%dist_postmortem``.  Everything here is
stdlib-only at import time (no JAX import — telemetry touches devices
lazily) so the coordinator side stays light and the modules are
unit-testable without a backend.
"""

from .clock import ClockEstimator
from .flightrec import FlightRecorder, read_ring
from .latency import LatencyObservatory
from .metrics import MetricsRegistry, registry
from .spans import Tracer, maybe_span, tracer
from .telemetry import TelemetrySampler

__all__ = ["ClockEstimator", "FlightRecorder", "LatencyObservatory",
           "MetricsRegistry", "TelemetrySampler", "Tracer",
           "maybe_span", "read_ring", "registry", "tracer"]
