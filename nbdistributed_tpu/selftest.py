"""Runnable end-to-end self-test: ``python -m nbdistributed_tpu.selftest``.

The reference *declared* a console-script integration entry
(``jupyter-dist-test`` → ``nbdistributed.tests.test_integration:main``,
pyproject.toml:50-51) but the module is absent from its snapshot
(SURVEY §4).  This is that artifact, real: bring up a 2-worker CPU/gloo
cluster through the public API, drive the core capabilities, print a
check-by-check report, exit nonzero on any failure.  Useful as a smoke
test of an installation (``nbd-selftest``) without pytest or a notebook.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

from .utils import knobs as _knobs


def main() -> int:
    from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
    from nbdistributed_tpu.messaging import CommunicationManager

    checks: list[tuple[str, bool, str]] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        checks.append((name, ok, detail))
        print(f"  {'✅' if ok else '❌'} {name}"
              + (f" — {detail}" if detail and not ok else ""), flush=True)

    print("nbdistributed_tpu self-test: 2 workers, cpu/gloo backend",
          flush=True)
    comm = CommunicationManager(num_workers=2, timeout=120)
    pm = ProcessManager()
    pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
    try:
        pm.start_workers(2, comm.port, backend="cpu")
        wait_until_ready(comm, pm, 180)
        check("worker bring-up + readiness handshake", True)

        out = {r: m.data.get("output")
               for r, m in comm.send_to_all("execute", "rank * 2").items()}
        check("remote execution with REPL echo", out == {0: "0", 1: "2"},
              repr(out))

        out = {r: m.data.get("output") for r, m in comm.send_to_all(
            "execute", "jax.device_count()").items()}
        check("jax.distributed world formed", out == {0: "2", 1: "2"},
              repr(out))

        out = {r: m.data.get("output") for r, m in comm.send_to_all(
            "execute", "float(all_reduce(jnp.ones(3) * (rank + 1))[0])",
            timeout=180).items()}
        check("cross-process all_reduce", out == {0: "3.0", 1: "3.0"},
              repr(out))

        comm.send_to_all("execute", "st_v = jnp.arange(4.0) + rank")
        with tempfile.TemporaryDirectory() as d:
            r1 = comm.send_to_all(
                "checkpoint", {"action": "save", "path": d,
                               "names": ["st_v"]})
            comm.send_to_all("execute", "st_v = None")
            r2 = comm.send_to_all(
                "checkpoint", {"action": "restore", "path": d,
                               "names": None})
            out = {r: m.data.get("output") for r, m in comm.send_to_all(
                "execute", "float(st_v[0])").items()}
            ok = (all(m.data.get("status") == "save" for m in r1.values())
                  and all(m.data.get("status") == "restore"
                          for m in r2.values())
                  and out == {0: "0.0", 1: "1.0"})
            check("checkpoint save/restore round-trip", ok, repr(out))

        resp = comm.send_to_all("sync", timeout=60)
        check("barrier sync", all(m.data.get("status") == "synced"
                                  for m in resp.values()))

        resp = comm.send_to_all("get_status", timeout=60)
        check("status probe", all("platform" in m.data or "rank" in m.data
                                  for m in resp.values()))

        resp = comm.send_to_all("execute", "1 / 0")
        ok = all("ZeroDivisionError" in (m.data.get("traceback") or "")
                 for m in resp.values())
        out = {r: m.data.get("output") for r, m in comm.send_to_all(
            "execute", "'alive'").items()}
        check("error isolation (workers survive exceptions)",
              ok and out == {0: "'alive'", 1: "'alive'"}, repr(out))

        # Model/kernel stack on rank 0: flash kernel exactness vs the
        # XLA reference (real Mosaic lowering on a TPU install,
        # interpret mode on CPU), then an int8 sampled decode.
        model_cell = """
import jax as _j, jax.numpy as _jn
from nbdistributed_tpu.ops import attention_reference, flash_attention
from nbdistributed_tpu.models import (tiny_config, init_params,
                                      generate, quantize_params)
_ks = _j.random.split(_j.random.PRNGKey(0), 3)
_q = _j.random.normal(_ks[0], (1, 96, 4, 32))
_k = _j.random.normal(_ks[1], (1, 96, 2, 32))
_v = _j.random.normal(_ks[2], (1, 96, 2, 32))
_err = float(_jn.max(_jn.abs(
    flash_attention(_q, _k, _v, True)
    - attention_reference(_q, _k, _v, causal=True))))
_cfg = tiny_config(dtype=_jn.float32, use_flash=False)
_p = quantize_params(init_params(_j.random.PRNGKey(0), _cfg))
_t = generate(_p, _jn.zeros((1, 4), _jn.int32), _cfg, 4,
              temperature=0.8, top_k=8, key=_j.random.PRNGKey(1),
              kv_quantized=True)
(_err < 2e-5, int(_t.shape[1]) == 8, int(_t.max()) < _cfg.vocab_size)
"""
        # Keep this WELL under the 420 s cap tests/integration/
        # test_selftest.py puts on the whole selftest subprocess
        # (bring-up + earlier checks can eat ~100 s on a slow box), so
        # a hung cell fails as a reported check, not a TimeoutExpired.
        r0 = comm.send_to_ranks([0], "execute", model_cell,
                                timeout=120)[0]
        check("model stack (flash kernel exact, int8 sampled decode)",
              r0.data.get("output") == "(True, True, True)",
              repr(r0.data.get("error") or r0.data.get("output")))

        # Round-3 additions: batched speculative decoding, sparse MoE
        # dispatch, and the windowed-ring hop plan.
        r3_cell = """
import jax as _j, jax.numpy as _jn
from nbdistributed_tpu.models import (tiny_config, init_params,
                                      generate, speculative_generate)
_cfg = tiny_config(dtype=_jn.float32, use_flash=False)
_p = init_params(_j.random.PRNGKey(0), _cfg)
_pr = _j.random.randint(_j.random.PRNGKey(1), (2, 5), 0,
                        _cfg.vocab_size)
_sp, _ = speculative_generate(_p, _p, _pr, _cfg, _cfg, 4, gamma=2)
_ok_spec = bool((_sp == generate(_p, _pr, _cfg, 4)).all())
from nbdistributed_tpu.parallel import expert as _ex
_mp = _ex.init_moe_params(_j.random.PRNGKey(2), 16, 32, 4,
                          dtype=_jn.float32)
_x = _j.random.normal(_j.random.PRNGKey(3), (24, 16), _jn.float32)
_yd, _ = _ex.moe_ffn(_x, _mp)
_ys, _ = _ex.moe_ffn(_x, _mp, dispatch_mode="sparse")
_ok_moe = float(_jn.max(_jn.abs(_yd - _ys))) < 1e-5
from nbdistributed_tpu.parallel.ring import hop_plan
_ok_plan = hop_plan(8, 16, 16) == (0, 1)
(_ok_spec, _ok_moe, _ok_plan)
"""
        r0 = comm.send_to_ranks([0], "execute", r3_cell,
                                timeout=120)[0]
        check("batched speculative + sparse MoE + SWA hop plan",
              r0.data.get("output") == "(True, True, True)",
              repr(r0.data.get("error") or r0.data.get("output")))

        # Continuous-batching server: staggered admission into a
        # 2-slot pool must reproduce standalone generate per request.
        serve_cell = """
import jax as _j, jax.numpy as _jn, numpy as _np
from nbdistributed_tpu.models import (DecodeServer, tiny_config,
                                      init_params, generate)
_cfg = tiny_config(dtype=_jn.float32, use_flash=False)
_p = init_params(_j.random.PRNGKey(0), _cfg)
_srv = DecodeServer(_p, _cfg, max_batch=2, max_len=32, pad_to=4)
_r0 = _srv.submit([5, 9, 2], 4)
_srv.step()
_r1 = _srv.submit([7, 1], 3)
_srv.run_until_done(max_steps=50)
def _solo(pr, n):
    o = generate(_p, _jn.asarray(pr, _jn.int32)[None], _cfg, n)
    return [int(t) for t in _np.asarray(o)[0][len(pr):]]
_dr = init_params(_j.random.PRNGKey(9), _cfg)
_ssrv = DecodeServer(_p, _cfg, max_batch=2, max_len=32, pad_to=4,
                     draft_params=_dr, draft_cfg=_cfg, gamma=2)
_r2 = _ssrv.submit([5, 9, 2], 4)
_ssrv.run_until_done(max_steps=20)
(_srv.outputs[_r0] == _solo([5, 9, 2], 4),
 _srv.outputs[_r1] == _solo([7, 1], 3),
 _ssrv.outputs[_r2] == _solo([5, 9, 2], 4))
"""
        r0 = comm.send_to_ranks([0], "execute", serve_cell,
                                timeout=180)[0]
        check("continuous-batching server (staggered + speculative "
              "== solo)",
              r0.data.get("output") == "(True, True, True)",
              repr(r0.data.get("error") or r0.data.get("output")))

        # Fault-injection smoke (gated: NBD_SELFTEST_FAULTS=1).
        # Duplicate-heavy plans on BOTH control-plane directions: the
        # worker replay cache must absorb every redelivered frame so a
        # 10-increment counter lands on exactly 10 per rank.
        # (Duplicate-only because this manager has no retry policy —
        # dropped frames would surface as request timeouts, which the
        # chaos integration test covers with retries enabled.)
        if _knobs.get_raw("NBD_SELFTEST_FAULTS"):
            from nbdistributed_tpu.resilience import FaultPlan
            comm.send_to_all(
                "chaos", {"action": "set",
                          "spec": {"seed": 7, "duplicate": 0.5}},
                timeout=60)
            comm.set_fault_plan(FaultPlan(seed=8, duplicate=0.5))
            comm.send_to_all("execute", "_ft_n = 0", timeout=60)
            for _ in range(10):
                comm.send_to_all("execute", "_ft_n += 1", timeout=60)
            out = {r: m.data.get("output") for r, m in
                   comm.send_to_all("execute", "_ft_n",
                                    timeout=60).items()}
            st = comm.send_to_all("get_status", timeout=60)
            dedup = sum(m.data.get("dedup_hits", 0)
                        for m in st.values())
            comm.set_fault_plan(None)
            comm.send_to_all("chaos", {"action": "clear"}, timeout=60)
            check("fault-injection smoke (duplicates absorbed, "
                  "exactly-once execute)",
                  out == {0: "10", 1: "10"},
                  f"{out} dedup_hits={dedup}")

        # Observability smoke (gated: NBD_SELFTEST_OBS=1): trace a
        # 2-rank cell end-to-end and assert the merged Chrome-trace
        # export carries spans from the coordinator AND every rank,
        # stitched under one trace id.
        if _knobs.get_raw("NBD_SELFTEST_OBS"):
            from nbdistributed_tpu.observability import export as _obs_exp
            comm.send_to_all("trace", {"action": "start",
                                       "trace_id": "selftest0trace00"},
                             timeout=60)
            comm.tracer.start(trace_id="selftest0trace00")
            comm.send_to_all(
                "execute", "float(all_reduce(jnp.ones(2))[0])",
                timeout=180)
            comm.tracer.stop()
            dumps = comm.send_to_all("trace", {"action": "dump"},
                                     timeout=60)
            comm.send_to_all("trace", {"action": "stop"}, timeout=60)
            merged = _obs_exp.merge_trace(
                comm.tracer.dump(),
                {r: m.data.get("trace") or {} for r, m in dumps.items()},
                comm.clock.offsets())
            spans = [e for e in merged["traceEvents"]
                     if e.get("ph") == "X"]
            pids = {e["pid"] for e in spans}
            names = {e["name"] for e in spans}
            check("observability (2-rank traced cell, merged export)",
                  {-1, 0, 1} <= pids and "handle/execute" in names
                  and any(n.startswith("send/") for n in names),
                  f"pids={sorted(pids)} names={sorted(names)[:8]}")
            m0 = comm.send_to_ranks([0], "metrics", {}, timeout=60)[0]
            mj = m0.data.get("metrics", {})
            check("observability (rank metrics registry exports)",
                  any(k.startswith("nbd_wire_messages_total")
                      for k in mj.get("counters", {})),
                  repr(sorted(mj.get("counters", {}))[:6]))

            # Postmortem sub-check (ISSUE 3): every process has been
            # flight-recording since bring-up — recover the rings from
            # the run dir, assemble a bundle, and assert the merged
            # trace carries recovered events for the coordinator and
            # both ranks (no one had to die for this to work).
            from nbdistributed_tpu.observability import flightrec
            from nbdistributed_tpu.observability import \
                postmortem as _obs_pm
            manifest = _obs_pm.capture(comm, [],
                                       reason="selftest sub-check")
            ok, detail = False, "capture returned None"
            if manifest is not None:
                import json as _json
                with open(os.path.join(manifest["dir"],
                                       "trace.json")) as f:
                    tr = _json.load(f)
                flight = [e for e in tr["traceEvents"]
                          if e.get("cat") == "flight"]
                pids = {e["pid"] for e in flight}
                rings = flightrec.find_rings(
                    _knobs.get_str("NBD_RUN_DIR", ""))
                ok = {-1, 0, 1} <= pids and len(rings) >= 3
                detail = (f"flight pids={sorted(pids)} "
                          f"rings={len(rings)} dir={manifest['dir']}")
            check("observability (flight rings recovered into "
                  "postmortem bundle)", ok, detail)
    except Exception as e:
        check("harness", False, f"{type(e).__name__}: {e}")
    finally:
        try:
            comm.post([0, 1], "shutdown")
            time.sleep(0.3)
        except Exception:
            pass
        pm.shutdown()
        comm.shutdown()

    # Serving smoke (gated: NBD_SELFTEST_SERVE=1): a 2-rank gateway
    # pool serving 3 requests through %dist_serve's wire surface, with
    # one injected rank SIGKILL mid-decode — every accepted request
    # must complete with its exact solo-generate greedy tokens after
    # the journal-replay failover, with zero duplicated emissions.
    # Runs AFTER the main fleet is down (its own pool, its own ports).
    if _knobs.get_raw("NBD_SELFTEST_SERVE"):
        _serve_smoke(check)

    failed = [c for c in checks if not c[1]]
    print(f"\n{len(checks) - len(failed)}/{len(checks)} checks passed",
          flush=True)
    return 1 if failed else 0


def _serve_smoke(check) -> None:
    import ast as _ast

    from nbdistributed_tpu.gateway.client import TenantClient
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon
    from nbdistributed_tpu.gateway.scheduler import SchedPolicy

    spec = (
        "import jax as _j, jax.numpy as _jn\n"
        "from nbdistributed_tpu.models import tiny_config, init_params\n"
        "cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
        "params = init_params(_j.random.PRNGKey(0), cfg)\n")
    ref_cell = (
        "import jax as _j, jax.numpy as _jn, numpy as _np\n"
        "from nbdistributed_tpu.models import (tiny_config, "
        "init_params, generate)\n"
        "_cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
        "_p = init_params(_j.random.PRNGKey(0), _cfg)\n"
        "_prompts = [[5, 9, 2], [7, 1], [3, 4, 8, 1]]\n"
        "[[int(t) for t in _np.asarray(generate(_p, _jn.asarray(pr, "
        "_jn.int32)[None], _cfg, 6))[0][len(pr):]] for pr in _prompts]")
    gw = client = None
    try:
        gw = GatewayDaemon(
            2, backend="cpu",
            policy=SchedPolicy("fair", mesh_slots=1,
                               tenant_inflight=8, queue_depth=16),
            request_timeout=None, attach_timeout=240.0,
            watchdog=False)
        client = TenantClient(gw.tenant_host, gw.tenant_port, "st",
                              pool_token=gw.pool_token)
        out = client.execute(ref_cell, timeout=240)
        solo = _ast.literal_eval(
            (out.get("results") or {}).get("0", {}).get("output"))
        # Arm the mid-decode SIGKILL on the decode rank (the highest
        # live rank, 1) BEFORE serving starts: spec execute +
        # serve_open + ticks count toward kill_at, so it dies inside
        # the decode loop.
        gw.comm.send_to_ranks([1], "chaos", {
            "action": "set",
            "spec": {"seed": 3, "kill_rank": 1, "kill_at": 4}},
            timeout=60)
        client.serve_start(spec, max_batch=2, max_len=32, pad_to=4,
                           steps=2, timeout=300)
        prompts = [[5, 9, 2], [7, 1], [3, 4, 8, 1]]
        rids = [client.serve_submit(pr, 6)["rid"] for pr in prompts]
        got: dict[str, list] = {}
        deadline = time.time() + 240
        while len(got) < len(rids) and time.time() < deadline:
            for rid in rids:
                if rid in got:
                    continue
                r = client.serve_result(rid)
                if r.get("done"):
                    got[rid] = (r.get("status"), r.get("tokens"))
            time.sleep(0.3)
        st = client.serve_status()
        ok = (len(got) == len(rids)
              and all(got[rid] == ("completed", solo[i])
                      for i, rid in enumerate(rids))
              and st.get("failovers", 0) >= 1
              and st.get("dup_dropped", 0) == 0)
        check("serving smoke (rank SIGKILL mid-decode; journal "
              "replay; exact greedy streams)", ok,
              f"got={got} solo={solo} failovers="
              f"{st.get('failovers')} replayed={st.get('replayed')} "
              f"dup={st.get('dup_dropped')}")
    except Exception as e:
        check("serving smoke harness", False,
              f"{type(e).__name__}: {e}")
    finally:
        try:
            if client is not None:
                client.close()
        except Exception:
            pass
        if gw is not None:
            gw.close()


if __name__ == "__main__":
    sys.exit(main())
