"""nbdistributed_tpu — interactive distributed JAX on TPU from a notebook.

TPU-native rebuild of the capabilities of ``nbdistributed`` (reference:
__init__.py, magic.py, communication.py, process_manager.py, worker.py):
one notebook kernel coordinates N JAX worker processes (one per TPU chip
or host); every cell executes remotely on all or selected ranks with REPL
semantics — streamed per-rank stdout, last-expression echo, persistent
namespaces — while collectives are XLA programs over ICI/DCN instead of
NCCL/Gloo.

Usage in a notebook::

    %load_ext nbdistributed_tpu
    %dist_init -n 8
    # every subsequent cell runs on all 8 workers
"""

__version__ = "0.1.0"


def load_ipython_extension(ipython):
    """``%load_ext nbdistributed_tpu`` hook (reference: __init__.py:7-18)."""
    from .magics.magic import DistributedMagics

    DistributedMagics.reset_class_state()
    magics = DistributedMagics(ipython)
    ipython.register_magics(magics)
    magics.on_extension_loaded()


def unload_ipython_extension(ipython):
    """``%unload_ext`` hook — tears down any running cluster
    (reference: __init__.py:21-25)."""
    from .magics.magic import DistributedMagics

    DistributedMagics.unregister_cell_hooks()
    DistributedMagics.shutdown_all()
