"""Jupyter-server integration: persist execution timelines INTO the
notebook file at save time.

The reference injects browser JavaScript that writes
``Jupyter.notebook.metadata.execution_timelines`` (reference:
magic.py:196-233) — a mechanism that silently no-ops everywhere except
the classic Notebook front-end.  The frontend-agnostic equivalent is a
server-side ``pre_save_hook``: the kernel flushes the timeline to a
sidecar JSON next to the notebook (``%timeline_sidecar``), and this
hook folds the sidecar into the notebook's metadata whenever the file
is saved — so the record travels inside the ``.ipynb`` again, for any
front-end (Lab, VS Code, classic), without trusting injected JS.

Enable in ``jupyter_server_config.py``::

    from nbdistributed_tpu.jupyter_hooks import pre_save_hook
    c.FileContentsManager.pre_save_hook = pre_save_hook

Then in the notebook::

    %timeline_sidecar on          # auto-flush after every cell
    # ... work ...                # each save embeds the latest record

The hook is deliberately fail-open: a missing, malformed, or
unreadable sidecar must never break saving a notebook.
"""

from __future__ import annotations

import json
import os

SIDECAR_SUFFIX = ".nbd_timeline.json"

# Notebook metadata key — same name the reference's JS used, so tools
# reading either format find the record in the same place.
METADATA_KEY = "execution_timelines"


def sidecar_path(notebook_path: str) -> str:
    """``x.ipynb`` -> ``x.ipynb.nbd_timeline.json`` (next to it)."""
    return str(notebook_path) + SIDECAR_SUFFIX


def pre_save_hook(model=None, path: str = "", contents_manager=None,
                  **kwargs) -> None:
    """``FileContentsManager.pre_save_hook`` — folds the kernel-written
    timeline sidecar into ``metadata.execution_timelines`` of the
    notebook being saved.  No sidecar, wrong model type, or any error:
    the save proceeds untouched."""
    try:
        if not isinstance(model, dict) or model.get("type") != "notebook":
            return
        content = model.get("content")
        if not isinstance(content, dict):
            return
        os_path = path
        if contents_manager is not None:
            getter = getattr(contents_manager, "_get_os_path", None)
            if getter is not None:
                os_path = getter(path)
        sc = sidecar_path(os_path)
        if not os.path.exists(sc):
            return
        with open(sc) as f:
            payload = json.load(f)
        if not isinstance(payload, dict) or "records" not in payload:
            return
        content.setdefault("metadata", {})[METADATA_KEY] = payload
    except Exception:
        # Fail-open: persisting a convenience record must never block
        # saving the user's notebook.
        return
