"""Rank-spec grammar for ``%%rank`` targeting.

Same surface grammar as the reference (reference: magic.py:1679-1715):
``[0,2]`` picks ranks, ``[0-2]`` is an inclusive range, and the two mix
(``[0, 2-4, 7]``).  Out-of-range ranks are *reported* — the reference
silently filtered them (reference: magic.py:1697-1715), which turns a
typo'd rank list into a silent no-op on those ranks.
"""

from __future__ import annotations

import re

_SPEC_RE = re.compile(r"^\s*\[([^\]]*)\]\s*$")


class RankSpecError(ValueError):
    pass


def parse_ranks(spec: str, world_size: int) -> list[int]:
    """Parse ``[0,1]`` / ``[0-2]`` / mixed specs into a sorted list of
    unique valid ranks.  Raises :class:`RankSpecError` on malformed specs
    or ranks outside ``[0, world_size)``."""
    m = _SPEC_RE.match(spec)
    if not m:
        raise RankSpecError(
            f"invalid rank spec {spec!r}: expected e.g. [0,1] or [0-2]")
    body = m.group(1).strip()
    if not body:
        raise RankSpecError("empty rank spec []")
    ranks: set[int] = set()
    for part in body.split(","):
        part = part.strip()
        rm = re.fullmatch(r"(\d+)\s*-\s*(\d+)", part)
        if rm:
            lo, hi = int(rm.group(1)), int(rm.group(2))
            if lo > hi:
                raise RankSpecError(f"descending range {part!r}")
            ranks.update(range(lo, hi + 1))
        elif re.fullmatch(r"\d+", part):
            ranks.add(int(part))
        else:
            raise RankSpecError(f"invalid rank spec element {part!r}")
    bad = sorted(r for r in ranks if r >= world_size)
    if bad:
        raise RankSpecError(
            f"ranks {bad} out of range for world size {world_size}")
    return sorted(ranks)
