"""Incremental rank-grouped streaming display.

Rebuilds the reference's streaming print pipeline (reference:
magic.py:538-607 callback+filters, magic.py:1088-1097 poll loop): the
control plane's IO thread feeds per-rank buffers; the cell's main thread
drains them periodically, printing ``🔹 Rank N:`` sections as output
arrives.  Draining from the main thread keeps output attached to the
right notebook cell — IPython display routing is thread-affine.
"""

from __future__ import annotations

import threading
from typing import Callable

# Noise lines some frontends inject; the reference filters similarly
# (reference: magic.py:558-573).
_NOISE_SNIPPETS = (
    "<IPython.core.display.Javascript object>",
    "window.require",
)


class StreamDisplay:
    """Per-cell collector of streamed worker output with incremental,
    rank-grouped printing."""

    def __init__(self, print_fn: Callable[[str], None] | None = None):
        self._lock = threading.Lock()
        self._chunks: list[tuple[int, str, str]] = []  # (rank, text, kind)
        self._drained = 0
        self._last_rank: int | None = None
        self._at_line_start = True
        self._print = print_fn or (lambda s: print(s, end=""))

    # -- feed side (IO thread) ----------------------------------------

    def feed(self, rank: int, data: dict) -> None:
        text = data.get("text", "")
        if not text.strip():
            return
        if any(s in text for s in _NOISE_SNIPPETS):
            return
        with self._lock:
            self._chunks.append((rank, text, data.get("stream", "stdout")))

    # -- drain side (main thread) -------------------------------------

    def drain(self) -> bool:
        """Print everything new; returns True if anything was printed."""
        with self._lock:
            new = self._chunks[self._drained:]
            self._drained = len(self._chunks)
        for rank, text, _kind in new:
            if rank != self._last_rank:
                if not self._at_line_start:
                    self._print("\n")
                self._print(f"🔹 Rank {rank}:\n")
                self._last_rank = rank
            # Text passes through verbatim — partial lines (progress
            # bars, \r rewrites) must not be force-terminated.
            self._print(text)
            self._at_line_start = text.endswith(("\n", "\r"))
        return bool(new)

    def finalize(self) -> None:
        """Terminate a trailing partial line at cell end."""
        if not self._at_line_start:
            self._print("\n")
            self._at_line_start = True

    def error_chunks(self) -> list[tuple[int, str]]:
        with self._lock:
            return [(r, t) for r, t, k in self._chunks if k == "stderr"]


def print_rank_errors(responses: dict, print_fn=None) -> int:
    """Print per-rank error reports after a distributed cell; stdout has
    already streamed, so only failures need echoing (reference:
    magic.py:1100-1115).  Returns the number of failed ranks."""
    p = print_fn or (lambda s: print(s, end=""))
    failures = 0
    for rank in sorted(responses):
        data = responses[rank].data
        if isinstance(data, dict) and data.get("error"):
            failures += 1
            p(f"❌ Rank {rank}: {data['error']}\n")
            tb = data.get("traceback")
            if tb:
                p(tb if tb.endswith("\n") else tb + "\n")
    return failures
