"""User/API layer (L4, SURVEY §1): IPython magics, auto-dispatch input
transformer, streaming display, IDE proxies, measured timelines."""
