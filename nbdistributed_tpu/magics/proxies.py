"""IDE namespace proxies: mirror worker names into the kernel namespace.

After distributed cells, the kernel's ``user_ns`` gets lightweight
stand-ins for rank 0's names so editor autocomplete / type hints work
(reference: magic.py:1131-1314).  JAX-native redesign:

* arrays    -> ``jax.ShapeDtypeStruct`` — honest shape/dtype carriers
               that cost nothing (the reference allocated real
               ``torch.zeros``, magic.py:1186-1199);
* callables -> closure-built stubs carrying the remote signature in
               their docstring and raising on call — the reference
               ``exec``-ed generated source in the kernel
               (magic.py:1262-1286), a scar SURVEY §7 says to avoid;
* modules   -> real import when available, else a placeholder module;
* scalars   -> literal values reconstructed from their repr;
* classes   -> empty dynamic types.

Every proxy is tagged via ``__nbd_proxy__`` so re-syncs can tell proxies
from user-assigned kernel variables and never clobber the latter.
"""

from __future__ import annotations

import ast
import importlib
import types
from typing import Any

PROXY_TAG = "__nbd_proxy__"

# Names seeded by the worker runtime itself (runtime/worker.py
# _seed_namespace); mirroring them into the kernel would shadow the
# coordinator's own meaning of ``jax`` or leave stale ``rank``/``dist``
# values behind after shutdown.  Only *user-created* names get proxies.
_SKIP_NAMES = {"jax", "jnp", "np", "Mesh", "NamedSharding", "P",
               "PartitionSpec", "shard_map", "__builtins__",
               "rank", "world_size", "process_index", "devices",
               "local_devices", "device", "dist", "all_reduce",
               "all_gather", "broadcast", "barrier", "reduce_scatter",
               "all_reduce_quantized"}


def make_proxy(name: str, desc: dict) -> tuple[Any, bool]:
    """Build a proxy object for one namespace descriptor (from
    ``introspect.describe_namespace``).  Returns (proxy, ok)."""
    kind = desc.get("kind")
    try:
        if kind == "array":
            import jax
            import numpy as np
            proxy = jax.ShapeDtypeStruct(
                tuple(desc["shape"]), np.dtype(_canonical(desc["dtype"])))
            return proxy, True
        if kind == "scalar":
            return ast.literal_eval(desc["repr"]), True
        if kind == "module":
            try:
                return importlib.import_module(desc["name"]), True
            except ImportError:
                mod = types.ModuleType(desc["name"])
                mod.__doc__ = f"placeholder for remote module {desc['name']}"
                setattr(mod, PROXY_TAG, True)
                return mod, True
        if kind == "callable":
            return _callable_stub(name, desc), True
        if kind == "class":
            cls = type(desc["name"], (), {
                "__module__": desc.get("module", "remote"),
                PROXY_TAG: True,
                "__doc__": f"proxy for remote class {desc['name']}"})
            return cls, True
        if kind in ("container", "object", "mesh", "pspec"):
            return _ObjectProxy(name, desc), True
    except Exception:
        pass
    return None, False


def _canonical(dtype: str) -> str:
    # bfloat16 has no numpy name; fall back to float32 for the proxy.
    return "float32" if dtype == "bfloat16" else dtype


def _callable_stub(name: str, desc: dict):
    signature = desc.get("signature", "(...)")
    doc = desc.get("doc", "")

    def stub(*_args, **_kwargs):
        raise RuntimeError(
            f"{name}{signature} exists on the workers, not in the kernel. "
            f"Run it in a distributed cell.")

    stub.__name__ = name
    stub.__qualname__ = name
    stub.__doc__ = (f"[remote] {name}{signature}\n\n{doc}" if doc
                    else f"[remote] {name}{signature}")
    setattr(stub, PROXY_TAG, True)
    return stub


class _ObjectProxy:
    """Repr-carrying stand-in for remote objects/containers."""

    def __init__(self, name: str, desc: dict):
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_desc", dict(desc))
        object.__setattr__(self, PROXY_TAG, True)

    def __repr__(self):
        d = self._desc
        if d["kind"] == "container":
            return (f"<remote {d.get('type', 'container')} "
                    f"len={d.get('len', '?')} on workers>")
        return d.get("repr") or f"<remote {d.get('type', 'object')}>"


_MISSING = object()


def sync_namespace(user_ns: dict, namespace_info: dict[str, dict],
                   registry: dict[str, Any]) -> int:
    """Install proxies for worker names into ``user_ns``.

    Mirrors rank 0's view (reference pulls rank 0 only: magic.py:1144-1152).
    ``registry`` records exactly which objects this module installed
    (name -> proxy), so ownership is tracked by identity rather than by
    sniffing types: a kernel variable the user assigned — even one that
    happens to be a ``jax.ShapeDtypeStruct`` — is never touched, and a
    user overwriting a proxy permanently reclaims the name.  Proxies
    whose remote name vanished are removed.  Returns the number of names
    synced.

    Known edge: interned scalars (small ints, short strings) can make a
    user's value identical-by-identity to an installed proxy value; such
    a name keeps refreshing from the workers.
    """
    synced = 0
    for name, desc in namespace_info.items():
        if name in _SKIP_NAMES or name.startswith("_"):
            continue
        existing = user_ns.get(name, _MISSING)
        if existing is not _MISSING:
            owned = name in registry and registry[name] is existing
            if not owned:
                registry.pop(name, None)  # the user holds this name now
                continue
        proxy, ok = make_proxy(name, desc)
        if ok:
            user_ns[name] = proxy
            registry[name] = proxy
            synced += 1
    for stale in list(registry):
        if stale not in namespace_info:
            if user_ns.get(stale, _MISSING) is registry[stale]:
                user_ns.pop(stale, None)
            del registry[stale]
    return synced


def remove_proxies(user_ns: dict, registry: dict[str, Any]) -> None:
    """Drop every still-owned proxy (used at cluster shutdown so raising
    stubs and stale mirrors don't outlive the workers)."""
    for name, proxy in list(registry.items()):
        if user_ns.get(name, _MISSING) is proxy:
            user_ns.pop(name, None)
    registry.clear()
