"""IDE namespace proxies: mirror worker names into the kernel namespace.

After distributed cells, the kernel's ``user_ns`` gets lightweight
stand-ins for rank 0's names so editor autocomplete / type hints work
(reference: magic.py:1131-1314).  JAX-native redesign:

* arrays    -> ``jax.ShapeDtypeStruct`` — honest shape/dtype carriers
               that cost nothing (the reference allocated real
               ``torch.zeros``, magic.py:1186-1199);
* callables -> closure-built stubs carrying the remote signature in
               their docstring and raising on call — the reference
               ``exec``-ed generated source in the kernel
               (magic.py:1262-1286), a scar SURVEY §7 says to avoid;
* modules   -> real import when available, else a placeholder module;
* scalars   -> literal values reconstructed from their repr;
* classes   -> empty dynamic types.

Every proxy is tagged via ``__nbd_proxy__`` so re-syncs can tell proxies
from user-assigned kernel variables and never clobber the latter.
"""

from __future__ import annotations

import ast
import importlib
import threading
import types
from typing import Any

PROXY_TAG = "__nbd_proxy__"

# Names seeded by the worker runtime itself (runtime/worker.py
# _seed_namespace); mirroring them into the kernel would shadow the
# coordinator's own meaning of ``jax`` or leave stale ``rank``/``dist``
# values behind after shutdown.  Only *user-created* names get proxies.
_SKIP_NAMES = {"jax", "jnp", "np", "Mesh", "NamedSharding", "P",
               "PartitionSpec", "shard_map", "__builtins__",
               "rank", "world_size", "process_index", "devices",
               "local_devices", "device", "dist", "all_reduce",
               "all_gather", "broadcast", "barrier", "reduce_scatter",
               "all_reduce_quantized"}


def make_proxy(name: str, desc: dict) -> tuple[Any, bool]:
    """Build a proxy object for one namespace descriptor (from
    ``introspect.describe_namespace``).  Returns (proxy, ok)."""
    kind = desc.get("kind")
    try:
        if kind == "array":
            import jax
            import numpy as np
            proxy = jax.ShapeDtypeStruct(
                tuple(desc["shape"]), np.dtype(_canonical(desc["dtype"])))
            return proxy, True
        if kind == "scalar":
            return ast.literal_eval(desc["repr"]), True
        if kind == "module":
            try:
                return importlib.import_module(desc["name"]), True
            except ImportError:
                mod = types.ModuleType(desc["name"])
                mod.__doc__ = f"placeholder for remote module {desc['name']}"
                setattr(mod, PROXY_TAG, True)
                return mod, True
        if kind == "callable":
            return _callable_stub(name, desc), True
        if kind == "class":
            cls = type(desc["name"], (), {
                "__module__": desc.get("module", "remote"),
                PROXY_TAG: True,
                "__doc__": f"proxy for remote class {desc['name']}"})
            return cls, True
        if kind in ("container", "object", "mesh", "pspec"):
            return _ObjectProxy(name, desc), True
    except Exception:
        pass
    return None, False


def _canonical(dtype: str) -> str:
    # bfloat16 has no numpy name; fall back to float32 for the proxy.
    return "float32" if dtype == "bfloat16" else dtype


def _callable_stub(name: str, desc: dict):
    signature = desc.get("signature", "(...)")
    doc = desc.get("doc", "")

    def stub(*_args, **_kwargs):
        raise RuntimeError(
            f"{name}{signature} exists on the workers, not in the kernel. "
            f"Run it in a distributed cell.")

    stub.__name__ = name
    stub.__qualname__ = name
    stub.__doc__ = (f"[remote] {name}{signature}\n\n{doc}" if doc
                    else f"[remote] {name}{signature}")
    setattr(stub, PROXY_TAG, True)
    return stub


class _ObjectProxy:
    """Repr-carrying stand-in for remote objects/containers."""

    def __init__(self, name: str, desc: dict):
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_desc", dict(desc))
        object.__setattr__(self, PROXY_TAG, True)

    def __repr__(self):
        d = self._desc
        if d["kind"] == "container":
            return (f"<remote {d.get('type', 'container')} "
                    f"len={d.get('len', '?')} on workers>")
        return d.get("repr") or f"<remote {d.get('type', 'object')}>"


class CellFuture:
    """The notebook-side handle of one async ``%%distributed`` cell
    (ISSUE 14): the cell magic returns this immediately — IPython's
    display hook echoes it as a pending handle — and the async
    executor resolves it when the workers' replies land.

    Consumption contract (matches the background-checkpoint handle's
    first-done-poll discipline in magic.py, made explicit here):

    * ``resolve``/``reject`` are **idempotent** — the first terminal
      transition wins, later calls return ``False`` and change
      nothing (a late redelivered reply can never flip an outcome);
    * an **errored** future surfaces its error on first *touch*
      (``result()``/``raise_if_error()``) **or at the next sync
      point** (``%dist_wait`` / a synchronous cell draining the
      window) — and if nothing ever touches it, the magic layer warns
      at the next cell instead of letting the error vanish;
    * reading the outcome marks the future **consumed**, so the warn
      pass never nags about an error the user already saw.
    """

    PENDING, DONE, ERROR = "pending", "done", "error"

    def __init__(self, code: str, seq: int, ranks: list[int]):
        self.code = code
        self.seq = seq
        self.ranks = list(ranks)
        self.state = self.PENDING
        self.results: dict | None = None   # rank -> reply data dict
        self.error: Exception | None = None
        self.consumed = False
        self.warned = False
        self.msg_id: str | None = None
        self._event = threading.Event()
        setattr(self, PROXY_TAG, True)

    # -- terminal transitions (idempotent, first one wins) -------------

    def resolve(self, results: dict) -> bool:
        if self.state != self.PENDING:
            return False
        self.results = dict(results or {})
        # Per-rank errors are errors: they must not slide by as a
        # quiet success just because the transport succeeded.
        rank_errors = {r: d.get("error")
                       for r, d in self.results.items()
                       if isinstance(d, dict) and d.get("error")}
        if rank_errors:
            self.state = self.ERROR
            lines = "; ".join(f"rank {r}: {e}"
                              for r, e in sorted(rank_errors.items()))
            self.error = RuntimeError(
                f"async cell #{self.seq} errored — {lines}")
        else:
            self.state = self.DONE
        self._event.set()
        return True

    def reject(self, exc: Exception) -> bool:
        if self.state != self.PENDING:
            return False
        self.error = exc
        self.state = self.ERROR
        self._event.set()
        return True

    # -- consumption ----------------------------------------------------

    @property
    def done(self) -> bool:
        return self.state != self.PENDING

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: float | None = None) -> dict:
        """Block until resolved; raise the cell's error on first
        touch; return ``{rank: reply_data}`` otherwise."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"async cell #{self.seq} still in flight after "
                f"{timeout}s — %dist_wait drains the window")
        self.consumed = True
        if self.error is not None:
            raise self.error
        return self.results or {}

    def raise_if_error(self) -> None:
        """The sync-point touch: consumes and re-raises an error,
        no-op while pending or on success."""
        if self.state == self.ERROR:
            self.consumed = True
            raise self.error

    def __repr__(self) -> str:
        if self.state == self.PENDING:
            return (f"⧗ async cell #{self.seq} in flight on ranks "
                    f"{self.ranks} — %dist_wait to drain, "
                    f".result() to block")
        if self.state == self.ERROR:
            self.consumed = True
            return f"✗ async cell #{self.seq}: {self.error}"
        outs = {r: (d or {}).get("output", "")
                for r, d in sorted((self.results or {}).items())}
        first = next(iter(outs.values()), "")
        tail = first.strip().splitlines()[-1] if first.strip() else ""
        return (f"✓ async cell #{self.seq} · {len(outs)} ranks"
                + (f" · {tail[:60]}" if tail else ""))


_MISSING = object()


def sync_namespace(user_ns: dict, namespace_info: dict[str, dict],
                   registry: dict[str, Any]) -> int:
    """Install proxies for worker names into ``user_ns``.

    Mirrors rank 0's view (reference pulls rank 0 only: magic.py:1144-1152).
    ``registry`` records exactly which objects this module installed
    (name -> proxy), so ownership is tracked by identity rather than by
    sniffing types: a kernel variable the user assigned — even one that
    happens to be a ``jax.ShapeDtypeStruct`` — is never touched, and a
    user overwriting a proxy permanently reclaims the name.  Proxies
    whose remote name vanished are removed.  Returns the number of names
    synced.

    Known edge: interned scalars (small ints, short strings) can make a
    user's value identical-by-identity to an installed proxy value; such
    a name keeps refreshing from the workers.
    """
    synced = 0
    for name, desc in namespace_info.items():
        if name in _SKIP_NAMES or name.startswith("_"):
            continue
        existing = user_ns.get(name, _MISSING)
        if existing is not _MISSING:
            owned = name in registry and registry[name] is existing
            if not owned:
                registry.pop(name, None)  # the user holds this name now
                continue
        proxy, ok = make_proxy(name, desc)
        if ok:
            user_ns[name] = proxy
            registry[name] = proxy
            synced += 1
    for stale in list(registry):
        if stale not in namespace_info:
            if user_ns.get(stale, _MISSING) is registry[stale]:
                user_ns.pop(stale, None)
            del registry[stale]
    return synced


def remove_proxies(user_ns: dict, registry: dict[str, Any]) -> None:
    """Drop every still-owned proxy (used at cluster shutdown so raising
    stubs and stale mirrors don't outlive the workers)."""
    for name, proxy in list(registry.items()):
        if user_ns.get(name, _MISSING) is proxy:
            user_ns.pop(name, None)
    registry.clear()
