"""IPython magics: the user/API layer (L4, SURVEY §1).

Rebuilds the reference's magic surface with the same names and semantics
(reference: magic.py:71-83 lists them): ``%dist_init``, ``%%distributed``,
``%%rank``, ``%sync``, ``%dist_status``, ``%dist_mode``,
``%dist_shutdown``, ``%dist_reset``, ``%dist_debug``, ``%dist_sync_ide``,
``%timeline_*``, plus the auto-distributed input transformer that makes
plain cells run on all workers (reference: magic.py:609-645).

TPU-era additions beyond parity: ``%dist_profile`` (jax.profiler over all
workers), ``%dist_trace``/``%dist_metrics`` (cross-rank span tracing
with Perfetto export + the unified metrics registry — observability/),
``%dist_pull``/``%dist_push`` (the reference wired get_var/
set_var in the worker but never exposed them: SURVEY §2.1 #9), and a
static collective-hazard warning when ``%%rank`` subsets run collective-
bearing code (SURVEY §5.2 — a mesh-deadlock guard the reference lacks).
"""

from __future__ import annotations

import re
import threading
import time

from IPython.core.magic import Magics, cell_magic, line_magic, magics_class
from IPython.core.magic_arguments import (argument, magic_arguments,
                                          parse_argstring)

from ..manager import ProcessManager
from ..messaging import CommunicationManager, WorkerDied
from ..utils import knobs as _knobs
from . import display as display_mod
from . import proxies, rankspec
from .timeline import Timeline

_COLLECTIVE_TOKENS = re.compile(
    r"\b(all_reduce_quantized|all_reduce|all_gather|broadcast|"
    r"reduce_scatter|barrier|psum|pmean|pmax|pmin|ppermute|all_to_all|"
    r"sync_global_devices|shard_map|dist\.(?:scatter|gather|reduce))\b")

_BANNER = """\
✅ {n} workers ready (backend={backend}, attach {secs:.1f}s).

Every cell now runs on ALL workers. Namespace on each worker:
  rank, world_size     — this worker's rank / total workers
  jax, jnp, np         — preloaded libraries
  devices, device      — global device list / this worker's device
  Mesh, P, shard_map   — sharding toolkit (PartitionSpec as P)
  dist                 — torch.distributed-style facade
  all_reduce, all_gather, broadcast, barrier, reduce_scatter,
  all_reduce_quantized — eager collectives over ICI/DCN
  make_mesh, shard_batch, ring_attention, ulysses_attention,
  pipeline_forward, shard_stage_params, moe_ffn, init_moe_params
                       — mesh/SP/PP/EP building blocks
  load_hf_pretrained   — HF Llama-family checkpoint → JAX pytree
  generate, speculative_generate, DecodeServer
                       — KV-cache decode / draft-verify decoding /
                         continuous-batching serving

Magics: %%rank [0,1] targeted cells · %sync barrier · %dist_interrupt ·
%dist_status ·
%%distributed --async (stream cells through the DAG-gated in-flight
window — NBD_ASYNC_WINDOW arms it session-wide) · %dist_wait (drain
the window) · %%distributed --repeat k [--until EXPR] (compile once,
loop worker-side, per-step telemetry on heartbeats) ·
%dist_mode -d/-e auto-run off/on · %dist_pull/%dist_push vars ·
%dist_checkpoint/%dist_restore path names · %dist_heal [--restore ckpt] ·
%dist_profile start/stop · %dist_trace start/stop/save (Perfetto) ·
%dist_metrics · %dist_lat (per-cell stage attribution + waterfall) ·
%dist_top (live device telemetry) ·
%dist_postmortem (crash bundles from the flight recorder) ·
%dist_watchdog (collective hang detection + escalation) ·
%dist_doctor (stuck-cell report: skew table, stacks, flight tails) ·
%dist_lint warn|strict|off (pre-dispatch cell vetting: rank-conditional
collectives, subset hazards, host-syncs in loops — strict blocks
error-severity cells; also %%distributed --strict per cell;
deps|effects render the session's inferred cell effect footprints
and write→read dependency DAG; self runs the ten framework
self-lint passes — registries, lock discipline, and the lifecycle
passes: resource-leak, bracket-discipline, shutdown-completeness) ·
%dist_supervise on (auto-heal) · %dist_chaos (fault injection) ·
%dist_attach (rejoin this fleet after a kernel restart) ·
%dist_pool start|status|stop (shared multi-tenant worker pool;
%dist_attach --tenant NAME joins it with an isolated namespace) ·
%dist_serve start|status|stop|submit|result|stream (chaos-hardened
continuous-batching generation through the pool: journaled requests
survive rank death; explicit shed/rejected verdicts under overload) ·
%dist_gc (sweep stale session run dirs) ·
%timeline_show · %timeline_sidecar (in-notebook persistence) ·
%dist_shutdown (explicit fleet teardown — a kernel restart alone only
orphans the fleet; it stays reattachable for NBD_ORPHAN_TTL_S)
"""


@magics_class
class DistributedMagics(Magics):
    # Class-level singletons so re-registration survives %load_ext cycles
    # (reference: magic.py:95-98).
    _comm: CommunicationManager | None = None
    _pm: ProcessManager | None = None
    _world: int = 0
    _auto_active: bool = False
    _timeline: Timeline = Timeline()
    _active_display = None
    _display_lock = threading.Lock()
    _instance = None
    _proxy_registry: dict = {}
    _sidecar: str | None = None
    # Last successful %dist_init line — %dist_heal replays it after a
    # crash (kept across %dist_reset on purpose: healing after a reset
    # is the common recovery flow).
    _last_init_line: str | None = None
    # Last checkpoint path a %dist_checkpoint COMPLETED writing — the
    # auto-heal supervisor restores it after a respawn.  Background
    # saves park their path in _bg_ckpt_path until a --status poll
    # confirms every rank finished (an in-flight or failed save must
    # never become the heal target).
    _last_ckpt_path: str | None = None
    _bg_ckpt_path: str | None = None
    # Ranks whose in-flight background save has reported "done": the
    # worker consumes its async handle on the first done poll (later
    # polls say "idle"), so doneness must accumulate ACROSS polls.
    _bg_ckpt_done: set = set()

    @classmethod
    def _clear_bg_ckpt(cls) -> None:
        """Invalidate the pending background-save promotion (the two
        fields are one invariant — always cleared together)."""
        cls._bg_ckpt_path = None
        cls._bg_ckpt_done = set()

    # Session-wide pre-dispatch cell-vetting mode (ISSUE 7): None =
    # resolve the NBD_LINT knob at use time; %dist_lint pins it.
    _lint_mode: str | None = None

    # Active auto-heal supervisor (resilience/supervisor.py), or None.
    _supervisor = None
    # Live scrape endpoint (observability/httpd.py), or None — started
    # by %dist_init when NBD_METRICS_PORT is set; closed on shutdown.
    _metrics_httpd = None
    # Active hang watchdog (resilience/watchdog.py), or None.  Auto-
    # started by %dist_init/%dist_attach when NBD_HANG enables it
    # (default on, ladder warn→dump); reconfigured by %dist_watchdog.
    _watchdog = None
    # True while %dist_heal is tearing down + respawning: shutdown_all
    # must NOT discard the watchdog then — the replayed %dist_init
    # re-binds the SAME instance, preserving a %dist_watchdog-
    # customized policy and the counters/event history.
    _healing: bool = False
    # True when this kernel joined the fleet via %dist_attach rather
    # than spawning it (durable sessions) — surfaced in %dist_status.
    _attached: bool = False
    # Tenant mode (gateway pools, ISSUE 8): this kernel is attached to
    # a shared pool as one tenant (`%dist_attach --tenant NAME`).  The
    # client replaces (comm, pm) — cells route through the gateway's
    # scheduler, and %dist_status/%dist_top render the pool view.
    _tenant = None              # gateway.client.TenantClient | None
    _pool_info: dict | None = None   # the gateway manifest we attached to
    # Async pipelined executor (ISSUE 14): the bounded in-flight
    # window %%distributed --async / NBD_ASYNC_WINDOW cells stream
    # through.  Created lazily against the live comm; dropped with it.
    _async_exec = None          # messaging.pipeline.AsyncExecutor | None

    _cell_hooks: tuple | None = None

    def __init__(self, shell):
        super().__init__(shell)
        DistributedMagics._instance = self
        self._register_cell_hooks()

    # ==================================================================
    # whole-session timeline hooks
    #
    # The reference registers pre/post_run_cell at load so *every* cell
    # — local and distributed — lands in the timeline (reference:
    # magic.py:123-130, 647-707).  Distributed cells get their richer
    # record from _run_on_ranks; these hooks add kind="local" records
    # for everything else (plain local cells, magics, auto-mode off).

    def _register_cell_hooks(self) -> None:
        cls = DistributedMagics
        if cls._cell_hooks is not None:
            # A previous %load_ext cycle left its bound methods
            # registered — drop them or every cell records twice.
            cls.unregister_cell_hooks()
        if self.shell is None:
            return
        self.shell.events.register("pre_run_cell", self._pre_run_cell)
        self.shell.events.register("post_run_cell", self._post_run_cell)
        cls._cell_hooks = (self._pre_run_cell, self._post_run_cell,
                           self.shell)

    @classmethod
    def unregister_cell_hooks(cls) -> None:
        if cls._cell_hooks is None:
            return
        pre, post, shell = cls._cell_hooks
        cls._cell_hooks = None
        for name, cb in (("pre_run_cell", pre), ("post_run_cell", post)):
            try:
                shell.events.unregister(name, cb)
            except ValueError:
                pass

    def _pre_run_cell(self, info) -> None:
        self._cell_t0 = time.time()
        self._cell_raw = getattr(info, "raw_cell", "") or ""
        self._cell_recs_before = len(DistributedMagics._timeline.records)

    def _post_run_cell(self, result) -> None:
        t0 = getattr(self, "_cell_t0", None)
        if t0 is None:
            return
        self._cell_t0 = None
        tl = DistributedMagics._timeline
        if len(tl.records) <= self._cell_recs_before:
            # not distributed — record the local cell (distributed
            # cells were already recorded richer by _run_on_ranks)
            tl.record_local(self._cell_raw, t0, time.time() - t0,
                            ok=bool(getattr(result, "success", True)))
        self._flush_sidecar()

    def _flush_sidecar(self) -> bool:
        """Write the timeline sidecar after every cell when
        %timeline_sidecar is on — the server-side pre_save_hook
        (jupyter_hooks.py) folds it into the notebook's metadata at
        save time.  Fail-open (a write error must never break cells)
        but returns whether THIS write landed, so %timeline_sidecar on
        can fail loudly instead of trusting a stale file."""
        path = DistributedMagics._sidecar
        if not path:
            return False
        import json
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(DistributedMagics._timeline.payload(), f)
            import os
            os.replace(tmp, path)
            return True
        except Exception:
            return False

    # ==================================================================
    # state helpers

    @classmethod
    def reset_class_state(cls) -> None:
        if cls._supervisor is not None:
            cls._supervisor.stop()
            cls._supervisor = None
        if cls._watchdog is not None:
            cls._watchdog.stop()
            cls._watchdog = None
        if cls._metrics_httpd is not None:
            try:
                cls._metrics_httpd.close()
            except Exception:
                pass
            cls._metrics_httpd = None
        # In-flight background-save tracking is world-specific (per-
        # rank doneness): stale entries from a previous (possibly
        # larger) world must not promote a half-written checkpoint in
        # the next one.  _last_ckpt_path survives like _last_init_line:
        # it names a COMPLETED checkpoint, healing's restore target.
        cls._clear_bg_ckpt()
        cls._drop_tenant_state()
        cls._async_exec = None
        cls._comm = None
        cls._pm = None
        cls._world = 0
        cls._attached = False
        cls._auto_active = False
        cls._timeline = Timeline()
        cls._active_display = None
        cls._proxy_registry = {}
        cls._cell_rank_history = {}
        if cls._sidecar:
            import os
            try:
                os.remove(cls._sidecar)
            except OSError:
                pass
        cls._sidecar = None

    def on_extension_loaded(self) -> None:
        print("nbdistributed_tpu loaded. Start workers with: "
              "%dist_init -n <N>")

    def _running(self) -> bool:
        return (self._comm is not None and self._pm is not None
                and self._pm.is_running())

    def _require_cluster(self) -> bool:
        if not self._running():
            if DistributedMagics._tenant is not None:
                # "%dist_init first" would be circular advice here —
                # %dist_init itself refuses in tenant mode.
                print(f"❌ attached to a gateway pool as tenant "
                      f"{DistributedMagics._tenant.name!r} — only "
                      "%%distributed cells run on a pool (subset "
                      "%%rank, %sync, interrupts and friends need a "
                      "dedicated fleet: %dist_shutdown to detach, "
                      "then %dist_init).")
            else:
                print("❌ No distributed cluster. Run %dist_init "
                      "first.")
            return False
        return True

    # ==================================================================
    # streaming plumbing

    def _feed_stream(self, rank: int, data: dict) -> None:
        """Output callback (IO thread).  Routes to the active cell's
        display, or prints directly for output that arrives outside any
        request (e.g. prints from background threads on workers)."""
        with DistributedMagics._display_lock:
            disp = DistributedMagics._active_display
        if disp is not None:
            disp.feed(rank, data)
        else:
            text = data.get("text", "")
            if text.strip():
                print(f"[rank {rank}] {text}", end=""
                      if text.endswith("\n") else "\n")

    def _run_on_ranks(self, code: str, ranks: list[int], kind: str,
                      deadline_s: float | None = None,
                      vet_s: float | None = None,
                      repeat: int | None = None,
                      until: str | None = None):
        """Send an execute request and stream output while waiting
        (reference: magic.py:1042-1129 runs the send in a helper thread
        and polls buffers from the main thread; same structure, 30 ms
        cadence instead of 100 ms).  ``repeat``/``until`` ride the
        payload: the worker compiles once and loops k steps
        (ISSUE 14)."""
        # A synchronous cell is a sync point for the async window:
        # every streamed cell completes (and surfaces its errors)
        # before this one dispatches, so program order stays readable.
        self._drain_async("synchronous cell")
        comm = self._comm
        assert comm is not None
        disp = display_mod.StreamDisplay()
        rec = self._timeline.start(code, ranks, kind=kind)
        # Cell-level span while a %dist_trace session is active: the
        # send span (opened inside send_to_ranks, on the helper thread)
        # nests under it via activate(), and the timeline record
        # carries its ids so a row maps to the span tree in Perfetto.
        tr = comm.tracer
        cell_span = (tr.begin(f"cell/{kind}", kind="cell",
                              attrs={"ranks": list(ranks),
                                     "code": code.strip()[:120]})
                     if tr.enabled else None)
        if cell_span is not None:
            rec.trace_id = cell_span.trace_id
            rec.span_id = cell_span.span_id
        with DistributedMagics._display_lock:
            DistributedMagics._active_display = disp
        result: dict = {}
        error: list[Exception] = []

        def _send():
            try:
                # target_ranks ride the request: the worker publishes
                # them while the cell runs, and the eager
                # world-collectives raise at CALL time when entered by
                # a strict subset (runtime/collective_guard.py) —
                # BEFORE the control plane would hang on replies that
                # cannot come.
                payload = {"code": code, "target_ranks": list(ranks)}
                if deadline_s is not None:
                    # The worker echoes this back on heartbeats so
                    # the hang watchdog can enforce the budget with
                    # no coordinator-side bookkeeping.
                    payload["deadline_s"] = deadline_s
                if repeat is not None:
                    # Worker-side step loop: compile once, run k
                    # steps, report per-step progress on heartbeats.
                    payload["repeat"] = int(repeat)
                    if until:
                        payload["until"] = until
                with tr.activate(cell_span):
                    # vet_s: how long pre-dispatch vetting took — the
                    # latency observatory's "vet" stage.
                    result.update(comm.send_to_ranks(
                        ranks, "execute", payload, vet_s=vet_s))
            except Exception as e:
                error.append(e)

        worker_thread = threading.Thread(target=_send, daemon=True)
        worker_thread.start()
        try:
            try:
                while worker_thread.is_alive():
                    worker_thread.join(timeout=0.03)
                    disp.drain()
            except KeyboardInterrupt:
                # Jupyter's interrupt button SIGINTs the kernel while we
                # block here; forward it to the workers (their cells
                # abort with KeyboardInterrupt replies) and keep
                # collecting those replies.  A second Ctrl-C abandons
                # the wait.
                print("\n🛑 interrupt: signaling workers "
                      f"{self._pm.interrupt()} — waiting for aborted-"
                      "cell replies (Ctrl-C again to stop waiting)")
                try:
                    while worker_thread.is_alive():
                        worker_thread.join(timeout=0.03)
                        disp.drain()
                except KeyboardInterrupt:
                    print("🛑 not waiting for worker replies; "
                          "%sync to realign later")
            disp.drain()
            disp.finalize()
        finally:
            with DistributedMagics._display_lock:
                DistributedMagics._active_display = None
            tr.end(cell_span)
        self._timeline.finish(rec, result or None)
        if error:
            e = error[0]
            if isinstance(e, WorkerDied):
                print(f"💀 {e}")
                print("   Run %dist_status for details; %dist_reset to "
                      "rebuild the cluster.")
            elif isinstance(e, TimeoutError):
                print(f"⏱️ {e}")
            else:
                print(f"❌ {type(e).__name__}: {e}")
            return None
        display_mod.print_rank_errors(result)
        if repeat is not None and result:
            d0 = next((m.data for m in result.values()
                       if isinstance(getattr(m, "data", None), dict)
                       and m.data.get("steps") is not None), None)
            if d0 is not None and not d0.get("error"):
                early = (" (stopped early by --until)"
                         if d0.get("stopped_early") else "")
                last = d0.get("last_scalar")
                print(f"🔁 {d0['steps']}/{d0.get('repeat')} steps in "
                      f"{d0.get('duration_s', 0):.2f}s — "
                      f"{d0.get('steps_per_s', 0):.1f} steps/s, one "
                      f"dispatch{early}"
                      + (f" · last {last:g}" if last is not None
                         else ""))
        self._record_cell_ranks(result, ranks)
        return result

    # Coordinator-side record of which ranks executed each cell (the
    # SURVEY §5.2 check): keyed by the worker-computed source hash.
    _cell_rank_history: dict = {}

    def _record_cell_ranks(self, result: dict, ranks: list[int]) -> None:
        """Track per-cell rank coverage and warn when a cell that
        ACTUALLY invoked world-collectives (runtime count, not a text
        scan) completed on a strict subset of the mesh.  The
        deadlocking case raises on the worker at call time
        (runtime/collective_guard.py) and its per-rank error already
        tells the story — the warning is suppressed when any reply
        errored.  What remains covers calls that complete locally
        (e.g. raw control-plane requests with no target stamp), which
        silently diverge state across ranks.  The accumulated history
        names the cell's earlier rank coverage so the user can see
        the drift; it is bounded and cleared on shutdown/reset."""
        ops, h, errored = 0, None, False
        for msg in result.values():
            d = getattr(msg, "data", None)
            if isinstance(d, dict):
                h = d.get("cell_sha1", h)
                ops = max(ops, int(d.get("collective_ops") or 0))
                errored = errored or "error" in d
        hist = DistributedMagics._cell_rank_history
        prior = set(hist.get(h, ())) if h is not None else set()
        if h is not None:
            hist[h] = prior | set(ranks)
            while len(hist) > 512:            # bound a long session
                hist.pop(next(iter(hist)))
        if ops and len(ranks) < self._world and not errored:
            extra = (f" (earlier runs of this cell covered ranks "
                     f"{sorted(prior)})" if prior - set(ranks) else "")
            print(f"⚠️ This cell made {ops} world-collective call(s) "
                  f"but ran on ranks {sorted(ranks)} of "
                  f"{self._world} — collective results computed by a "
                  f"subset diverge from the mesh; run it on all "
                  f"ranks.{extra}")

    # ==================================================================
    # %dist_init

    @magic_arguments()
    @argument("-n", "--num-workers", type=int, default=2,
              help="number of worker processes (one per TPU chip)")
    @argument("--backend", default="auto", choices=["auto", "cpu", "tpu"],
              help="accelerator backend; cpu uses cross-process gloo")
    @argument("-t", "--timeout", type=float, default=None,
              help="per-request timeout in seconds (default: none — "
                   "training mode)")
    @argument("--chips-per-worker", type=int, default=1,
              help="TPU chips owned by each worker process")
    @argument("--chips", default=None,
              help="explicit TPU chip ids, comma-separated (e.g. "
                   "'2,3') — pin workers to specific chips on a "
                   "shared host; the reference's --gpu-ids analog")
    @argument("--attach-timeout", type=float, default=180.0,
              help="seconds to wait for workers to come up")
    @argument("--hosts", default=None,
              help="multi-host spec 'h1,h2:2,local' (one worker per TPU "
                   "host); requires --coordinator-addr for remote hosts")
    @argument("--coordinator-addr", default="127.0.0.1",
              help="address of this kernel reachable from every host")
    @argument("--agents", default=None,
              help="host-agent endpoints 'h1=10.0.0.2:7411,h2=...' — "
                   "remote hosts listed here launch through their "
                   "nbd_agent daemon (tools/nbd_agent.py) instead of "
                   "ssh")
    @argument("--attach", nargs="?", const="", default=None,
              dest="attach_dir",
              help="reattach to a surviving fleet instead of spawning "
                   "one (optionally naming its run dir) — alias for "
                   "%%dist_attach")
    @line_magic
    def dist_init(self, line):
        """Start N workers and route subsequent cells to them
        (reference: magic.py:397-536)."""
        args = parse_argstring(self.dist_init, line)
        if args.attach_dir is not None:
            return self.dist_attach(args.attach_dir)
        if DistributedMagics._tenant is not None:
            # Tenant mode routes every cell to the pool; a second
            # local fleet here would spawn, burn chips, and never
            # receive a cell.
            print(f"⚠️ attached to a gateway pool as tenant "
                  f"{DistributedMagics._tenant.name!r} — "
                  "%dist_shutdown (detaches, pool survives) first.")
            return
        if self._running():
            print(f"⚠️ {self._world} workers already running. "
                  "%dist_shutdown first.")
            return
        t0 = time.time()
        num_workers = args.num_workers
        # Explicit chip pinning (reference: magic.py:454-488): parse
        # and sanity-check before anything spawns; full count/dup/
        # availability validation happens pre-spawn in start_workers.
        chips = None
        if args.chips:
            from ..manager import topology as _topo
            try:
                chips = _topo.parse_chips(args.chips)
            except ValueError as e:
                print(f"❌ {e}")
                return
            if args.hosts:
                print("❌ --chips is a single-host option; host plans "
                      "assign whole hosts, not chips.")
                return
            backend_now = (args.backend if args.backend != "auto"
                           else _topo.detect_backend())
            if backend_now != "tpu":
                # Reference parity: "CUDA not available, GPU IDs will
                # be ignored" (magic.py:481-483).
                print("⚠️  TPU backend not active, chip IDs will be "
                      "ignored")
                chips = None
            else:
                print(f"Using TPU chips: {chips}")
        host_specs = None
        agents = None
        if args.hosts:
            if args.chips_per_worker != 1:
                print("❌ --chips-per-worker is a single-host option; "
                      "host plans run one worker per TPU host.")
                return
            from ..manager import multihost
            try:
                host_specs = multihost.parse_hosts(args.hosts)
            except ValueError as e:
                print(f"❌ {e}")
                return
            num_workers = sum(h.workers for h in host_specs)
            if args.agents:
                from ..manager import hostagent
                try:
                    # IPython's non-posix arg_split keeps quote chars
                    # inside the token; strip them like %dist_attach.
                    agents = hostagent.parse_agents(
                        args.agents.strip().strip("'\""))
                except ValueError as e:
                    print(f"❌ {e}")
                    return
        elif args.agents:
            print("❌ --agents requires a --hosts plan naming the "
                  "agent hosts.")
            return
        # Remote hosts must be able to dial the control plane: bind all
        # interfaces when the plan leaves this machine (default stays
        # loopback-only) — and require a per-cluster shared secret on
        # that bind: this port executes code, so an unauthenticated
        # non-loopback listener would be remote code execution for
        # anyone who can reach it.
        bind_host, auth_token = "127.0.0.1", None
        if host_specs is not None and any(h.host != "local"
                                          for h in host_specs):
            import secrets
            bind_host = "0.0.0.0"
            auth_token = secrets.token_hex(16)
        # Durable session identity: the token ties workers, manifest,
        # and any future reattaching coordinator to ONE session; epoch
        # 1 is this first coordinator's tenancy (a reattach bumps it).
        from ..resilience import session as session_mod
        session_token = session_mod.mint_token()
        comm = CommunicationManager(num_workers=num_workers,
                                    host=bind_host,
                                    timeout=args.timeout,
                                    auth_token=auth_token,
                                    session_token=session_token,
                                    session_epoch=1)
        pm = ProcessManager()
        pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
        pm.add_death_callback(self._announce_death)
        try:
            print(f"🚀 Spawning {num_workers} workers "
                  f"(backend={args.backend}"
                  + (f", hosts={args.hosts}" if args.hosts else "")
                  + ")...")
            if host_specs is not None:
                # Agents authenticate with their daemon-start secret
                # (export the same one as NBD_AGENT_TOKEN here), NOT
                # this session's minted control-plane token.
                agent_token = _knobs.get_str("NBD_AGENT_TOKEN")
                if agents and agent_token is None:
                    print("⚠️ NBD_AGENT_TOKEN is not set — dialing the "
                          "agents with this session's minted secret, "
                          "which only works if the daemons were "
                          "started with it")
                pm.start_workers_multihost(
                    host_specs, comm.port,
                    coordinator_host=args.coordinator_addr,
                    backend=args.backend, auth_token=auth_token,
                    agents=agents, agent_token=agent_token,
                    extra_env={"NBD_SESSION_TOKEN": session_token,
                               "NBD_SESSION_EPOCH": "1"})
            else:
                pm.start_workers(num_workers, comm.port,
                                 backend=args.backend,
                                 chips_per_worker=args.chips_per_worker,
                                 chips=chips,
                                 extra_env={
                                     "NBD_SESSION_TOKEN": session_token,
                                     "NBD_SESSION_EPOCH": "1"})
            from ..manager import wait_until_ready
            wait_until_ready(
                comm, pm, args.attach_timeout,
                on_wait=lambda: print(
                    f"   ... waiting ({len(comm.connected_ranks())}/"
                    f"{num_workers} attached)"))
        except Exception as e:
            print(f"❌ Worker startup failed: {e}")
            pm.shutdown()
            comm.shutdown()
            return
        comm.set_output_callback(self._feed_stream)
        # Host topology → link shaping, partition sentry, per-host
        # status (single-host worlds: everything "local", inert).
        comm.set_host_map(pm.hosts)
        DistributedMagics._comm = comm
        DistributedMagics._pm = pm
        DistributedMagics._world = num_workers
        DistributedMagics._attached = False
        if host_specs is not None:
            # Multi-host session bootstrap: the workers got the
            # session token/epoch via their env; the hello exchange
            # mirrors the session manifest to every worker so the
            # orphan reconnect loop can rediscover the endpoint
            # WITHOUT a shared run-dir filesystem (partition
            # tolerance, ISSUE 6).
            mirror = session_mod.make_manifest(
                world_size=num_workers,
                control_host=args.coordinator_addr,
                control_port=comm.port, bind_host=bind_host,
                token=session_token, epoch=1,
                pids={r: p.pid for r, p in pm.processes.items()},
                backend=pm.backend, dist_port=pm.dist_port,
                auth_token=auth_token, init_line=line)
            try:
                comm.send_to_all(
                    "hello", {"token": session_token, "epoch": 1,
                              "manifest": mirror}, timeout=30)
            except Exception as e:
                print(f"⚠️ manifest mirror hello failed ({e}) — "
                      "orphaned workers will only retry the "
                      "spawn-time endpoint")
        if host_specs is None:
            # Session manifest: what a future %dist_attach needs to
            # adopt this fleet after THIS kernel dies.  Single-host
            # only — pid adoption and the shared run-dir manifest
            # assume one pid namespace and filesystem.
            from ..observability import flightrec as _flightrec
            _rd = _flightrec.run_dir()
            _existing = session_mod.read_manifest(_rd)
            if (_existing is not None
                    and _existing.get("token") != session_token
                    and session_mod.live_pids(_existing)):
                # NBD_RUN_DIR points at ANOTHER session whose fleet is
                # still alive (e.g. after a failed %dist_attach, or a
                # user-exported run dir): clobbering its manifest would
                # strand that fleet unreattachable.  This new world
                # simply isn't durable.
                print(f"⚠️ {_rd} already holds a LIVE session's "
                      "manifest — not overwriting it; this world is "
                      "NOT reattachable. %dist_attach that session, "
                      "or unset NBD_RUN_DIR and re-init.")
            else:
                try:
                    session_mod.write_manifest(
                        _rd, session_mod.make_manifest(
                            world_size=num_workers,
                            control_host="127.0.0.1",
                            control_port=comm.port, bind_host=bind_host,
                            token=session_token, epoch=1,
                            pids={r: p.pid
                                  for r, p in pm.processes.items()},
                            backend=pm.backend, dist_port=pm.dist_port,
                            auth_token=auth_token, init_line=line,
                            supervised=DistributedMagics._supervisor
                            is not None))
                except OSError as e:
                    print(f"⚠️ session manifest not written ({e}) — "
                          "%dist_attach will not find this session")
        if DistributedMagics._last_init_line != line:
            # A DIFFERENT world configuration invalidates the previous
            # world's checkpoint as an auto-heal restore target (its
            # rank layout / model state need not fit this world).  A
            # same-line re-init — the heal replay path — keeps it.
            DistributedMagics._last_ckpt_path = None
        DistributedMagics._last_init_line = line
        self._enable_auto_mode()
        self._maybe_start_watchdog()
        self._maybe_start_metrics_httpd()
        print(_BANNER.format(n=num_workers,
                             backend=pm.backend,
                             secs=time.time() - t0))

    def _maybe_start_metrics_httpd(self) -> None:
        """Start the live scrape endpoint when NBD_METRICS_PORT asks
        for one (ISSUE 13): /metrics (Prometheus), /healthz,
        /latency.json over this kernel's coordinator.  Loopback-bound
        and ungated — the single-kernel analog of the gateway's
        token-gated endpoint."""
        port = _knobs.get_int("NBD_METRICS_PORT", 0)
        if not port or DistributedMagics._metrics_httpd is not None \
                or self._comm is None:
            return
        from ..observability import httpd as obs_httpd
        try:
            DistributedMagics._metrics_httpd = obs_httpd.start_for_comm(
                self._comm, port=port)
            print(f"📈 scrape endpoint: http://127.0.0.1:"
                  f"{DistributedMagics._metrics_httpd.port}/metrics "
                  f"(/healthz, /latency.json)")
        except OSError as e:
            print(f"⚠️ metrics endpoint not started "
                  f"(NBD_METRICS_PORT={port}): {e}")

    def _announce_death(self, rank: int, rc: int | None) -> None:
        # Runs on the monitor thread; a print is best-effort context.
        print(f"\n💀 worker {rank} exited (code {rc}). "
              "%dist_status / %dist_heal [--restore ckpt] / %dist_reset")
        # Automatic postmortem: recover the dead rank's flight ring and
        # last telemetry NOW, while the evidence is fresh.  When a
        # supervisor is attached it owns capture (on its own thread,
        # before the heal destroys the world); otherwise this monitor-
        # thread capture is the only shot.
        if DistributedMagics._supervisor is None \
                and DistributedMagics._comm is not None:
            from ..observability import postmortem as pm_mod
            manifest = pm_mod.capture(
                DistributedMagics._comm, [rank],
                reason=f"worker {rank} exited (code {rc})")
            if manifest is not None:
                print(f"🛩  postmortem bundle → {manifest['dir']} "
                      f"(%dist_postmortem --last)")

    @magic_arguments()
    @argument("--restore", default=None,
              help="checkpoint directory to %%dist_restore once the "
                   "world is back")
    @argument("--force", action="store_true",
              help="rebuild even when every worker looks alive")
    @line_magic
    def dist_heal(self, line):
        """Recover from worker death: tear the remnants down, respawn
        the world with the SAME ``%dist_init`` configuration, and
        optionally restore a checkpoint into the fresh namespaces.

        ``jax.distributed`` worlds are fixed-membership — a dead rank
        cannot rejoin a live coordination service — so recovery is a
        full restart + state restore, the standard elastic-training
        recipe (SURVEY §5.3): pair with periodic
        ``%dist_checkpoint path names --background`` and healing costs
        one respawn plus one restore, not a lost session.
        """
        args = parse_argstring(self.dist_heal, line)
        replay = DistributedMagics._last_init_line
        if replay is None:
            print("❌ nothing to heal from: no successful %dist_init "
                  "recorded in this session")
            return
        dead: list[int] = []
        pm = DistributedMagics._pm
        if pm is not None and self._running():
            alive = set(pm.alive_ranks())
            dead = sorted(set(range(self._world)) - alive)
            if not dead and not args.force:
                print(f"✅ all {self._world} workers alive; nothing to "
                      f"heal (--force rebuilds anyway)")
                return
        print(f"🩹 healing: dead ranks {dead if dead else '(world down)'}"
              f" — rebuilding with: %dist_init {replay}")
        sup = DistributedMagics._supervisor  # survives a manual heal
        DistributedMagics._healing = True    # so does the watchdog
        try:
            self.shutdown_all()
            self._nuclear_shutdown()
            self.dist_init(replay)
        finally:
            DistributedMagics._healing = False
        if not self._running():
            print("❌ heal failed: the replayed %dist_init did not "
                  "bring the world up")
            if sup is not None and not sup.on_own_thread():
                print("⚠️ supervision was stopped by this heal and is "
                      "now OFF — %dist_supervise on after recovery")
            return
        if args.restore:
            self.dist_restore(args.restore)
        if sup is not None and not sup.on_own_thread():
            # Manual heal with supervision active: re-bind the
            # supervisor to the fresh world (shutdown_all stopped it).
            # The supervisor-driven path re-binds itself from the heal
            # callback's return value instead.
            sup.attach(self._comm, self._pm)
            DistributedMagics._supervisor = sup

    # ==================================================================
    # durable sessions: reattach + stale-run GC (ISSUE 4)

    @magic_arguments()
    @argument("run_dir", nargs="?", default=None,
              help="session run directory (default: NBD_RUN_DIR, else "
                   "the newest manifest with live pids under the runs "
                   "root)")
    @argument("-t", "--timeout", type=float, default=None,
              help="per-request timeout for the new manager (default: "
                   "none — training mode)")
    @argument("--attach-timeout", type=float, default=90.0,
              help="seconds to wait for orphaned workers to dial back")
    @argument("--tenant", default=None,
              help="attach to a GATEWAY POOL as this tenant name "
                   "(%%dist_pool start spawns one) instead of adopting "
                   "a single-kernel fleet; reattaching under the same "
                   "name resumes the tenant session and drains its "
                   "parked results exactly once")
    @argument("--priority", type=int, default=None,
              help="tenant scheduling priority in the pool's "
                   "fair-share queue (higher wins; tenant mode "
                   "only).  Omitted on a reattach = keep the "
                   "tenant's current priority (new tenants get 0)")
    @line_magic
    def dist_attach(self, line):
        """Reattach this kernel to a fleet that survived its
        coordinator's death (durable sessions), or — with
        ``--tenant NAME`` — attach to a shared gateway pool as one
        tenant of many.

        The single-kernel path reads the session manifest under the
        run dir, adopts the worker pids, re-binds the control
        endpoint, bumps the session epoch (fencing out any stale
        coordinator), verifies the session token with a per-rank
        hello, and drains results the workers parked while orphaned —
        the interrupted cell's output is redelivered exactly once, and
        every worker's namespace, compiled functions, and device state
        are exactly as the crash left them.  The tenant path does the
        same dance against the gateway: a reattach under the same name
        proves the tenant token, bumps the TENANT epoch (fencing the
        crashed kernel's old connection), and drains the tenant's own
        parked-result partition exactly once."""
        from ..resilience import session as session_mod
        args = parse_argstring(self.dist_attach, line)
        if self._running() or DistributedMagics._tenant is not None:
            what = ("tenant " + DistributedMagics._tenant.name
                    if DistributedMagics._tenant is not None
                    else f"{self._world} workers")
            print(f"⚠️ already attached ({what}). "
                  "%dist_shutdown first.")
            return
        t0 = time.time()
        run_dir = (args.run_dir or "").strip().strip("'\"") or None
        if args.tenant:
            return self._attach_tenant(
                run_dir, args.tenant.strip().strip("'\""),
                priority=args.priority, timeout=args.timeout)
        try:
            comm, pm, manifest, hello = session_mod.attach(
                run_dir, attach_timeout=args.attach_timeout,
                request_timeout=args.timeout)
        except Exception as e:
            print(f"❌ attach failed: {e}")
            return
        pm.add_death_callback(self._announce_death)
        comm.set_output_callback(self._feed_stream)
        comm.set_host_map(pm.hosts)
        DistributedMagics._comm = comm
        DistributedMagics._pm = pm
        DistributedMagics._world = comm.num_workers
        DistributedMagics._attached = True
        if manifest.get("init_line") is not None:
            # %dist_heal replays the ORIGINAL init of this session.
            DistributedMagics._last_init_line = manifest["init_line"]
        self._enable_auto_mode()
        sizes = sorted({(m.data or {}).get("namespace_size") or 0
                        for m in hello.values()})
        print(f"🔗 reattached to {comm.num_workers} workers "
              f"(epoch {comm.session_epoch}, "
              f"run {_knobs.get_str('NBD_RUN_DIR')}, "
              f"{time.time() - t0:.1f}s) — namespaces intact "
              f"({'/'.join(str(s) for s in sizes)} names/rank)")
        # Exactly-once redelivery of results parked while orphaned.
        if any((m.data or {}).get("parked") for m in hello.values()):
            try:
                drained = session_mod.drain_mailboxes(comm)
            except Exception as e:
                print(f"⚠️ mailbox drain failed: {e} — parked results "
                      "remain claimable on the workers")
                drained = {}
            for r in sorted(drained):
                for mid, res in drained[r].items():
                    self._render_late_result(
                        r, res, "finished while orphaned", mid=mid)
        if manifest.get("supervised") \
                and DistributedMagics._supervisor is None:
            print("🛡  re-arming supervision (the session had "
                  "%dist_supervise on)")
            self.dist_supervise("on")
        self._maybe_start_watchdog()
        print("Every cell runs on ALL workers again. %dist_status "
              "shows the session header.")

    @staticmethod
    def _render_late_result(rank, res, suffix: str, *, mid: str = "",
                            prefix: str = "") -> None:
        """One 📬 line for a cell result that outlived its waiter —
        drained from a mailbox (orphaned/detached) or delivered late
        after an interrupt.  The single render path for all three."""
        res = res or {}
        text = (res.get("error")
                or str(res.get("output") or "").strip()
                or "(no output)")
        tag = f" {mid[:8]}…" if mid else ""
        print(f"{prefix}📬 rank {rank} · interrupted cell{tag} "
              f"{suffix}: {text}")

    def _render_drained_reply(self, mid, res, suffix: str, *,
                              prefix: str = "") -> None:
        """Render one claimed/late reply: per-rank lines when it
        carries results, else its gateway-level verdict.  The crash
        verdicts (worker death, request timeout, shed) have no
        ``results`` key, and the claim that surfaced them was
        destructive — the verdict renders here or nowhere."""
        res = res or {}
        results = res.get("results") or {}
        if not results:
            text = (res.get("error")
                    or f"status={res.get('status') or '?'} "
                       "(no output)")
            tag = f" {mid[:8]}…" if mid else ""
            print(f"{prefix}📬 interrupted cell{tag} {suffix}: {text}")
            return
        first = True
        for r in sorted(results, key=int):
            self._render_late_result(r, results[r], suffix, mid=mid,
                                     prefix=prefix if first else "")
            first = False

    # ==================================================================
    # session gateway: tenant attach + %dist_pool (ISSUE 8)

    @classmethod
    def _drop_tenant_state(cls, *, detach: bool = False) -> str | None:
        """The one tenant-teardown path (reset, %dist_shutdown,
        %dist_pool stop): close the client, clear the pool
        bookkeeping.  Returns the tenant name, or None when this
        kernel was not attached."""
        t = cls._tenant
        if t is None:
            return None
        try:
            t.close(detach=detach)
        except Exception:
            pass
        cls._tenant = None
        cls._pool_info = None
        cls._world = 0
        cls._attached = False
        return t.name

    def _attach_tenant(self, run_dir, name, *, priority=None,
                       timeout=None):
        from ..gateway import daemon as gw_mod
        from ..gateway.client import TenantClient
        d = gw_mod.discover_gateway(run_dir)
        if d is None:
            print("❌ no gateway pool found"
                  + (f" in {run_dir}" if run_dir else
                     " (start one: %dist_pool start -n 4, or pass "
                     "its run dir)"))
            return
        manifest = gw_mod.read_gateway_manifest(d)
        if manifest is None or not gw_mod.gateway_alive(manifest):
            print(f"❌ {d} has no live gateway daemon "
                  "(%dist_pool status / %dist_gc --dry-run to "
                  "inspect)")
            return
        plane = manifest.get("tenant_plane") or {}
        # A prior session under this name: its token (recorded in the
        # gateway manifest, same-filesystem trust like session.json)
        # proves we RESUME it — the gateway bumps the tenant epoch and
        # fences the crashed kernel's old connection.
        token = ((manifest.get("tenants") or {}).get(name)
                 or {}).get("token")
        t0 = time.time()
        try:
            client = TenantClient(
                plane.get("host") or "127.0.0.1",
                int(plane.get("port") or 0), name, token=token,
                pool_token=manifest.get("pool_token"),
                priority=priority, on_stream=self._feed_stream,
                hello_timeout=float(timeout) if timeout else 30.0)
        except Exception as e:
            print(f"❌ tenant attach failed: {e}")
            return

        def _on_parked(_d: dict) -> None:
            # A cell that was in flight ACROSS the reattach just
            # finished and parked — the hello's parked list predates
            # it, so this nudge is the only signal it exists.  Drain
            # off the reader thread: drain() waits on a reply the
            # reader itself delivers.
            def _drain_bg():
                try:
                    drained = client.drain()
                except Exception:
                    return   # stays claimable on the next attach
                first = True
                for mid, res in sorted(drained.items()):
                    self._render_drained_reply(
                        mid, res, "finished while reattaching",
                        prefix="\n" if first else "")
                    first = False
            threading.Thread(target=_drain_bg, daemon=True,
                             name="nbd-parked-drain").start()

        client.on_parked = _on_parked

        def _on_serve(d: dict) -> None:
            # Serving-plane pushes (reader thread): incremental token
            # notices while a %dist_serve request decodes, and the
            # live terminal result.
            rid = d.get("rid")
            if d.get("status") is not None or d.get("done"):
                n = len(d.get("tokens") or ())
                st = d.get("status") or "done"
                extra = (f": {d['error']}" if d.get("error") else
                         f" ({n} tokens)")
                print(f"\n🧾 serve {rid} {st}{extra}")
            elif d.get("t"):
                print(f"\n📡 serve {rid}[{d.get('o')}] "
                      f"+{list(d['t'])}")

        client.on_serve = _on_serve
        DistributedMagics._tenant = client
        DistributedMagics._pool_info = {"run_dir": d, **manifest}
        DistributedMagics._world = client.world_size
        DistributedMagics._attached = True
        verb = ("🔗 reattached" if client.attach_status == "reattached"
                else "🤝 attached")
        pol = client.policy or {}
        print(f"{verb} to pool {d} as tenant {name!r} "
              f"(epoch {client.epoch}, {client.world_size} ranks, "
              f"sched {pol.get('mode', '?')}, "
              f"{time.time() - t0:.1f}s)")
        if client.parked:
            # Exactly-once redelivery of results that finished while
            # this tenant had no kernel.
            def _late_drain(claimed: dict) -> None:
                # The drain reply outlived its waiter (timeout or
                # Ctrl-C mid-attach).  The gateway's claim was already
                # destructive, so render from the reader thread — the
                # alternative is losing the results on both sides.
                first = True
                for mid, res in sorted(claimed.items()):
                    self._render_drained_reply(
                        mid, res, "finished while detached",
                        prefix="\n" if first else "")
                    first = False
            try:
                drained = client.drain(on_late=_late_drain)
            except Exception as e:
                print(f"⚠️ mailbox drain failed: {e} — parked results "
                      "remain claimable on the gateway")
                drained = {}
            for mid, res in sorted(drained.items()):
                self._render_drained_reply(mid, res,
                                           "finished while detached")
        print("Cells (%%distributed) now run on the POOL under this "
              "tenant's isolated namespace; `shared` is the opt-in "
              "cross-tenant dict. %dist_pool status shows the queue.")

    def _pool_endpoint(self, run_dir=None):
        """(manifest, run_dir) of the pool to administer: the attached
        one first, else discovery."""
        from ..gateway import daemon as gw_mod
        if run_dir is None and DistributedMagics._pool_info is not None:
            d = DistributedMagics._pool_info.get("run_dir")
            m = gw_mod.read_gateway_manifest(d)
            # No silent fallback to discovery here: a bare
            # `%dist_pool stop` targets THE ATTACHED pool, and if its
            # manifest is gone, discovering the newest other live pool
            # would aim the shutdown at a pool the user never meant
            # (possibly someone else's).  Name the problem instead.
            if m is None:
                print(f"⚠️ attached pool {d} has no readable manifest "
                      "(daemon exited?) — pass --run-dir explicitly "
                      "to administer a different pool")
            return m, d
        d = gw_mod.discover_gateway(run_dir)
        if d is None:
            return None, None
        return gw_mod.read_gateway_manifest(d), d

    @magic_arguments()
    @argument("command", nargs="?", default="status",
              choices=["start", "status", "stop", "resize", "migrate",
                       "template"])
    @argument("-n", "--workers", type=int, default=2,
              help="pool world size (start / resize target)")
    @argument("--backend", default="auto",
              choices=["auto", "cpu", "tpu"])
    @argument("--run-dir", default=None,
              help="pool run dir (start: minted when omitted; "
                   "status/stop: discovery override)")
    @argument("--max-tenants", type=int, default=None)
    @argument("--sched", default=None, choices=[None, "fifo", "fair"])
    @argument("--mesh-slots", type=int, default=None)
    @argument("--queue-depth", type=int, default=None)
    @argument("--tenant-inflight", type=int, default=None)
    @argument("--effects", action="store_true",
              help="effects-aware admission: with --mesh-slots > 1, "
                   "only cells PROVEN collective-free may overlap a "
                   "collective-bearing cell (NBD_POOL_SCHED_EFFECTS)")
    @argument("--metrics-port", type=int, default=None,
              help="start: serve GET /metrics (Prometheus), /healthz "
                   "and /latency.json on this port, token-gated with "
                   "the pool token (default: NBD_METRICS_PORT; "
                   "0 = off)")
    @argument("--start-timeout", type=float, default=240.0,
              help="seconds to wait for the daemon's readiness line")
    @argument("--autoscale", default=None, nargs="?", const="show",
              metavar="MIN:MAX",
              help="start: arm the pressure-driven autoscaler with "
                   "this worker band (thresholds from the "
                   "NBD_AUTOSCALE_* knobs); status: render the "
                   "decision audit trail (no value needed)")
    @argument("--tenant", default=None,
              help="migrate: the tenant to move")
    @argument("--to", dest="dest", default=None,
              help="migrate: destination pool run dir (default: the "
                   "least-loaded OTHER live pool)")
    @argument("--force", action="store_true",
              help="migrate: move an ATTACHED tenant too, fencing "
                   "its live connection")
    @argument("--name", default="default",
              help="template: template name")
    @argument("--file", dest="tpl_file", default=None,
              help="template: file whose contents become the "
                   "warm-start template cell (omit to list)")
    @line_magic
    def dist_pool(self, line):
        """Gateway pool admin: ``%dist_pool start -n 4`` spawns a
        gateway daemon owning a pooled worker fleet that N notebook
        kernels share (``%dist_attach --tenant NAME``);
        ``status`` shows the scheduler queue, per-tenant counters, and
        tenant-attributed per-rank busy state; ``stop`` shuts the
        daemon and its workers down.  Elastic pools (ISSUE 16):
        ``resize -n N`` changes the world size via a drain-barrier
        epoch bump, ``start --autoscale MIN:MAX`` arms the
        pressure-driven autoscaler, ``migrate --tenant NAME [--to
        RUN_DIR]`` moves a tenant to another pool, and ``template
        --file CELL.py`` registers a warm-start cell re-run on every
        resized fleet.  Scheduling/admission defaults come from the
        ``NBD_POOL_*``/``NBD_TENANT_*`` knobs."""
        import subprocess
        import sys as _sys

        from ..gateway import daemon as gw_mod
        args = parse_argstring(self.dist_pool, line)
        if args.command == "start":
            run_dir = args.run_dir
            if not run_dir:
                import tempfile
                from ..resilience import session as session_mod
                root = session_mod.default_runs_root()
                import os as _os
                _os.makedirs(root, exist_ok=True)
                run_dir = tempfile.mkdtemp(prefix="pool-", dir=root)
            cmd = [_sys.executable, "-m",
                   "nbdistributed_tpu.gateway.daemon",
                   "-n", str(args.workers), "--backend", args.backend,
                   "--run-dir", run_dir]
            for flag, v in (("--max-tenants", args.max_tenants),
                            ("--sched", args.sched),
                            ("--mesh-slots", args.mesh_slots),
                            ("--queue-depth", args.queue_depth),
                            ("--tenant-inflight",
                             args.tenant_inflight),
                            ("--metrics-port", args.metrics_port),
                            ("--autoscale", args.autoscale)):
                if v is not None:
                    cmd += [flag, str(v)]
            if args.effects:
                cmd += ["--effects"]
            import os as _os
            env = dict(_os.environ)
            env.pop("NBD_RUN_DIR", None)  # the daemon owns its own
            print(f"🚀 starting gateway pool ({args.workers} workers, "
                  f"backend={args.backend}) → {run_dir}")
            # Daemon output goes to a log FILE, not a pipe: the
            # daemon outlives this kernel by design and nobody would
            # drain a pipe — one chatty dependency later the ~64 KiB
            # buffer fills and every daemon write (and the pool with
            # it) wedges.
            log_path = _os.path.join(run_dir, "gateway.log")
            with open(log_path, "ab") as logf:
                proc = subprocess.Popen(cmd, env=env, stdout=logf,
                                        stderr=subprocess.STDOUT,
                                        start_new_session=True)
            deadline = time.time() + args.start_timeout
            m = None
            while time.time() < deadline:
                if proc.poll() is not None:
                    try:
                        with open(log_path, "rb") as f:
                            out = f.read().decode("utf-8", "replace")
                    except OSError:
                        out = ""
                    print(f"❌ gateway daemon exited "
                          f"({proc.returncode}):\n{out[-2000:]}")
                    return
                m = gw_mod.read_gateway_manifest(run_dir)
                if gw_mod.gateway_alive(m):
                    break
                time.sleep(0.3)
            if not gw_mod.gateway_alive(m):
                # SIGTERM, not SIGKILL: the daemon installs its
                # handlers before spawning, so a graceful stop reaps
                # the half-started fleet — SIGKILL orphaned those
                # workers (and any TPU devices they held) until the
                # orphan TTL expired.
                proc.terminate()
                try:
                    proc.wait(timeout=15)
                except Exception:
                    proc.kill()
                print("❌ gateway daemon never became ready "
                      f"(waited {args.start_timeout:.0f}s)")
                return
            plane = m.get("tenant_plane") or {}
            print(f"✅ pool up: pid {m.get('pid')} · tenant plane "
                  f"{plane.get('host')}:{plane.get('port')} · "
                  f"policy {m.get('policy')} · run dir {run_dir}")
            met = m.get("metrics") or {}
            if met:
                print(f"📈 scrape endpoint: http://{met.get('host')}:"
                      f"{met.get('port')}/metrics?token=<pool token> "
                      f"(/healthz, /latency.json)")
            print(f"   attach kernels with: %dist_attach --tenant "
                  f"NAME {run_dir}")
            return
        manifest, d = self._pool_endpoint(args.run_dir)
        if manifest is None:
            print("❌ no gateway pool found (start one: %dist_pool "
                  "start -n 4)")
            return
        plane = manifest.get("tenant_plane") or {}
        if args.command == "stop":
            from ..gateway.client import pool_shutdown
            try:
                res = pool_shutdown(plane.get("host") or "127.0.0.1",
                                    int(plane.get("port") or 0),
                                    manifest.get("pool_token"))
            except Exception as e:
                print(f"❌ pool stop failed: {e}")
                return
            attached_dir = (DistributedMagics._pool_info or {}).get(
                "run_dir")
            # Only tear down this kernel's attachment when the pool
            # we just stopped IS the attached one (stop --run-dir X
            # must not drop a live attachment to pool Y).
            if (DistributedMagics._tenant is not None
                    and attached_dir == d):
                DistributedMagics._drop_tenant_state()
            print(f"🛑 pool {d}: {res.get('status', res)}")
            return
        if args.command == "resize":
            from ..gateway.client import pool_resize
            print(f"🔧 resizing pool {d} → {args.workers} workers "
                  f"(drain barrier + epoch bump — in-flight cells "
                  f"finish first)...")
            try:
                res = pool_resize(plane.get("host") or "127.0.0.1",
                                  int(plane.get("port") or 0),
                                  manifest.get("pool_token"),
                                  args.workers)
            except Exception as e:
                print(f"❌ pool resize failed: {e}")
                return
            if res.get("status") == "resized":
                print(f"✅ resized: {res.get('world_size')} ranks · "
                      f"epoch {res.get('epoch')} · generation "
                      f"{res.get('generation')} · drain "
                      f"{res.get('drain_s')}s"
                      + ("" if res.get("drained") else
                         " (drain TIMED OUT — in-flight cells were "
                         "aborted with explicit verdicts)")
                      + f" · total {res.get('wall_s')}s")
            elif res.get("status") == "noop":
                print(f"ℹ pool is already {res.get('world_size')} "
                      f"ranks")
            else:
                print(f"❌ {res.get('error') or res}")
            return
        if args.command == "migrate":
            if not args.tenant:
                print("❌ migrate needs --tenant NAME")
                return
            from ..gateway.router import (MigrationError,
                                          PoolDirectory,
                                          migrate_tenant)
            dest = args.dest
            if not dest:
                placed = PoolDirectory().place(exclude=d)
                if placed is None:
                    print("❌ no OTHER live pool to migrate to "
                          "(start one, or name it with --to)")
                    return
                dest = placed[0]
            print(f"🚚 migrating tenant {args.tenant!r}: {d} → "
                  f"{dest} ...")
            try:
                res = migrate_tenant(args.tenant, d, dest,
                                     force=args.force)
            except MigrationError as e:
                print(f"❌ migration refused: {e}")
                return
            except Exception as e:
                print(f"❌ migration failed: {type(e).__name__}: {e}")
                return
            print(f"✅ migrated to {dest} (epoch "
                  f"{res.get('epoch')}) · parked results moved: "
                  f"{res.get('parked_moved')} · serve journal: "
                  f"{'yes' if res.get('journal_moved') else 'no'}"
                  + ("" if res.get("src_alive") else
                     " · source pool was DEAD — recovered from its "
                     "manifest + journal")
                  + ("" if res.get("released") else
                     " · ⚠ source copy NOT released (re-run the "
                     "migration once the source answers)"))
            print(f"   reattach kernels with: %dist_attach --tenant "
                  f"{args.tenant} {dest}")
            return
        if args.command == "template":
            from ..gateway.client import pool_template
            code = None
            if args.tpl_file:
                try:
                    with open(args.tpl_file) as f:
                        code = f.read()
                except OSError as e:
                    print(f"❌ cannot read {args.tpl_file}: {e}")
                    return
            try:
                res = pool_template(plane.get("host") or "127.0.0.1",
                                    int(plane.get("port") or 0),
                                    manifest.get("pool_token"),
                                    code, name=args.name)
            except Exception as e:
                print(f"❌ pool template failed: {e}")
                return
            if code is None:
                tpls = res.get("templates") or []
                print(f"📋 templates: {', '.join(tpls) if tpls else '(none)'}"
                      f" — register one with --file CELL.py; each "
                      f"re-runs on every resized fleet so new workers "
                      f"start warm")
            elif res.get("status") == "ok":
                print(f"✅ template {args.name!r} ran on ranks "
                      f"{res.get('ranks')} — it will re-run after "
                      f"every resize")
            else:
                print(f"❌ {res.get('error') or res.get('errors') or res}")
            return
        # status — the attached tenant connection only answers for
        # ITS pool: `status --run-dir X` while attached to pool Y
        # must probe X, not render Y's queue under X's run dir
        # (same cross-pool guard as stop above).
        attached_dir = (DistributedMagics._pool_info or {}).get(
            "run_dir")
        client = (DistributedMagics._tenant if attached_dir == d
                  else None)
        try:
            if client is not None and client.alive:
                st = client.pool_status()
            else:
                from ..gateway.client import pool_status_probe
                st = pool_status_probe(
                    plane.get("host") or "127.0.0.1",
                    int(plane.get("port") or 0),
                    manifest.get("pool_token"))
        except Exception as e:
            print(f"❌ pool status failed: {e}")
            return
        self._render_pool_status(
            st, d, show_autoscale=args.autoscale is not None)

    def _render_pool_status(self, st: dict, run_dir, *,
                            show_autoscale: bool = False) -> None:
        sched = st.get("scheduler") or {}
        pol = sched.get("policy") or {}
        mem = st.get("membership") or {}
        epoch_bit = (f" · epoch {st.get('epoch')} · gen "
                     f"{mem.get('generation')}"
                     if st.get("epoch") is not None else "")
        print(f"🏊 pool {run_dir} · pid {st.get('pid')} · "
              f"{st.get('world_size')} ranks{epoch_bit} · sched "
              f"{pol.get('mode')} (slots {pol.get('mesh_slots')}, "
              f"queue {sched.get('queued', 0)}/"
              f"{pol.get('queue_depth') or '∞'}, active "
              f"{sched.get('active', 0)}, shed "
              f"{sched.get('shed_total', 0)} total)")
        if st.get("autoscale"):
            print(f"⚖ autoscale armed: {st['autoscale']}")
        if show_autoscale:
            self._render_autoscale_audit(
                st.get("autoscale_decisions"))
        trans = mem.get("transition")
        if trans:
            print(f"⚠ resize in flight: {trans.get('from_world')} → "
                  f"{trans.get('to_world')} ranks (epoch "
                  f"{trans.get('from_epoch')} → "
                  f"{trans.get('to_epoch')}, reason: "
                  f"{trans.get('reason')}) — queued cells hold, "
                  f"in-flight cells drain")
        lat = (st.get("latency") or {}).get("summary") or {}
        if lat.get("count"):
            e = lat.get("e2e_ms") or {}
            q = (lat.get("stages") or {}).get("queue") or {}
            x = (lat.get("stages") or {}).get("execute") or {}
            print(f"⏱ cells: e2e p50/p99 {e.get('p50', 0)}/"
                  f"{e.get('p99', 0)} ms · queue p99 "
                  f"{q.get('p99', 0)} ms · execute p99 "
                  f"{x.get('p99', 0)} ms "
                  f"({lat['count']} recorded — %dist_lat for stages)")
        if st.get("metrics_port"):
            print(f"📈 scrape endpoint on port {st['metrics_port']} "
                  f"(/metrics, /healthz, /latency.json — pool token)")
        tenants = (st.get("tenants") or {}).get("tenants") or {}
        me = (DistributedMagics._tenant.name
              if DistributedMagics._tenant is not None else None)
        if tenants:
            hdr = (f"{'tenant':<14}{'state':<10}{'epoch':<7}"
                   f"{'prio':<6}{'queued':<8}{'active':<8}"
                   f"{'done':<7}{'shed':<6}{'rej':<5}{'parked':<7}")
            print(hdr)
            print("─" * len(hdr))
            per = (sched.get("tenants") or {})
            for name in sorted(tenants):
                t = tenants[name]
                s = per.get(name) or {}
                mark = "*" if name == me else ""
                state = ("attached" if t.get("attached")
                         else "detached")
                print(f"{(name + mark):<14}{state:<10}"
                      f"{t.get('epoch', '-'):<7}"
                      f"{t.get('priority', 0):<6}"
                      f"{s.get('queued', 0):<8}{s.get('active', 0):<8}"
                      f"{s.get('completed', 0):<7}"
                      f"{s.get('shed', 0):<6}{s.get('rejected', 0):<5}"
                      f"{t.get('parked', 0):<7}")
        else:
            print("(no tenants attached yet)")
        ranks = st.get("ranks") or {}
        mranks = mem.get("ranks") or {}
        draining = {r for r, m in mranks.items()
                    if m.get("state") == "draining"}
        stalled: set = set()
        for v in st.get("hang_verdicts") or ():
            stalled.update(str(r) for r in v.get("ranks") or ())
        # A draining rank is parked by the resize barrier ON PURPOSE —
        # rendering it stalled would be exactly the watchdog
        # mis-blame the drain path exists to prevent.
        stalled -= draining
        rows = [(r, v) for r, v in sorted(ranks.items(),
                                          key=lambda kv:
                                          int(kv[0]))
                if v.get("busy_type") or v.get("srv")
                or r in draining or r in stalled
                or (mranks.get(r) or {}).get("join_epoch", 1) > 1]
        for r, v in rows:
            who = (f" · tenant {v['tenant']}" if v.get("tenant")
                   else "")
            if r in draining:
                busy = "⚠ draining"
            elif r in stalled:
                busy = "⚠ stalled"
            elif v.get("busy_type"):
                busy = f"⚙ {v['busy_type']} {v.get('busy_s', 0):.1f}s"
            else:
                busy = "idle"
            je = (mranks.get(r) or {}).get("join_epoch")
            joined = (f" · joined ep {je}"
                      if je is not None and je > 1 else "")
            srv = v.get("srv") or {}
            kvb = srv.get("kvb") or ()
            scol = (f" · 🔄 {srv.get('tps', 0)} tok/s · KV "
                    f"{srv.get('occ', 0)}/{srv.get('slots', 0)}"
                    + (f" · {kvb[0]}/{kvb[1]} blk" if len(kvb) == 2
                       else "")
                    if srv else "")
            print(f"   rank {r}: {busy}{joined}{who}{scol}")
        if st.get("serving"):
            self._render_serve_status(st["serving"])
        for v in st.get("hang_verdicts") or ():
            print(f"   ⚠ HUNG [{v.get('kind')}] {v.get('detail')}")

    @staticmethod
    def _render_autoscale_audit(decisions) -> None:
        """The autoscaler decision audit trail (ISSUE 18): one row
        per recent observation — pressure inputs, sustain/cooldown
        state, verdict — newest last."""
        decs = decisions or []
        if not decs:
            print("   (no autoscale audit records — arm the "
                  "autoscaler with %dist_pool start --autoscale "
                  "MIN:MAX)")
            return
        hdr = (f"   {'age':>6} {'world':>5} {'verdict':<8} "
               f"{'target':>6} {'queued':>6} {'backlog':>7} "
               f"{'p95':>7} {'sustain':>8} reason")
        print(hdr)
        print("   " + "─" * (len(hdr) - 3))
        now = time.time()
        for rec in decs[-12:]:
            inp = rec.get("inputs") or {}
            age = max(0.0, now - float(rec.get("ts") or now))
            reason = rec.get("reason") \
                or ", ".join(rec.get("pressure") or ()) or "-"
            if rec.get("clamp"):
                reason = f"[clamp] {reason}"
            cd = rec.get("cooldown_s") or 0
            if cd and rec.get("verdict") == "hold":
                reason = f"cooldown {cd:.0f}s"
            tgt = rec.get("target")
            print(f"   {f'-{age:.0f}s':>6} "
                  f"{rec.get('world', '-'):>5} "
                  f"{rec.get('verdict', '-'):<8} "
                  f"{tgt if tgt is not None else '-':>6} "
                  f"{inp.get('queued', 0):>6} "
                  f"{inp.get('backlog', 0):>7} "
                  f"{inp.get('queue_p95_s', 0):>6.2f}s "
                  f"{rec.get('sustain_s', 0):>7.1f}s {reason}")

    def _run_on_pool(self, code: str, *, priority=None,
                     deadline_s=None):
        """Tenant-mode cell dispatch: submit to the gateway, surface
        the explicit queue-position / shed / rejected verdicts, and
        render per-rank results the way the single-kernel path does."""
        from ..gateway.client import (CellSubmitError, GatewayGone,
                                      TenantFenced)
        client = DistributedMagics._tenant
        rec = self._timeline.start(code,
                                   list(range(self._world or 0)),
                                   kind="pool")
        def _late(d: dict) -> None:
            # The interrupted cell's terminal reply arrived on this
            # still-live connection (so the gateway delivered it and
            # nothing parked): render it instead of dropping it —
            # including the no-results verdicts (worker death, request
            # timeout, shed), which are exactly the crash outcomes.
            self._render_drained_reply("", d, "finished", prefix="\n")

        data = None
        try:
            data = client.execute(
                code, priority=priority, deadline_s=deadline_s,
                timeout=None,
                on_queued=lambda n: print(
                    f"⏳ pool busy — queued at position "
                    f"{n.get('position')}"
                    + (f"\n   🚧 {n['reason']}" if n.get("reason")
                       else "")),
                on_late=_late)
        except CellSubmitError as e:
            v = e.verdict
            if v.get("status") == "shed":
                print(f"🪓 {v.get('error')}")
            else:
                print(f"🚦 {v.get('error')}")
            return None
        except GatewayGone as e:
            print(f"💀 {e}\n   The pool (or its daemon) is gone — "
                  "%dist_pool status, or %dist_attach --tenant "
                  f"{client.name} once it is back.")
            return None
        except KeyboardInterrupt:
            print("\n🛑 interrupt: the cell keeps running on the "
                  "pool; its result will print here when it finishes "
                  "(or parks for redelivery on the next attach if "
                  "this kernel exits first)")
            return None
        except Exception as e:
            print(f"❌ {type(e).__name__}: {e}")
            return None
        finally:
            self._timeline.finish(rec, None)
        # Only errors render from the reply: stdout AND the result
        # repr already arrived live as tenant-routed stream_output
        # frames (same contract as the single-kernel display path —
        # printing the reply's "output" here would double everything).
        data = data or {}
        if data.get("error"):
            # Gateway-level failure (worker death, request timeout):
            # there are no per-rank results to render the error from —
            # without this line the cell looks like a silent success.
            print(f"❌ pool: {data['error']}")
        results = data.get("results") or {}
        for r in sorted(results, key=int):
            d = results[r] or {}
            if d.get("error"):
                print(f"❌ rank {r}: {d['error']}")
        return results

    @magic_arguments()
    @argument("command", nargs="?", default="status",
              choices=["start", "status", "stop", "submit", "result",
                       "stream", "lat"])
    @argument("--spec", default=None,
              help="kernel variable holding the model-spec cell "
                   "(code that binds params/cfg in the serving "
                   "tenant's namespace on every rank)")
    @argument("--tenant", default=None,
              help="serving tenant name (default 'serve')")
    @argument("--params", default=None,
              help="params name in the serving namespace")
    @argument("--cfg", default=None,
              help="config name in the serving namespace")
    @argument("--max-batch", type=int, default=None,
              help="KV slots (continuous-batching width)")
    @argument("--max-len", type=int, default=None)
    @argument("--pad-to", type=int, default=None)
    @argument("--eos", type=int, default=None)
    @argument("--steps", type=int, default=None,
              help="decode steps per serve tick")
    @argument("--queue-depth", type=int, default=None)
    @argument("--inflight", type=int, default=None)
    @argument("--decode-ranks", type=int, default=None,
              help="decode ranks to drive (0 = every live rank; "
                   "default NBD_SERVE_DECODE_RANKS)")
    @argument("--kv-block-tokens", type=int, default=None,
              help="paged-KV block size in tokens "
                   "(default NBD_KV_BLOCK_TOKENS)")
    @argument("--kv-blocks", type=int, default=None,
              help="KV blocks per decode rank (0 = dense capacity; "
                   "default NBD_KV_BLOCKS_PER_RANK)")
    @argument("--prefill-chunk", type=int, default=None,
              help="chunked-prefill size in tokens — long prompts "
                   "interleave with decode ticks "
                   "(default NBD_PREFILL_CHUNK_TOKENS)")
    @argument("--kv-quantized", action="store_true",
              help="int8 KV cache on the decode servers")
    @argument("--prompt", default=None,
              help="comma-separated token ids (submit)")
    @argument("--max-new", type=int, default=16)
    @argument("--priority", type=int, default=None)
    @argument("--rid", default=None, help="request id (result/stream)")
    @argument("--from", dest="from_offset", type=int, default=0,
              help="resume offset (stream) — your last acked token")
    @argument("--wait", action="store_true",
              help="submit: block until the request finishes and "
                   "print its tokens")
    @argument("--last", type=int, default=0,
              help="lat: also render the stage waterfall of the "
                   "last N completed requests")
    @line_magic
    def dist_serve(self, line):
        """Serving through the gateway (tenant mode): ``%dist_serve
        start --spec SPEC_VAR`` opens a continuous-batching decode
        loop on the pool; ``submit --prompt 1,2,3 --max-new 16``
        enters a generation request (explicit accepted/shed/rejected
        verdicts, tokens stream back live); ``result``/``stream
        --from K`` poll or resume a stream; ``status``/``stop`` manage
        the plane.  Accepted requests are journaled and survive rank
        death — see README "Serving through the gateway"."""
        from ..gateway.client import CellSubmitError, GatewayGone
        client = DistributedMagics._tenant
        if client is None:
            print("❌ not attached to a gateway pool — %dist_attach "
                  "--tenant NAME first (%dist_pool start spawns one)")
            return
        args = parse_argstring(self.dist_serve, line)
        try:
            if args.command == "start":
                spec = None
                if args.spec:
                    spec = self.shell.user_ns.get(args.spec)
                    if not isinstance(spec, str):
                        print(f"❌ --spec {args.spec}: no string "
                              "variable of that name in this kernel")
                        return
                st = client.serve_start(
                    spec, tenant=args.tenant, params=args.params,
                    cfg=args.cfg, max_batch=args.max_batch,
                    max_len=args.max_len, pad_to=args.pad_to,
                    eos_id=args.eos, steps=args.steps,
                    queue_depth=args.queue_depth,
                    inflight=args.inflight,
                    decode_ranks=args.decode_ranks,
                    kv_block_tokens=args.kv_block_tokens,
                    kv_blocks=args.kv_blocks,
                    prefill_chunk=args.prefill_chunk,
                    kv_quantized=(True if args.kv_quantized
                                  else None))
                kv = st.get("kv") or {}
                print(f"🍽️ serving as tenant {st.get('tenant')!r}: "
                      f"{st.get('slots')} KV slots · max_len "
                      f"{st.get('max_len')} · decode rank "
                      f"{st.get('decode_rank')}"
                      + (f" · {kv.get('blocks_per_rank')} KV blocks"
                         f"/rank × {kv.get('block_tokens')} tok"
                         if kv else ""))
            elif args.command == "submit":
                if not args.prompt:
                    print("❌ submit needs --prompt 1,2,3")
                    return
                prompt = [int(t) for t in args.prompt.replace(",", " ")
                          .split()]
                v = client.serve_submit(prompt, args.max_new,
                                        priority=args.priority)
                rid = v.get("rid")
                pos = (f" (queued at {v['position']})"
                       if v.get("queued") else "")
                print(f"✅ accepted {rid}{pos} — tokens stream here; "
                      f"%dist_serve result --rid {rid} to poll")
                if args.wait:
                    while True:
                        r = client.serve_result(rid)
                        if r.get("done"):
                            print(f"🧾 {rid} {r.get('status')}: "
                                  f"{r.get('tokens')}")
                            break
                        time.sleep(0.3)
            elif args.command == "result":
                if not args.rid:
                    print("❌ result needs --rid rN")
                    return
                r = client.serve_result(args.rid)
                print(f"{args.rid}: {r.get('status')} "
                      f"{r.get('tokens')}"
                      + (f" — {r['error']}" if r.get("error") else ""))
            elif args.command == "stream":
                if not args.rid:
                    print("❌ stream needs --rid rN")
                    return
                r = client.serve_stream(args.rid, args.from_offset)
                print(f"{args.rid}[{r.get('offset')}:]: "
                      f"{r.get('tokens')} "
                      f"({'done' if r.get('done') else 'decoding'})")
            elif args.command == "stop":
                st = client.serve_stop()
                print(f"🛑 serving stopped: {st.get('completed')} "
                      f"completed · {st.get('tokens_total')} tokens")
            elif args.command == "lat":
                st = client.serve_status()
                if st.get("status") == "off":
                    print("(no serving plane running — %dist_serve "
                          "start)")
                    return
                self._render_serve_lat(st.get("lat") or {},
                                       last=args.last)
            else:  # status
                st = client.serve_status()
                if st.get("status") == "off":
                    print("(no serving plane running — %dist_serve "
                          "start)")
                    return
                self._render_serve_status(st)
        except CellSubmitError as e:
            v = e.verdict
            mark = "🪓" if v.get("status") == "shed" else "🚦"
            print(f"{mark} {v.get('error')}")
        except GatewayGone as e:
            print(f"💀 {e}")
        except Exception as e:
            print(f"❌ {type(e).__name__}: {e}")

    @staticmethod
    def _render_serve_status(st: dict) -> None:
        dranks = st.get("decode_ranks") or []
        rank_str = (str(st.get("decode_rank")) if len(dranks) <= 1
                    else ",".join(str(r) for r in sorted(dranks)))
        print(f"🍽️ serving[{st.get('tenant')}] · decode rank"
              f"{'s' if len(dranks) > 1 else ''} {rank_str} · KV "
              f"{st.get('decoding', 0)}/{st.get('slots')} · pending "
              f"{st.get('pending', 0)} · tokens "
              f"{st.get('tokens_total', 0)}")
        kv = st.get("kv") or {}
        if kv.get("used") or kv.get("free"):
            per_rank = " · ".join(
                f"r{r}: {v.get('placed', 0)} req, "
                f"{v.get('kv_used', 0)} blk"
                for r, v in sorted((st.get("ranks") or {}).items(),
                                   key=lambda kv_: int(kv_[0])))
            print(f"   KV blocks {kv.get('used', 0)}/"
                  f"{kv.get('used', 0) + kv.get('free', 0)} used · "
                  f"{kv.get('block_tokens')} tok/block"
                  + (f" · {per_rank}" if per_rank else ""))
            tb = kv.get("tenants") or {}
            if tb:
                print("   blocks by tenant: " + " · ".join(
                    f"{t}: {n}" for t, n in sorted(tb.items())))
        # Utilization line (ISSUE 18): recent batch fill + the
        # prefill/decode token split + per-rank fragmentation.
        util = (st.get("lat") or {}).get("util") or {}
        if util.get("count"):
            frag = " · ".join(
                f"r{r}: run {v.get('frag', '?')}"
                + (f", defer {v['pending']}"
                   if v.get("pending") else "")
                for r, v in sorted((util.get("ranks") or {}).items(),
                                   key=lambda kv_: int(kv_[0])))
            print(f"   util: batch fill {util.get('fill_mean', 0):.0%}"
                  f" mean / {util.get('fill_max', 0):.0%} max · "
                  f"prefill share "
                  f"{util.get('prefill_share', 0):.0%} of "
                  f"{util.get('prefill_toks', 0) + util.get('decode_toks', 0)}"
                  f" tok" + (f" · {frag}" if frag else ""))
        print(f"   accepted {st.get('accepted', 0)} · completed "
              f"{st.get('completed', 0)} · shed {st.get('shed', 0)} · "
              f"rejected {st.get('rejected', 0)} · replayed "
              f"{st.get('replayed', 0)} · resumed "
              f"{st.get('resumed', 0)} · failovers "
              f"{st.get('failovers', 0)} · dup-dropped "
              f"{st.get('dup_dropped', 0)}")
        slo = st.get("slo") or {}

        def _pp(block: dict, key: str) -> str:
            s = (block or {}).get(key + "_ms")
            return (f"{s['p50']:g}/{s['p99']:g}" if s else "–")

        if slo:
            print(f"   SLO p50/p99 ms · TTFT {_pp(slo, 'ttft')} · "
                  f"TPOT {_pp(slo, 'tpot')} · queue "
                  f"{_pp(slo, 'queue')} · e2e {_pp(slo, 'e2e')}")
            for t, b in sorted((slo.get("tenants") or {}).items()):
                print(f"     {t}: TTFT {_pp(b, 'ttft')} · TPOT "
                      f"{_pp(b, 'tpot')} · queue {_pp(b, 'queue')} · "
                      f"e2e {_pp(b, 'e2e')}")
        if st.get("last_error"):
            print(f"   ⚠ last driver error: {st['last_error']}")

    @staticmethod
    def _render_serve_lat(lat: dict, *, last: int = 0) -> None:
        """``%dist_serve lat``: per-stage percentile table over the
        observatory ring, plus (with ``--last N``) the ASCII stage
        waterfall of the most recent completions."""
        from ..observability import servingobs as _sobs
        summ = lat.get("summary") or {}
        if not summ.get("count"):
            print("(no completed serving requests recorded yet — "
                  "submit some, or check NBD_SERVE_LAT)")
            return
        print(f"⏱ serving stage decomposition ({summ['count']} "
              f"recorded, {summ.get('dropped', 0)} dropped):")
        print(_sobs.format_serve_stage_table(summ))
        if last:
            recs = (lat.get("records") or [])[-last:]
            if recs:
                print()
                print(_sobs.format_serve_waterfall(recs))
            else:
                print("(no per-request records in the status "
                      "payload)")

    @magic_arguments()
    @argument("--dry-run", action="store_true",
              help="list what would be swept without removing anything")
    @argument("--ttl", type=float, default=None,
              help="stale age in seconds (default: NBD_GC_TTL_S, "
                   "else 6h)")
    @argument("--root", default=None,
              help="runs root to sweep (default: <tmpdir>/nbd_runs)")
    @line_magic
    def dist_gc(self, line):
        """Sweep abandoned session run dirs: siblings whose manifest
        (or directory) is older than the TTL and whose recorded pids
        are all dead.  The current session's run dir and any dir with
        a live pid are never touched."""
        from ..resilience import session as session_mod
        args = parse_argstring(self.dist_gc, line)
        res = session_mod.gc_runs(args.root, ttl_s=args.ttl,
                                  dry_run=args.dry_run)
        verb = "would sweep" if args.dry_run else "swept"
        print(f"🧹 {verb} {len(res['swept'])} stale run dir(s) under "
              f"{res['root']} (ttl {res['ttl_s']:.0f}s) · "
              f"kept {len(res['kept'])}")
        for d in res["swept"]:
            print(f"   - {d}")
        if args.dry_run:
            # Say WHY each survivor was skipped — "my pool's run dir
            # vanished" and "why is this old dir still here" get the
            # same one-line answer.
            for d in res["kept"]:
                why = res.get("kept_why", {}).get(d)
                print(f"   = kept {d}" + (f" — {why}" if why else ""))
        for e in res["errors"]:
            print(f"   ⚠ {e}")

    # ==================================================================
    # resilience: auto-heal supervision + fault injection

    def _supervised_heal(self):
        """Heal callback the supervisor runs on worker death: replay
        the recorded %dist_init, restore the last checkpoint (when one
        was taken), hand the fresh (comm, pm) back for re-binding."""
        line = ""
        ckpt = DistributedMagics._last_ckpt_path
        if ckpt:
            # Verbatim, NOT shlex-quoted: IPython's arg_split keeps
            # quote characters inside the token (non-posix), so
            # _last_ckpt_path already holds exactly the token the user
            # typed (quotes and all, e.g. '"my ckpt"').  Re-emitting it
            # unchanged reproduces the same token — and the same rank
            # directories — through dist_heal's parse; adding a quoting
            # layer would become part of the path and miss the files.
            line = f"--restore {ckpt}"
        print("\n🛡  supervisor: auto-healing...")
        self.dist_heal(line)
        if not self._running():
            raise RuntimeError("auto-heal failed: the replayed "
                               "%dist_init did not bring the world up")
        return DistributedMagics._comm, DistributedMagics._pm

    @magic_arguments()
    @argument("command", nargs="?", default="status",
              choices=["on", "off", "status"])
    @argument("--max-restarts", type=int, default=3,
              help="restart budget inside --window seconds")
    @argument("--window", type=float, default=600.0,
              help="restart-budget window in seconds")
    @argument("--degraded-after", type=float, default=6.0,
              help="heartbeat staleness (s) before a rank is flagged "
                   "degraded (slow/wedged — NOT restarted)")
    @argument("--no-auto", action="store_true",
              help="observe and log transitions only; never heal")
    @line_magic
    def dist_supervise(self, line):
        """Auto-heal supervisor: watches process deaths + heartbeat
        staleness; on death, automatically replays %dist_init and
        restores the last %dist_checkpoint, within a capped restart
        budget.  ``%dist_supervise on [knobs] | off | status``; every
        transition also shows in %dist_status."""
        from ..resilience.supervisor import Supervisor, SupervisorPolicy
        args = parse_argstring(self.dist_supervise, line)
        sup = DistributedMagics._supervisor
        if args.command == "off":
            if sup is None:
                print("supervisor: not running")
                return
            sup.stop()
            DistributedMagics._supervisor = None
            print("✅ supervisor stopped")
            self._note_supervised(False)
            return
        if args.command == "status":
            if sup is None:
                print("supervisor: not running (%dist_supervise on)")
            else:
                print(sup.describe())
            return
        if not self._require_cluster():
            return
        if sup is not None:
            sup.stop()
        policy = SupervisorPolicy(
            degraded_after_s=args.degraded_after,
            max_restarts=args.max_restarts,
            restart_window_s=args.window,
            auto_heal=not args.no_auto)
        sup = Supervisor(policy, heal=self._supervised_heal)
        sup.attach(self._comm, self._pm)
        DistributedMagics._supervisor = sup
        self._note_supervised(True)
        print(f"✅ supervising {self._world} workers: auto-heal "
              f"{'ON' if policy.auto_heal else 'OFF'}, budget "
              f"{policy.max_restarts} restarts/{policy.restart_window_s:.0f}s, "
              f"degraded after {policy.degraded_after_s:.0f}s silence"
              + ("" if DistributedMagics._last_ckpt_path else
                 " · no checkpoint yet — heal will restore nothing "
                 "(%dist_checkpoint to protect state)"))

    @staticmethod
    def _note_supervised(on: bool) -> None:
        """Record the supervision flag in the session manifest so a
        reattaching coordinator re-arms it (durable sessions)."""
        from ..resilience import session as session_mod
        d = _knobs.get_str("NBD_RUN_DIR")
        if d:
            session_mod.update_manifest(d, supervised=on)

    @magic_arguments()
    @argument("command", nargs="?", default="status",
              choices=["on", "off", "status"])
    @argument("--seed", type=int, default=0,
              help="fault plan seed (same seed = same fault sequence)")
    @argument("--drop", type=float, default=0.0,
              help="probability a control frame is dropped")
    @argument("--delay-p", type=float, default=0.0, dest="delay_p",
              help="probability a frame is delayed by --delay-s")
    @argument("--delay-s", type=float, default=0.02, dest="delay_s")
    @argument("--duplicate", type=float, default=0.0,
              help="probability a frame is sent twice")
    @argument("--truncate", type=float, default=0.0,
              help="probability a frame is cut mid-write "
                   "(connection-fatal: exercises death handling)")
    @argument("--freeze-heartbeats", action="store_true",
              help="stop worker pings (exercises degraded detection)")
    @argument("--kill-rank", type=int, default=None,
              help="SIGKILL this rank ...")
    @argument("--kill-at", type=int, default=None,
              help="... at this received-message index (1 = next)")
    @argument("--side", default="both",
              choices=["coordinator", "worker", "both"],
              help="which send path(s) inject frame faults")
    @argument("--partition", default=None,
              help="host pair 'hostA,hostB' whose link to blackhole "
                   "(multi-host worlds; labels from the --hosts plan, "
                   "'local' = the coordinator's host)")
    @argument("--partition-after", type=float, default=0.0,
              dest="partition_after",
              help="seconds after arming before the partition opens")
    @argument("--partition-for", type=float, default=10.0,
              dest="partition_for",
              help="partition duration in seconds (0 = until "
                   "%%dist_chaos off — allowed with --side coordinator "
                   "only: a worker-side plan can't be cleared across "
                   "the link it cuts)")
    @argument("--link-latency", type=float, default=0.0,
              dest="link_latency",
              help="added per-frame delay on the --link-hosts pair "
                   "(uniformly-slow link, no partition)")
    @argument("--link-loss", type=float, default=0.0, dest="link_loss",
              help="per-frame drop probability on the --link-hosts "
                   "pair")
    @argument("--link-hosts", default=None, dest="link_hosts",
              help="host pair 'hostA,hostB' for --link-latency/"
                   "--link-loss ('*,hostB' matches any peer)")
    @argument("--corrupt", default=None,
              help="param-leaf path substring to corrupt on "
                   "--corrupt-rank at --corrupt-step ('*' = first "
                   "leaf) — the SDC drill the training-integrity "
                   "guard's audit exists to catch (ISSUE 19); fires "
                   "inside the rank's guarded train loop")
    @argument("--corrupt-rank", type=int, default=None,
              dest="corrupt_rank",
              help="rank whose params --corrupt damages")
    @argument("--corrupt-step", type=int, default=1,
              dest="corrupt_step",
              help="guarded-step index at which the corruption fires "
                   "(one-shot, >= semantics)")
    @argument("--corrupt-mode", default="bitflip",
              choices=["bitflip", "scale"], dest="corrupt_mode",
              help="bitflip: XOR seeded bits; scale: multiply a "
                   "seeded contiguous slice by --corrupt-scale")
    @argument("--corrupt-bits", type=int, default=1,
              dest="corrupt_bits",
              help="bits to flip in bitflip mode")
    @argument("--corrupt-scale", type=float, default=4.0,
              dest="corrupt_scale",
              help="multiplier for scale mode")
    @argument("--corrupt-count", type=int, default=1,
              dest="corrupt_count",
              help="elements the scale-mode slice covers")
    @line_magic
    def dist_chaos(self, line):
        """Deterministic fault injection on the live control plane:
        ``%dist_chaos on --drop 0.1 --seed 7`` / ``off`` / ``status``.
        The same knobs drive CI via the NBD_FAULT_PLAN env spec; pair
        with retries (NBD_RETRY_TIMEOUT_S) and %dist_supervise to
        rehearse preemption recovery in a notebook."""
        from ..resilience.faults import FaultPlan
        args = parse_argstring(self.dist_chaos, line)
        if not self._require_cluster():
            return
        if args.command == "off":
            self._comm.set_fault_plan(None)
            try:
                resps = self._comm.send_to_all(
                    "chaos", {"action": "clear"}, timeout=30)
                for r in sorted(resps):
                    c = resps[r].data.get("counters")
                    if c:
                        print(f"🔹 rank {r} injected: {c}")
            except Exception as e:
                print(f"⚠️ worker-side clear failed: {e}")
            print("✅ chaos off")
            return
        if args.command == "status":
            plan = self._comm.fault_plan()
            print(f"coordinator side: "
                  f"{plan.counters if plan else 'off'}")
            try:
                resps = self._comm.send_to_all(
                    "chaos", {"action": "status"}, timeout=30)
                for r in sorted(resps):
                    d = resps[r].data
                    print(f"🔹 rank {r}: {d.get('status')} "
                          f"counters={d.get('counters')} "
                          f"dedup_hits={d.get('dedup_hits')}")
            except Exception as e:
                print(f"⚠️ worker-side status failed: {e}")
            return
        # Reconfiguring while chaos is active: clear the coordinator
        # plan FIRST (like the 'off' path) so the arming broadcast
        # below doesn't have to fight the outgoing fault schedule it
        # replaces.  (The workers' old plans still apply to the acks —
        # that side is inherently chaotic until the new spec lands.)
        self._comm.set_fault_plan(None)
        spec = {"seed": args.seed, "drop": args.drop,
                "delay_p": args.delay_p, "delay_s": args.delay_s,
                "duplicate": args.duplicate, "truncate": args.truncate,
                "freeze_heartbeat": args.freeze_heartbeats}

        def _host_pair(raw: str) -> list[str] | None:
            # Non-posix arg_split keeps quote chars inside the token.
            raw = raw.strip().strip("'\"")
            pair = [h.strip() for h in raw.split(",") if h.strip()]
            if len(pair) != 2:
                print(f"❌ host pair must be 'hostA,hostB', got {raw!r}")
                return None
            return pair

        links = []
        if args.partition:
            pair = _host_pair(args.partition)
            if pair is None:
                return
            if not args.partition_for and args.side != "coordinator":
                # An open-ended partition shipped to the WORKERS can
                # never be cleared: `%dist_chaos off` cannot traverse
                # the link the plan itself blackholes, so the far side
                # would wait out its orphan TTL and self-terminate —
                # a fleet-destroying knob documented as reversible.
                print("❌ --partition-for 0 (until cleared) is "
                      "coordinator-side only — the 'off' that would "
                      "clear a worker-side plan can't cross the "
                      "partition. Use --side coordinator, or give a "
                      "finite --partition-for.")
                return
            links.append({"hosts": pair,
                          "after_s": args.partition_after,
                          "for_s": args.partition_for})
        if args.link_latency or args.link_loss:
            if not args.link_hosts:
                print("❌ --link-latency/--link-loss need --link-hosts "
                      "'hostA,hostB' to name the link")
                return
            pair = _host_pair(args.link_hosts)
            if pair is None:
                return
            links.append({"hosts": pair,
                          "latency_s": args.link_latency,
                          "loss": args.link_loss})
        if links:
            known = set((self._pm.hosts or {}).values()) | {"local", "*"}
            for l in links:
                unknown = set(l["hosts"]) - known
                if unknown:
                    print(f"⚠️ link hosts {sorted(unknown)} are not in "
                          f"this world's host map {sorted(known)} — "
                          "the spec will match nothing")
            spec["links"] = links
        corrupt = None
        if args.corrupt is not None:
            if args.corrupt_rank is None:
                print("❌ --corrupt needs --corrupt-rank to name the "
                      "rank whose params get damaged")
                return
            from ..resilience.faults import CorruptSpec
            try:
                # Build the real CorruptSpec (validation) and ship its
                # spec() — the same dict FaultPlan.from_spec rebuilds,
                # so magic and env (NBD_CORRUPT_SPEC) stay one format.
                corrupt = CorruptSpec(
                    rank=args.corrupt_rank, step=args.corrupt_step,
                    name=args.corrupt.strip().strip("'\""),
                    mode=args.corrupt_mode, bits=args.corrupt_bits,
                    scale=args.corrupt_scale,
                    count=args.corrupt_count).spec()
            except (TypeError, ValueError) as e:
                print(f"❌ bad --corrupt spec: {e}")
                return
            if args.side == "coordinator":
                print("⚠️ --corrupt ignored: corruption fires inside "
                      "the workers' guarded train loop, but --side "
                      "coordinator never ships them a plan")
                corrupt = None
        kill_armed = (args.kill_rank is not None
                      and args.side in ("worker", "both"))
        if args.kill_rank is not None and not kill_armed:
            print("⚠️ --kill-rank ignored: the kill arms on workers, "
                  "but --side coordinator never ships them a plan")
        if args.freeze_heartbeats and args.side == "coordinator":
            print("⚠️ --freeze-heartbeats ignored: only the worker "
                  "heartbeat loop consults it, but --side coordinator "
                  "never ships workers a plan")
        if args.side in ("worker", "both"):
            wspec = dict(spec)
            if kill_armed:
                wspec["kill_rank"] = args.kill_rank
                wspec["kill_at"] = args.kill_at or 1
            if corrupt is not None:
                wspec["corrupt"] = [corrupt]
            try:
                self._comm.send_to_all("chaos", {"action": "set",
                                                 "spec": wspec},
                                       timeout=30)
            except Exception as e:
                print(f"❌ arming worker-side chaos failed: {e}")
                return
        if args.side in ("coordinator", "both"):
            # Different stream than the workers' (offset seed) so the
            # two directions don't mirror each other's decisions.
            cspec = dict(spec)
            cspec["seed"] = args.seed + 1
            self._comm.set_fault_plan(FaultPlan.from_spec(cspec))
        warn = (" · ⚠ no retry policy on this manager — lost frames "
                "only surface as timeouts"
                if not self._comm.retry.enabled() else "")
        print(f"💥 chaos ON ({args.side}): {spec}"
              + (f" · kill rank {args.kill_rank} at msg "
                 f"{args.kill_at or 1}" if kill_armed else "")
              + (f" · corrupt rank {corrupt['rank']} step "
                 f"{corrupt['step']} {corrupt['mode']} "
                 f"{corrupt['name']!r}" if corrupt else "") + warn)

    @magic_arguments()
    @argument("command", nargs="?", default="status",
              choices=["status", "on", "off", "audit"])
    @line_magic
    def dist_guard(self, line):
        """Training-integrity guard control (ISSUE 19):
        ``%dist_guard`` reports each rank's TrainGuard (skips, audits,
        repairs, rollbacks, quarantine suspects); ``on``/``off``
        toggles the host-side machinery; ``audit`` forces a
        replica-consistency audit now on every rank (the fan-out is
        what keeps the audit's all-gather aligned)."""
        args = parse_argstring(self.dist_guard, line)
        if not self._require_cluster():
            return
        action = {"status": "status", "on": "on", "off": "off",
                  "audit": "audit"}[args.command]
        try:
            resps = self._comm.send_to_all("guard", {"action": action},
                                           timeout=60)
        except Exception as e:
            print(f"❌ guard {action} failed: {e}")
            return
        for r in sorted(resps):
            d = resps[r].data or {}
            if d.get("error"):
                print(f"🔹 rank {r}: ⚠ {d['error']}")
                continue
            if not d.get("active"):
                print(f"🔹 rank {r}: enabled={d.get('enabled')} · "
                      f"no live TrainGuard")
                continue
            line_out = (f"🔹 rank {r}: step {d.get('step')} · "
                        f"skips {d.get('skips')} "
                        f"(streak {d.get('skip_streak')}/"
                        f"{d.get('skip_budget')}) · "
                        f"audits {d.get('audits')} "
                        f"(last @{d.get('last_audit_step')}: "
                        f"{d.get('last_verdict')}) · "
                        f"repairs {d.get('repairs')} · "
                        f"rollbacks {d.get('rollbacks')}")
            if d.get("suspects"):
                line_out += f" · 🔶 suspects {d['suspects']}"
            print(line_out)
        if action == "audit":
            print("✅ audit fanned out to every rank")
        elif action in ("on", "off"):
            print(f"✅ guard {action}")

    # ==================================================================
    # hang watchdog + stuck-cell doctor (ISSUE 5)

    def _maybe_start_watchdog(self) -> None:
        """Arm (or, after a heal, re-bind) the hang watchdog for the
        world that just came up.  Policy comes from the NBD_HANG_* env
        knobs (NBD_HANG=0 disables; %dist_watchdog reconfigures)."""
        from ..resilience.watchdog import HangPolicy, HangWatchdog
        wd = DistributedMagics._watchdog
        if wd is not None:
            # Heal path: the surviving watchdog re-binds to the fresh
            # world, keeping any %dist_watchdog-customized policy —
            # UNCONDITIONALLY, before any env parsing: an env that
            # fails the strict parse (or NBD_HANG flipped to 0
            # mid-session) must not leave this instance silently
            # watching the torn-down world's comm/pm forever.
            wd.attach(self._comm, self._pm)
            return
        try:
            policy = HangPolicy.from_env()
        except ValueError as e:
            print(f"⚠️ hang watchdog NOT started: {e}")
            return
        if not policy.enabled:
            return
        wd = HangWatchdog(policy, heal=self._supervised_heal)
        wd.attach(self._comm, self._pm)
        DistributedMagics._watchdog = wd

    @staticmethod
    def _hang_piggyback_off() -> bool:
        """Workers gate the heartbeat collective-position piggyback on
        NBD_HANG at SPAWN time: with it off, a coordinator-side
        watchdog can only ever see coarse busy state (stall detection;
        no skew, no --deadline)."""
        return not _knobs.get_bool("NBD_HANG", True)

    @magic_arguments()
    @argument("command", nargs="?", default="status",
              choices=["on", "off", "status"])
    @argument("--skew", type=float, default=None,
              help="seconds a rank may lag its peers' collective "
                   "position before the cell is flagged HUNG")
    @argument("--stall", type=float, default=None,
              help="seconds a rank may stay busy with zero collective "
                   "progress before the cell is flagged HUNG")
    @argument("--poll", type=float, default=None,
              help="watchdog poll cadence in seconds")
    @argument("--grace", type=float, default=None,
              help="pause between escalation ladder steps")
    @argument("--escalate", default=None,
              help="comma-separated ladder from: warn,dump,interrupt,"
                   "heal (default warn,dump)")
    @line_magic
    def dist_watchdog(self, line):
        """Collective hang watchdog: compares every rank's position in
        the collective stream (piggybacked on heartbeats) and flags a
        cell HUNG — cross-rank skew, absolute stall, or a blown
        ``%%distributed --deadline`` — distinct from merely slow, then
        walks the escalation ladder: warn → stack-dump (SIGUSR1) →
        interrupt → heal.  ``%dist_watchdog on [knobs] | off |
        status``; auto-armed at %dist_init unless NBD_HANG=0."""
        from ..resilience.watchdog import (HangPolicy, HangWatchdog,
                                           parse_ladder)
        args = parse_argstring(self.dist_watchdog, line)
        wd = DistributedMagics._watchdog
        if args.command != "on" and any(
                v is not None for v in (args.skew, args.stall,
                                        args.poll, args.grace,
                                        args.escalate)):
            # Knobs without 'on' would be parsed and silently dropped
            # — the user would believe the policy changed.
            print("❌ policy flags require the 'on' subcommand "
                  "(%dist_watchdog on --stall ...); nothing changed")
            return
        if args.command == "off":
            if wd is None:
                print("hang watchdog: not running")
                return
            wd.stop()
            DistributedMagics._watchdog = None
            print("✅ hang watchdog stopped")
            return
        if args.command == "status":
            if wd is None:
                print("hang watchdog: not running (%dist_watchdog on)")
            else:
                print(wd.describe())
            return
        if not self._require_cluster():
            return
        # Lenient env parse: a typo'd NBD_HANG_ESCALATE must not wedge
        # the one command that can fix it.
        base = (wd.policy if wd is not None
                else HangPolicy.from_env_lenient())
        try:
            policy = HangPolicy(
                enabled=True,
                poll_s=args.poll if args.poll is not None
                else base.poll_s,
                skew_s=args.skew if args.skew is not None
                else base.skew_s,
                stall_s=args.stall if args.stall is not None
                else base.stall_s,
                grace_s=args.grace if args.grace is not None
                else base.grace_s,
                escalate=parse_ladder(args.escalate)
                if args.escalate is not None else base.escalate)
        except ValueError as e:
            print(f"❌ {e}")
            return
        if wd is not None:
            # Reconfigure the LIVE instance: a policy change mid-hang
            # must not zero ladder progress, counters, or history (a
            # replaced watchdog would re-run warn/dump from step 0 on
            # the still-hung cell).
            wd.set_policy(policy)
        else:
            wd = HangWatchdog(policy, heal=self._supervised_heal)
            wd.attach(self._comm, self._pm)
            DistributedMagics._watchdog = wd
        print(f"✅ hang watchdog ON: {policy.describe()}")
        if self._hang_piggyback_off():
            print("   ⚠ NBD_HANG=0: workers spawned with it send no "
                  "collective positions — skew/--deadline detection "
                  "is unavailable (coarse busy-stall only); unset "
                  "NBD_HANG and re-%dist_init for full detection")
        if "heal" in policy.escalate \
                and not DistributedMagics._last_ckpt_path:
            print("   · no checkpoint yet — a heal step would restore "
                  "nothing (%dist_checkpoint to protect state)")

    @magic_arguments()
    @argument("--save", default=None,
              help="also write the report to this path")
    @argument("--no-stacks", action="store_true",
              help="skip the SIGUSR1 stack dump (read-only diagnosis)")
    @line_magic
    def dist_doctor(self, line):
        """The stuck-cell doctor: one report naming the lagging
        rank(s) and the divergence point — per-rank collective
        positions and busy ages, the skew table, in-flight requests,
        watchdog verdicts, freshly dumped all-thread stacks (SIGUSR1 →
        faulthandler, per-rank files under the run dir), and each
        flight ring's last events.  Works mid-hang: nothing here goes
        through the workers' (possibly wedged) serial request
        loops."""
        if self._pm is None or self._comm is None:
            print("❌ No cluster. %dist_init to start one.")
            return
        from ..resilience.watchdog import hang_report
        args = parse_argstring(self.dist_doctor, line)
        ex = DistributedMagics._async_exec
        report = hang_report(self._comm, self._pm,
                             DistributedMagics._watchdog,
                             dump_stacks=not args.no_stacks,
                             async_window=(ex.snapshot()
                                           if ex is not None else None))
        print(report)
        if args.save:
            try:
                with open(args.save, "w") as f:
                    f.write(report + "\n")
                print(f"✅ report → {args.save}")
            except OSError as e:
                print(f"❌ could not write {args.save}: {e}")

    # ==================================================================
    # pre-dispatch cell vetting (ISSUE 7)

    @classmethod
    def _lint_mode_now(cls) -> str:
        """The effective vetting mode: the %dist_lint-pinned value,
        else the NBD_LINT env knob, else ``warn``."""
        if cls._lint_mode is not None:
            return cls._lint_mode
        mode = (_knobs.get_str("NBD_LINT", "warn") or "warn").lower()
        return mode if mode in ("warn", "strict", "off") else "warn"

    @staticmethod
    def _note_effects(code: str) -> None:
        """Record a dispatched cell's effect footprint in the
        preflight store (ISSUE 9): the substrate of the session
        dependency DAG ``%dist_lint deps`` renders and the async
        in-flight window will consult.  Best effort — effect
        inference must never break dispatch."""
        try:
            from ..analysis import infer_effects, preflight
            from ..runtime.collective_guard import cell_hash
            preflight.note_effects(cell_hash(code),
                                   infer_effects(code))
        except Exception:
            pass

    def _vet_cell(self, code: str, ranks: list[int], *,
                  strict: bool = False) -> bool:
        """Statically vet a cell BEFORE ``send_to_ranks`` (the ISSUE 7
        tentpole): rank-conditional collectives, subset-rankspec
        collectives, rank-conditional early exits, blocking host
        syncs in loops, namespace shadowing.  Findings print as
        inline annotations; error-severity findings block dispatch
        only under ``--strict`` / ``%dist_lint strict``.  Returns
        False when the cell must not ship.  Unparseable source NEVER
        blocks — it degrades to the legacy regex warning for subset
        cells and dispatches.  Every cell that WILL dispatch also gets
        its effect footprint recorded (``_note_effects``); ``off``
        mode skips analysis entirely, effect tracking included."""
        mode = self._lint_mode_now()
        if mode == "off" and not strict:
            return True  # an explicit per-cell --strict still vets
        try:
            from .. import analysis
            res = analysis.vet_cell(code, ranks=ranks,
                                    world=self._world)
        except Exception:
            return True  # the analyzer must never break dispatch
        if not res.parsed:
            if len(ranks) < self._world \
                    and _COLLECTIVE_TOKENS.search(code):
                print(f"⚠️ Cell names a collective but targets only "
                      f"ranks {ranks} of {self._world}. A collective "
                      "run by a subset deadlocks the mesh; %sync can "
                      "realign after errors.")
            # Unparseable cells still dispatch — their footprint is
            # OPAQUE, which poisons the dependency DAG on purpose.
            self._note_effects(code)
            return True
        if not res.findings:
            self._note_effects(code)
            return True
        from ..analysis import preflight
        from ..observability import flightrec
        from ..observability import metrics as obs_metrics
        from ..runtime.collective_guard import cell_hash
        sha = cell_hash(code)
        reg = obs_metrics.registry()
        for f in res.findings:
            reg.counter("nbd_lint_findings_total",
                        "pre-dispatch cell-vetting findings",
                        {"rule": f.rule}).inc()
            flightrec.record("lint_finding", rule=f.rule,
                             severity=f.severity, line=f.line,
                             cell=sha)
            print(f.render())
        errors = res.errors
        if errors and (strict or mode == "strict"):
            print(f"⛔ cell NOT dispatched: {len(errors)} error-"
                  f"severity finding(s) under strict vetting — fix "
                  f"the cell, or loosen with %dist_lint warn (or "
                  f"drop --strict) to dispatch anyway")
            return False
        # Dispatched despite findings: remember them so a later hang
        # verdict / %dist_doctor / postmortem on this cell cites the
        # pre-flight warning (resilience/watchdog.py).
        preflight.note(sha, res.findings)
        self._note_effects(code)
        return True

    @staticmethod
    def _render_effects_entry(e: dict, *, verbose: bool) -> str:
        """One dispatched cell's footprint as a compact line."""
        col = e.get("collective_verdict", "?")
        n = len(e.get("collectives") or ())
        if col == "exact":
            col = f"exact({n})"
        flags = []
        if e.get("opaque"):
            flags.append("OPAQUE")
        if e.get("host_sync_in_loop"):
            flags.append("host-sync-loop")
        elif e.get("host_sync"):
            flags.append("host-sync")
        if e.get("pure"):
            flags.append("pure")

        def names(key, cap=6):
            vals = list(e.get(key) or ())
            if not vals:
                return "∅"
            shown = ", ".join(vals[:cap])
            extra = len(vals) - cap
            return shown + (f" +{extra}" if extra > 0 else "")

        line = (f"#{e['seq']} {e['sha'][:8]} · collectives={col}"
                + (f" [{' '.join(flags)}]" if flags else ""))
        if verbose:
            line += (f"\n      writes {names('writes')} · mutates "
                     f"{names('mutates')} · dels {names('deletes')}"
                     f"\n      reads  {names('reads', 8)}")
            sites = e.get("collectives") or ()
            if sites:
                line += "\n      order  " + " → ".join(
                    f"{s['op']}@L{s['line']}"
                    + (f"(via {s['via']})" if s.get("via") else "")
                    for s in sites[:8])
            for t in (e.get("taints") or ())[:3]:
                line += f"\n      ? {t}"
            for r in (e.get("opaque_reasons") or ())[:3]:
                line += f"\n      ! {r}"
        return line

    @magic_arguments()
    @argument("command", nargs="?", default="status",
              choices=["strict", "warn", "off", "status", "deps",
                       "effects", "self"])
    @argument("--dot", action="store_true",
              help="with `deps`: print the dependency DAG as "
                   "Graphviz dot instead of text (paste into any dot "
                   "renderer; `nbd-lint --deps-dot` is the file-mode "
                   "analog)")
    @line_magic
    def dist_lint(self, line):
        """Pre-dispatch SPMD cell vetting: every ``%%distributed`` /
        ``%%rank`` / auto-distributed cell is AST-analyzed
        coordinator-side before dispatch — rank-conditional
        collectives (``if rank == 0: all_reduce(...)`` deadlocks the
        mesh), collectives in subset-``--ranks`` cells,
        rank-conditional ``return``/``break``/``raise`` that desync
        the collective sequence, blocking host syncs inside loops
        (``.item()``, ``device_get``, printing device values), and
        shadowed framework names.  ``%dist_lint warn`` (default)
        annotates, ``strict`` blocks error-severity cells,
        ``off`` disables; the NBD_LINT env knob sets the session
        default, and ``%%distributed --strict`` arms strict for one
        cell.  Never blocks on unparseable source.

        ``%dist_lint effects`` lists each dispatched cell's inferred
        effect footprint (reads/writes, ordered collective sites,
        opacity); ``%dist_lint deps`` renders the session cell
        dependency DAG (RAW/WAR/WAW hazard edges) — the substrate for
        effects-aware pool scheduling and async dispatch; ``--dot``
        emits it as Graphviz dot for visual audit.

        ``%dist_lint self`` runs the framework's own ten self-lint
        passes over the checkout — the CLI ``nbd-lint --self``
        in-notebook: env-knob / codec-header / protocol registries,
        thread-shared-state, the lock-discipline passes (lock-order,
        blocking-under-lock, callback-under-lock), and the lifecycle
        passes (resource-leak, bracket-discipline,
        shutdown-completeness) — and reports per-pass counts."""
        args = parse_argstring(self.dist_lint, line)
        if args.command == "self":
            from ..analysis.cli import _repo_root
            from ..analysis.selfcheck import run_self_lint
            root = _repo_root(None)
            if root is None:
                print("🔎 %dist_lint self needs a repo checkout "
                      "(README.md next to nbdistributed_tpu/) — from "
                      "an installed wheel run `nbd-lint --self "
                      "--root <checkout>` instead")
                return
            results = run_self_lint(root)
            total = sum(len(v) for v in results.values())
            print(f"🔎 framework self-lint — {len(results)} passes "
                  f"over {root}:")
            for name, findings in results.items():
                status = ("clean" if not findings
                          else f"{len(findings)} finding(s)")
                print(f"   · {name}: {status}")
                for f in findings[:5]:
                    print(f"     {f.render()}")
                if len(findings) > 5:
                    print(f"     … +{len(findings) - 5} more "
                          f"(nbd-lint --self for the full list)")
            print("   all passes clean ✅" if not total
                  else f"   {total} finding(s) — CI's static-analysis "
                       f"gate fails on these")
            return
        if args.command in ("deps", "effects"):
            from ..analysis import preflight
            entries = preflight.effects_log()
            if not entries:
                print("🔎 no dispatched cells recorded this session "
                      "(effect footprints are captured at dispatch; "
                      "%dist_lint off disables them)")
                return
            if args.command == "effects":
                print(f"🔎 effect footprints — {len(entries)} "
                      f"dispatched cell(s), oldest first:")
                for e in entries:
                    print("  " + self._render_effects_entry(
                        e, verbose=True))
                return
            dag = preflight.deps_dag()
            if args.dot:
                print(preflight.dag_to_dot(dag))
                return
            by_dst: dict = {}
            for edge in dag["edges"]:
                by_dst.setdefault(edge["dst"], []).append(edge)
            print(f"🔎 cell dependency DAG — {len(dag['nodes'])} "
                  f"cell(s), {len(dag['edges'])} write→read edge(s):")
            for e in dag["nodes"]:
                print("  " + self._render_effects_entry(
                    e, verbose=False))
                for edge in by_dst.get(e["seq"], ()):
                    names = ", ".join(edge["names"][:6])
                    extra = len(edge["names"]) - 6
                    if extra > 0:
                        names += f" +{extra}"
                    print(f"      ← #{edge['src']} via {{{names}}}")
            if not dag["edges"]:
                print("   (no edges: every recorded cell is "
                      "independent — safe to overlap)")
            return
        if args.command == "status":
            mode = self._lint_mode_now()
            src = ("pinned by %dist_lint"
                   if DistributedMagics._lint_mode is not None
                   else "from NBD_LINT / default")
            print(f"🔎 cell vetting: {mode} ({src})")
            from ..observability import metrics as obs_metrics
            counters = obs_metrics.registry().to_json()["counters"]
            found = {k: v for k, v in counters.items()
                     if k.startswith("nbd_lint_findings_total")}
            if found:
                print("   findings this session:")
                for k in sorted(found):
                    rule = k.split('rule="')[-1].rstrip('"}')
                    print(f"   · {rule}: {found[k]:.0f}")
            else:
                print("   no findings this session")
            return
        DistributedMagics._lint_mode = args.command
        verb = {"strict": "ON (strict — error-severity cells are "
                          "blocked pre-dispatch)",
                "warn": "ON (annotate only)",
                "off": "OFF"}[args.command]
        print(f"✅ cell vetting {verb}")

    # ==================================================================
    # async pipelined execution (ISSUE 14)

    @classmethod
    def _async_window_armed(cls) -> bool:
        """Session-wide async mode: NBD_ASYNC_WINDOW > 0 makes every
        %%distributed cell stream through the window by default
        (--sync opts out per cell)."""
        return _knobs.get_int("NBD_ASYNC_WINDOW", 0) > 0

    def _ensure_async_executor(self):
        """The lazily-built AsyncExecutor over the live comm.  One per
        fleet: reset_class_state/shutdown_all drop it with the comm."""
        cls = DistributedMagics
        ex = cls._async_exec
        if ex is not None and ex.comm is self._comm:
            return ex
        from ..messaging.pipeline import AsyncExecutor
        ex = AsyncExecutor(
            self._comm,
            on_hold=lambda reason: print(f"⧗ held: {reason} — "
                                         "waiting for the window"),
            on_result=self._async_cell_done)
        cls._async_exec = ex
        return ex

    @staticmethod
    def _async_cell_done(cell) -> None:
        """Executor completion hook (IO thread): surface an async
        cell's ERROR the moment its reply lands — stdout already
        streamed live; a quiet success needs no echo, a silent error
        would vanish."""
        fut = cell.future
        if fut.state == "error" and not fut.consumed:
            fut.consumed = True
            print(f"\n✗ async cell #{fut.seq}: {fut.error}")

    def _warn_unconsumed_async(self) -> None:
        """The next-cell warn pass (the proxy-future consumption
        contract): errored futures nobody inspected are announced
        once instead of vanishing."""
        ex = DistributedMagics._async_exec
        if ex is None:
            return
        for fut in ex.unconsumed_errors():
            print(f"⚠️ async cell #{fut.seq} errored un-inspected: "
                  f"{fut.error} (.result() on its handle re-raises)")

    def _drain_async(self, why: str,
                     timeout: float | None = None) -> list:
        """Drain the in-flight window (the sync points: a synchronous
        cell, %sync, %dist_wait, shutdown).  Errors surface here —
        rendered once, futures marked consumed."""
        ex = DistributedMagics._async_exec
        if ex is None or ex.depth == 0:
            return []
        depth = ex.depth
        print(f"⧗ draining async window ({depth} in flight) — {why}")
        try:
            futures = ex.drain(timeout)
        except KeyboardInterrupt:
            print("🛑 drain interrupted — cells keep running on the "
                  "workers; %dist_wait to re-drain")
            return []
        for fut in futures:
            if fut.state == "error" and not fut.consumed:
                fut.consumed = True
                print(f"✗ async cell #{fut.seq}: {fut.error}")
        return futures

    @magic_arguments()
    @argument("--timeout", type=float, default=None,
              help="bound the drain in seconds (cells still pending "
                   "at the deadline stay in flight)")
    @line_magic
    def dist_wait(self, line):
        """Drain the async in-flight window (ISSUE 14): block until
        every ``%%distributed --async`` / ``NBD_ASYNC_WINDOW``-
        streamed cell has completed, render any errors, and refresh
        the IDE proxies.  The explicit sync point of async pipelined
        execution — a synchronous cell or ``%sync`` drains
        implicitly."""
        args = parse_argstring(self.dist_wait, line)
        ex = DistributedMagics._async_exec
        if ex is None or ex.depth == 0:
            snap = ex.snapshot() if ex is not None else {}
            done = snap.get("completed", 0)
            print("✅ async window empty"
                  + (f" · {done} cell(s) completed this session, "
                     f"{snap.get('errored', 0)} errored"
                     if done else ""))
            return
        futures = self._drain_async("%dist_wait", args.timeout)
        still = [f for f in futures if not f.done]
        ok = sum(1 for f in futures if f.state == "done")
        err = sum(1 for f in futures if f.state == "error")
        print(f"✅ drained {ok} cell(s)"
              + (f" · {err} errored" if err else "")
              + (f" · {len(still)} still in flight (--timeout hit)"
                 if still else ""))
        if not still and self._running():
            self._sync_ide_quietly()

    def _run_async(self, code: str, ranks: list[int], *,
                   deadline_s=None, repeat=None, until=None,
                   vet_s=None):
        """Submit one cell through the async window and return its
        CellFuture (the cell magic's return value — IPython's display
        hook echoes the pending handle; the executor resolves it when
        the replies land)."""
        from ..runtime.collective_guard import cell_hash
        from ..analysis import preflight
        sha = cell_hash(code)
        # The entry _note_effects just recorded for THIS cell — the
        # admission gate's footprint (None → treated opaque, which
        # drains the window and serializes; %dist_lint off lands here
        # on purpose: no proofs, no overlap).
        entry = preflight.effects_for(sha)
        ex = self._ensure_async_executor()
        # The timeline row records the SUBMISSION (per-rank durations
        # live on the future; the row closes immediately — an async
        # cell must not look like a still-running cell forever).
        rec = self._timeline.start(code, ranks, kind="async")
        self._timeline.finish(rec, None)
        try:
            fut = ex.submit_cell(
                code, ranks, entry=entry, sha=sha,
                deadline_s=deadline_s, repeat=repeat, until=until,
                vet_s=vet_s)
        except KeyboardInterrupt:
            print("🛑 interrupted while held at the window gate — "
                  "nothing was submitted (%dist_wait drains the "
                  "window)")
            return None
        except Exception as e:
            print(f"❌ async submit failed: {type(e).__name__}: {e}")
            return None
        snap = ex.snapshot()
        print(f"⧗ async cell #{fut.seq} streamed to ranks {ranks} "
              f"(window {snap['depth']}/{snap['window']}"
              + (f", collective stream held by "
                 f"#{snap['collective_holder']}"
                 if snap.get("collective_holder") is not None else "")
              + ") — %dist_wait drains")
        return fut

    # ==================================================================
    # execution magics

    @magic_arguments()
    @argument("--strict", action="store_true",
              help="block dispatch when the pre-flight analyzer finds "
                   "an error-severity hazard (rank-conditional "
                   "collective, subset collective, desyncing exit)")
    @argument("--deadline", type=float, default=None,
              help="per-cell budget in seconds: the hang watchdog "
                   "escalates (warn → dump → interrupt → heal, per "
                   "its ladder) when any rank is still busy past it")
    @argument("--priority", type=int, default=None,
              help="tenant mode only: this cell's pool-scheduling "
                   "priority (higher dispatches first in fair mode; "
                   "default: the tenant's attach-time priority)")
    @argument("--async", dest="use_async", action="store_true",
              help="stream this cell through the async in-flight "
                   "window and return a pending CellFuture instead "
                   "of blocking (admission gated by the effects/deps "
                   "DAG; %%dist_wait drains)")
    @argument("--sync", dest="use_sync", action="store_true",
              help="force synchronous dispatch for this cell (drains "
                   "the async window first) even when "
                   "NBD_ASYNC_WINDOW arms async mode session-wide")
    @argument("--repeat", type=int, default=None, metavar="K",
              help="worker-side step loop: compile the cell once and "
                   "run it K times in ONE dispatch — per-step "
                   "progress (step, last scalar, steps/s) rides the "
                   "heartbeats; a redelivered request never re-runs "
                   "steps")
    @argument("--until", default=None, metavar="EXPR",
              help="with --repeat: stop early when this expression "
                   "is truthy in the worker namespace (evaluated "
                   "after each step), e.g. --until 'loss < 0.1'")
    @cell_magic
    def distributed(self, line, cell):
        """Run the cell on every worker (reference: magic.py:1042-1129).
        ``%%distributed --deadline 60`` arms a per-cell budget the
        hang watchdog enforces through its escalation ladder.
        ``--async`` streams the cell through the bounded in-flight
        window (ISSUE 14) and returns a pending future; ``--repeat K
        [--until EXPR]`` compiles once and loops worker-side.  In
        tenant mode (``%dist_attach --tenant``) the cell is submitted
        to the gateway pool instead — same vetting, explicit
        queued/shed verdicts, per-tenant isolated namespace."""
        self._warn_unconsumed_async()
        if DistributedMagics._tenant is not None:
            try:
                args = parse_argstring(self.distributed, line)
            except Exception as e:
                print(f"❌ {e}")
                return
            if args.use_async or args.repeat is not None:
                print("⚠️ --async/--repeat are single-kernel options "
                      "(the pool's scheduler owns tenant-mode "
                      "overlap) — dispatching synchronously")
            if not self._vet_cell(cell, list(range(self._world)),
                                  strict=args.strict):
                return
            self._run_on_pool(cell, priority=args.priority,
                              deadline_s=args.deadline)
            return
        if not self._require_cluster():
            return
        try:
            args = parse_argstring(self.distributed, line)
        except Exception as e:
            print(f"❌ {e}")
            return
        if args.priority is not None:
            print("⚠️ --priority only applies in tenant (pool) mode "
                  "— ignored")
        if args.use_async and args.use_sync:
            print("❌ choose one of --async / --sync")
            return
        if args.until is not None:
            # IPython's non-posix arg_split keeps quote chars inside
            # the token: without the strip, --until 'loss < 0.1'
            # evaluates a quoted STRING — always truthy — and stops
            # after one step.  Strip ONE matching outer pair only
            # (the expression may legitimately end in a quote:
            # --until "phase == 'done'").
            u = args.until.strip()
            if len(u) >= 2 and u[0] == u[-1] and u[0] in "'\"":
                u = u[1:-1]
            args.until = u
        if args.until and args.repeat is None:
            print("❌ --until requires --repeat K")
            return
        if args.repeat is not None and args.repeat < 1:
            print("❌ --repeat needs K >= 1")
            return
        if args.deadline is not None:
            if DistributedMagics._watchdog is None:
                print("⚠️ --deadline set but the hang watchdog is off "
                      "(%dist_watchdog on) — the budget will not be "
                      "enforced")
            elif self._hang_piggyback_off():
                print("⚠️ --deadline set but workers were spawned "
                      "with NBD_HANG=0 (no heartbeat piggyback) — "
                      "the budget will not be enforced")
        t_vet = time.monotonic()
        if not self._vet_cell(cell, list(range(self._world)),
                              strict=args.strict):
            return
        use_async = (args.use_async
                     or (self._async_window_armed()
                         and not args.use_sync))
        if use_async:
            # The window path: return the pending future — IPython's
            # display hook echoes it; the executor resolves it when
            # the replies land.  Its admission gate consults the
            # footprint _vet_cell just recorded.
            return self._run_async(
                cell, list(range(self._world)),
                deadline_s=args.deadline, repeat=args.repeat,
                until=args.until, vet_s=time.monotonic() - t_vet)
        result = self._run_on_ranks(cell, list(range(self._world)),
                                    kind="distributed",
                                    deadline_s=args.deadline,
                                    vet_s=time.monotonic() - t_vet,
                                    repeat=args.repeat,
                                    until=args.until)
        if result is not None:
            self._sync_ide_quietly()

    @cell_magic
    def rank(self, line, cell):
        """Run the cell on selected ranks: ``%%rank [0,2]`` / ``[0-2]``
        (reference: magic.py:1476-1565)."""
        self._warn_unconsumed_async()
        if not self._require_cluster():
            return
        try:
            ranks = rankspec.parse_ranks(line, self._world)
        except rankspec.RankSpecError as e:
            print(f"❌ {e}")
            return
        # Pre-dispatch vetting with the SUBSET context armed: the
        # analyzer upgrades the old regex warning to real findings
        # (calls = error under strict, bare references = warning) and
        # falls back to the regex only for unparseable source.
        t_vet = time.monotonic()
        if not self._vet_cell(cell, ranks):
            return
        self._run_on_ranks(cell, ranks, kind="rank",
                           vet_s=time.monotonic() - t_vet)

    @magic_arguments()
    @argument("--ranks", default=None,
              help="target spec like [0,2]; default all")
    @line_magic
    def dist_interrupt(self, line):
        """SIGINT worker process(es) so the running cell aborts with a
        KeyboardInterrupt error and the workers stay alive.

        While a distributed cell is executing, the kernel itself is
        busy — use Jupyter's interrupt button (Ctrl-C) instead, which
        this framework forwards to the workers automatically; this
        magic is for targeted/after-the-fact signaling.  Limits: a cell
        blocked *inside* a native collective/compile aborts only when
        that native call returns, and interrupting a subset of ranks
        mid-collective leaves the others blocked (run a full interrupt,
        then %sync).  The reference's only remedy for a stuck cell is
        destroying the cluster (%dist_reset)."""
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_interrupt, line)
        ranks = None
        if args.ranks:
            try:
                ranks = rankspec.parse_ranks(args.ranks, self._world)
            except rankspec.RankSpecError as e:
                print(f"❌ {e}")
                return
        signaled = self._pm.interrupt(ranks)
        print(f"🛑 interrupt sent to ranks {signaled}")
        if ranks is not None and len(signaled) < self._world:
            print("⚠️ subset interrupt: if the cell was running a "
                  "collective, the un-signaled ranks stay blocked in "
                  "it — interrupt all ranks, then %sync.")
        # SIGINT delivery is asynchronous: a signal aimed at an *idle*
        # worker can land inside the NEXT cell and abort it instead.
        # Absorb that race with a sacrificial probe cell — it either
        # returns normally (signal was consumed by the idle recv) or
        # eats the late KeyboardInterrupt itself; both outcomes leave
        # the worker clean for the user's next real cell.  Short
        # timeout: a worker stuck in a native call can't serve the
        # probe, and the magic must not stall the kernel.
        try:
            self._comm.send_to_ranks(signaled, "execute",
                                     "'interrupt-probe'", timeout=2)
        except Exception:
            pass  # a busy/aborting worker answers the probe late; fine

    @line_magic
    def sync(self, line):
        """Barrier across all workers (reference: magic.py:1567-1587).
        Also a sync point for the async window: in-flight streamed
        cells drain (and surface their errors) before the barrier."""
        if not self._require_cluster():
            return
        self._drain_async("%sync barrier")
        try:
            self._comm.send_to_all("sync", timeout=120)
            print(f"✅ All {self._world} workers synchronized")
        except Exception as e:
            print(f"❌ sync failed: {e}")

    # ==================================================================
    # auto-distributed mode (input transformer)

    def _auto_transformer(self, lines: list[str]) -> list[str]:
        """Prepend %%distributed to plain cells (reference:
        magic.py:709-741).  Skips magics, shell escapes, help syntax and
        comment-only cells."""
        if not DistributedMagics._auto_active or not lines:
            return lines
        stripped = [ln.strip() for ln in lines]
        first = next((s for s in stripped if s), "")
        if not first:
            return lines
        if first.startswith(("%", "!", "?")) or first.endswith("?"):
            return lines
        if all(s.startswith("#") or not s for s in stripped):
            return lines
        return ["%%distributed\n"] + lines

    def _enable_auto_mode(self) -> None:
        shell = self.shell
        if self._auto_transformer not in shell.input_transformers_cleanup:
            shell.input_transformers_cleanup.append(self._auto_transformer)
        DistributedMagics._auto_active = True

    def _disable_auto_mode(self) -> None:
        shell = self.shell
        try:
            shell.input_transformers_cleanup.remove(self._auto_transformer)
        except ValueError:
            pass
        DistributedMagics._auto_active = False

    @magic_arguments()
    @argument("-e", "--enable", action="store_true")
    @argument("-d", "--disable", action="store_true")
    @line_magic
    def dist_mode(self, line):
        """Toggle auto-distribution of plain cells
        (reference: magic.py:1626-1677)."""
        args = parse_argstring(self.dist_mode, line)
        if args.enable and args.disable:
            print("❌ choose one of -e / -d")
            return
        if args.enable:
            if not self._require_cluster():
                return
            self._enable_auto_mode()
            print("✅ Auto-distributed mode ON — plain cells run on all "
                  "workers")
        elif args.disable:
            self._disable_auto_mode()
            print("✅ Auto-distributed mode OFF — cells run locally; use "
                  "%%distributed / %%rank explicitly")
        else:
            state = "ON" if DistributedMagics._auto_active else "OFF"
            print(f"Auto-distributed mode: {state}")

    # ==================================================================
    # status / debug

    @line_magic
    def dist_status(self, line):
        """Cluster tree report (reference: magic.py:743-809).  In
        tenant mode this is the POOL view: scheduler queue, tenant
        table (this tenant starred), tenant-attributed busy ranks."""
        if DistributedMagics._tenant is not None:
            client = DistributedMagics._tenant
            info = DistributedMagics._pool_info or {}
            print(f"🌐 tenant {client.name!r} @ pool "
                  f"{info.get('run_dir', '?')} · epoch "
                  f"{client.epoch} · "
                  f"{'alive' if client.alive else '💀 gateway gone'}")
            try:
                st = client.pool_status()
            except Exception as e:
                print(f"   (pool status unavailable: {e})")
                return
            self._render_pool_status(st, info.get("run_dir"))
            return
        if self._pm is None:
            print("❌ No cluster. %dist_init to start one.")
            return
        proc_status = self._pm.get_status()
        live: dict[int, dict] = {}
        alive = self._pm.alive_ranks()
        # Heartbeats carry the worker loop's busy state; a rank busy in
        # a long cell cannot answer get_status (the request loop is
        # serial), so probing it would stall this magic for the full
        # timeout — skip busy ranks and report what the pings say.
        busy: dict[int, dict] = {}
        if self._comm is not None:
            from ..runtime.worker import HEARTBEAT_INTERVAL_S
            now = time.time()
            for r in alive:
                ping = self._comm.last_ping(r)
                if (ping is not None and ping[1].get("busy_s") is not None
                        and now - ping[0] < 3 * HEARTBEAT_INTERVAL_S):
                    busy[r] = {"type": ping[1].get("busy_type"),
                               "s": ping[1]["busy_s"] + (now - ping[0])}
                    col = ping[1].get("col")
                    # Seconds since the rank last ENTERED a collective
                    # — a long cell actively advancing through
                    # collectives is busy, never stalled.
                    busy[r]["col_age"] = (
                        (col.get("age") or 0) + (now - ping[0])
                        if col else None)
        idle = [r for r in alive if r not in busy]
        if self._comm is not None and idle:
            try:
                resp = self._comm.send_to_ranks(idle, "get_status",
                                                timeout=5)
                live = {r: m.data for r, m in resp.items()}
            except Exception:
                pass  # degrade to process-level info (reference does too)
        mode = "ON" if self._auto_active else "OFF"
        print(f"🌐 Cluster: {self._world} workers · backend="
              f"{self._pm.backend} · auto-mode {mode}")
        # Durable-session header: run dir, token fingerprint, epoch,
        # and whether this kernel spawned the fleet (orphan-capable:
        # it survives us) or adopted one (%dist_attach).
        if self._comm is not None and getattr(self._comm,
                                              "session_token", None):
            from ..resilience import session as session_mod
            ttl = _knobs.get_raw("NBD_ORPHAN_TTL_S") or "600"
            print(f"🔑 session: run {_knobs.get_str('NBD_RUN_DIR', '-')}"
                  f" · token {session_mod.token_fingerprint(self._comm.session_token)}"
                  f" · epoch {self._comm.session_epoch}"
                  f" · {'attached' if DistributedMagics._attached else 'orphan-capable'}"
                  f" (orphan TTL {ttl}s)")
        connected = (set(self._comm.connected_ranks())
                     if self._comm is not None else None)
        # Stall threshold for the ⚠ state: the active watchdog's
        # policy, else the env-configured default — a rank busy beyond
        # it is rendered stalled even before (or without) a watchdog
        # verdict, so the human eye gets the same signal.
        wd = DistributedMagics._watchdog
        stalled: set = set()
        if wd is not None:
            # An armed watchdog is the authority: a rank is stalled
            # when its current assessment says HUNG, never merely
            # long-busy (the core "distinct from slow" contract).
            for v in wd.last_verdicts:
                stalled.update(v.get("ranks") or ())
        else:
            from ..resilience.watchdog import HangPolicy
            pol = HangPolicy.from_env_lenient()
            # NBD_HANG=0 turns hang detection OFF everywhere — a long
            # legitimate cell must then render busy, never stalled.
            # Without a watchdog, stalled = busy past the window AND
            # no collective entered within it (a rank advancing
            # through collectives is slow, not stuck).
            if pol.enabled:
                for r, b in busy.items():
                    if b["s"] > pol.stall_s and (
                            b.get("col_age") is None
                            or b["col_age"] > pol.stall_s):
                        stalled.add(r)
        # Multi-host worlds: group ranks per host, with the link's
        # health (RTT from clock samples, worst heartbeat age,
        # redeliveries ≈ loss) on each host header (ISSUE 6).
        hosts_map = dict(getattr(self._pm, "hosts", None) or {})
        multi = len(set(hosts_map.values())) > 1
        link = None
        if multi and self._comm is not None:
            try:
                link = self._comm.link_stats()
            except Exception:
                link = None
        order = (sorted(proc_status,
                        key=lambda r: (hosts_map.get(r, "local"), r))
                 if multi else sorted(proc_status))
        cur_host = None
        for rank_id in order:
            if multi:
                h = hosts_map.get(rank_id, "local")
                if h != cur_host:
                    cur_host = h
                    hdr = f"┌ host {h}"
                    hs = ((link or {}).get("hosts") or {}).get(h)
                    if hs:
                        from ..resilience.partition import \
                            format_link_suffix
                        hdr += f" · {format_link_suffix(hs)}"
                    print(hdr)
            p = proc_status[rank_id]
            if not p["running"]:
                state = f"✖ exited ({p['returncode']})"
            elif connected is not None and rank_id not in connected:
                # Process alive but not attached to THIS coordinator:
                # the fleet-side view of orphan grace.
                state = "◌ orphaned"
            elif rank_id in stalled:
                # Alive and heartbeating, but stuck by the watchdog's
                # assessment (or, unarmed, busy past the stall window
                # with zero collective progress) — the live-but-stuck
                # middle state the hang watchdog exists for.
                state = "⚠ stalled"
            else:
                state = "● running"
            line_txt = f"├─ Rank {rank_id}: pid {p['pid']} {state}"
            if rank_id in live:
                st = live[rank_id]
                devs = st.get("devices", [])
                if devs:
                    d = devs[0]
                    line_txt += f" · {d['platform']}:{d['id']} ({d['kind']})"
                    mem = d.get("memory_gb") or {}
                    if mem.get("in_use") is not None:
                        line_txt += (f" · mem {mem['in_use']:.2f}"
                                     f"/{mem.get('limit') or 0:.2f} GB")
                line_txt += (f" · {st['global_device_count']} global "
                             f"devices")
                # A profiler/span trace left running used to be
                # invisible; surface both (satellite of ISSUE 2).
                if st.get("profiling"):
                    line_txt += f" · 🔬 profiling → {st['profiling']}"
                if st.get("tracing"):
                    line_txt += (f" · 📡 tracing "
                                 f"({st.get('trace_spans', 0)} spans)")
            if rank_id in busy:
                b = busy[rank_id]
                line_txt += (f" · ⚙ busy: {b['type']} running "
                             f"{b['s']:.1f}s")
            if self._comm is not None:
                seen = self._comm.last_seen(rank_id)
                if seen is not None:
                    line_txt += f" · seen {time.time() - seen:.1f}s ago"
                # Heartbeat age as its own column: `seen` refreshes on
                # ANY frame (a reply stream keeps it young), so a rank
                # whose heartbeat thread froze — the early sign of a
                # wedged host — is only visible here, before the
                # supervisor's degraded timeout fires.
                ping = self._comm.last_ping(rank_id)
                line_txt += (f" · hb {time.time() - ping[0]:.1f}s"
                             if ping is not None else " · hb –")
            print(line_txt)
        if self._comm is not None:
            # Clock-skew surfacing (ISSUE 13 satellite): big offsets
            # silently degrade merged traces and stage attribution —
            # say so here, where the operator already looks.
            from ..observability import latency as lat_mod
            for w in lat_mod.skew_warnings(self._comm.clock.stats()):
                print(w)
        ex = DistributedMagics._async_exec
        if ex is not None:
            snap = ex.snapshot()
            if snap["depth"]:
                holder = snap.get("collective_holder")
                print(f"⧗ async window: {snap['depth']}/"
                      f"{snap['window']} in flight"
                      + (f" · collective stream held by cell "
                         f"#{holder}" if holder is not None
                         else " · all proven collective-free"))
                for c in snap["cells"]:
                    print(f"   #{c['seq']} {c['sha'] or '?'} · "
                          f"{c['collective']} · {c['age_s']}s in "
                          f"flight · {c['state']}")
            elif snap["submitted"]:
                print(f"⧗ async window idle · {snap['completed']} "
                      f"cell(s) completed"
                      + (f", {snap['errored']} errored"
                         if snap["errored"] else "")
                      + (f", held {snap['held_total']}×"
                         if snap["held_total"] else ""))
        sup = DistributedMagics._supervisor
        if sup is not None:
            print(sup.describe())
        if wd is not None:
            print(wd.describe())
        plan = self._comm.fault_plan() if self._comm is not None else None
        if plan is not None:
            print(f"💥 chaos active (coordinator side): {plan.counters}")
        if self._comm is not None and self._comm.tracer.enabled:
            print(f"📡 span trace active: {len(self._comm.tracer)} "
                  f"coordinator spans — %dist_trace save <path> / "
                  f"%dist_trace stop")

    @magic_arguments()
    @argument("--ranks", default=None,
              help="target spec like [0,2]; default all")
    @argument("-n", "--lines", type=int, default=20,
              help="tail length per rank")
    @line_magic
    def dist_logs(self, line):
        """Tail the raw process stdio of worker(s) — output that
        bypassed the streaming path (native-library prints, XLA/absl
        logs, crash output captured before the control plane came up).
        """
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_logs, line)
        args.lines = max(1, args.lines)  # tail(0/-n) would mis-slice
        ranks = sorted(self._pm.io)
        if args.ranks:
            try:
                ranks = rankspec.parse_ranks(args.ranks, self._world)
            except rankspec.RankSpecError as e:
                print(f"❌ {e}")
                return
        for r in ranks:
            io = self._pm.io.get(r)
            text = io.tail(args.lines) if io else ""
            print(f"── rank {r} stdio (last {args.lines} lines) ──")
            print(text if text.strip() else "(empty)")

    @line_magic
    def dist_debug(self, line):
        """Internals dump (reference: magic.py:1589-1624)."""
        print(f"comm manager : {self._comm}")
        if self._comm:
            print(f"  port       : {self._comm.port}")
            print(f"  connected  : {self._comm.connected_ranks()}")
        print(f"process mgr  : {self._pm}")
        if self._pm:
            print(f"  backend    : {self._pm.backend}")
            print(f"  dist port  : {self._pm.dist_port}")
            print(f"  status     : {self._pm.get_status()}")
        print(f"world size   : {self._world}")
        print(f"auto mode    : {self._auto_active}")
        print(f"timeline     : {len(self._timeline.records)} records")

    # ==================================================================
    # variable transfer (latent in the reference: SURVEY §2.1 #9)

    @magic_arguments()
    @argument("name", help="worker variable name")
    @argument("--rank", type=int, default=0, help="rank to pull from")
    @argument("--all", dest="all_ranks", action="store_true",
              help="pull from every rank into a {rank: value} dict")
    @argument("--as", dest="as_name", default=None,
              help="kernel name to bind (default: same name)")
    @argument("--readonly", action="store_true",
              help="bind read-only views of the decode buffers "
                   "(zero assembly copies — cheapest way to inspect "
                   "a large value)")
    @line_magic
    def dist_pull(self, line):
        """Copy a variable from worker(s) into the kernel namespace.
        Values at or above ``NBD_XFER_THRESHOLD_BYTES`` stream over
        the chunked bulk plane (messaging/xfer.py) straight into
        preallocated destination arrays; smaller ones ride one
        round-trip."""
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_pull, line)
        target = args.as_name or args.name
        ranks = (list(range(self._world)) if args.all_ranks
                 else [args.rank])
        pulled: dict = {}
        how = None
        for r in ranks:
            try:
                pulled[r], h = self._pull_one(r, args.name,
                                              readonly=args.readonly)
            except Exception as e:
                print(f"❌ rank {r}: {e}")
                return
            how = how or h
        suffix = f" [{how}]" if how else ""
        if args.all_ranks:
            self.shell.user_ns[target] = pulled
            print(f"✅ {target} = {{rank: value}} from "
                  f"{sorted(pulled)} ranks{suffix}")
        else:
            value = pulled[args.rank]
            self.shell.user_ns[target] = value
            print(f"✅ {target} = {self._describe_pulled(value)} "
                  f"(from rank {args.rank}){suffix}")

    def _pull_one(self, rank: int, name: str, *,
                  readonly: bool = False):
        """One rank's value: chunked plane first, legacy ``get_var``
        when the value cannot ride the buffer path.  Returns
        ``(value, how)`` where ``how`` describes a chunked move (None
        for the one-round-trip paths)."""
        from ..messaging import xfer
        try:
            value, stats = xfer.pull_value(self._comm, rank, name,
                                           readonly=readonly)
            how = None
            if stats.get("chunks"):
                how = (f"chunked: {stats['bytes'] / 1e6:.1f} MB in "
                       f"{stats['chunks']} chunks, "
                       f"{stats['seconds']:.1f}s")
            return value, how
        except xfer.XferFallback:
            pass
        resp = self._comm.send_to_rank(
            rank, "get_var", name, timeout=xfer.scaled_timeout(0))
        if resp.data.get("error"):
            raise RuntimeError(resp.data["error"])
        return self._pulled_value(resp, readonly=readonly), None

    @staticmethod
    def _describe_pulled(value) -> str:
        import numpy as np
        if isinstance(value, np.ndarray):
            return f"array{tuple(value.shape)} {value.dtype}"
        if isinstance(value, (dict, list, tuple)):
            return f"pytree ({type(value).__name__})"
        return repr(value)

    @staticmethod
    def _pulled_value(msg, readonly: bool = False):
        """Reconstruct one rank's get_var reply: raw array, pytree on
        the buffer path (treedef JSON + leaf bufs — no pickle), or
        plain JSON value.  Writable results are assembled with exactly
        ONE copy — ``np.empty`` destination + ``copyto`` from the
        decode view (never view + extra copy); ``readonly`` skips even
        that and hands back the decode views themselves."""
        import numpy as np

        def into_writable(view):
            out = np.empty(view.shape, dtype=view.dtype)
            np.copyto(out, view)
            return out

        if msg.data.get("array"):
            view = msg.bufs["value"]
            return view if readonly else into_writable(view)
        if msg.data.get("pytree") is not None:
            from ..messaging.codec import unflatten_pytree_wire
            leaf = ((lambda a, j: a) if readonly
                    else (lambda a, j: into_writable(a)))
            return unflatten_pytree_wire(msg.data["pytree"], msg.bufs,
                                         leaf)
        return msg.data.get("value")

    @magic_arguments()
    @argument("name", help="kernel variable name")
    @argument("--ranks", default=None,
              help="target spec like [0,2]; default all")
    @line_magic
    def dist_push(self, line):
        """Copy a kernel variable to workers' namespaces.  Values at
        or above ``NBD_XFER_THRESHOLD_BYTES`` stream over the chunked
        bulk plane (crc-verified, resumable, window-bounded memory);
        smaller ones ride one legacy frame with a payload-scaled
        deadline."""
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_push, line)
        if args.name not in self.shell.user_ns:
            print(f"❌ {args.name!r} is not defined in the kernel")
            return
        value = self.shell.user_ns[args.name]
        ranks = list(range(self._world))
        if args.ranks:
            try:
                ranks = rankspec.parse_ranks(args.ranks, self._world)
            except rankspec.RankSpecError as e:
                print(f"❌ {e}")
                return
        import numpy as np
        from ..messaging import xfer
        est = xfer.approx_nbytes(value)
        if est >= xfer.threshold_bytes():
            try:
                stats = xfer.push_value(self._comm, ranks, args.name,
                                        value)
                extra = ""
                if stats["resumed_chunks"] or stats["resent_chunks"]:
                    extra = (f", resumed {stats['resumed_chunks']} / "
                             f"resent {stats['resent_chunks']}")
                print(f"✅ pushed {args.name} to ranks {ranks} "
                      f"[chunked: {stats['bytes'] / 1e6:.1f} MB in "
                      f"{stats['chunks']} chunks, "
                      f"{stats['seconds']:.1f}s{extra}]")
                return
            except xfer.XferFallback:
                pass        # not a buffer-path value: legacy frame
            except xfer.XferError as e:
                print(f"❌ push failed: {e}")
                return
        try:
            if isinstance(value, np.ndarray) or type(value).__module__ \
                    .startswith("jax"):
                arr = np.asarray(value)
                self._comm.send_to_ranks(
                    ranks, "set_var", {"name": args.name},
                    bufs={"value": arr},
                    timeout=xfer.scaled_timeout(arr.nbytes))
            else:
                # Pytrees of arrays (params/optimizer state) take the
                # buffer path: treedef as JSON, leaves as raw bufs —
                # never the codec's pickle fallback.
                payload = {"name": args.name, "value": value}
                bufs = None
                if isinstance(value, (dict, list, tuple)):
                    from ..messaging.codec import flatten_pytree_wire
                    try:
                        meta, bufs = flatten_pytree_wire(value)
                        payload = {"name": args.name, "pytree": meta}
                    except TypeError:
                        bufs = None
                self._comm.send_to_ranks(
                    ranks, "set_var", payload, bufs=bufs,
                    timeout=xfer.scaled_timeout(est))
        except Exception as e:
            print(f"❌ push failed: {e}")
            return
        print(f"✅ pushed {args.name} to ranks {ranks}")

    # ==================================================================
    # IDE sync

    def _sync_ide_quietly(self) -> None:
        try:
            self._sync_ide(verbose=False)
        except Exception:
            pass

    def _sync_ide(self, verbose: bool = True) -> None:
        resp = self._comm.send_to_ranks([0], "get_namespace_info",
                                        timeout=30)
        info = resp[0].data.get("namespace_info", {})
        n = proxies.sync_namespace(self.shell.user_ns, info,
                                   DistributedMagics._proxy_registry)
        if verbose:
            print(f"✅ synced {n} names from rank 0 into the kernel "
                  "namespace (proxies)")

    @line_magic
    def dist_sync_ide(self, line):
        """Refresh kernel-side proxies for worker variables
        (reference: magic.py:1756-1776)."""
        if not self._require_cluster():
            return
        try:
            self._sync_ide(verbose=True)
        except Exception as e:
            print(f"❌ IDE sync failed: {e}")

    # ==================================================================
    # checkpoint / restore (SURVEY §5.4 upgrade — absent in the reference,
    # whose users hand-roll torch.save in cells)

    @magic_arguments()
    @argument("path", nargs="?", default=None,
              help="checkpoint directory (per-rank subdirs)")
    @argument("names", nargs="*", help="worker variable names to save")
    @argument("-b", "--background", action="store_true",
              help="return immediately; the device->host drain and "
                   "disk IO run on a worker thread (jax.Arrays are "
                   "immutable, so training can continue while the "
                   "old buffers stream out)")
    @argument("--status", action="store_true",
              help="poll the in-flight background save instead of "
                   "saving")
    @argument("--fetch", default=None, metavar="LOCAL_DIR",
              help="after a sync save, pull every rank's shard to "
                   "this coordinator-local directory over the chunked "
                   "bulk plane (no shared filesystem needed)")
    @line_magic
    def dist_checkpoint(self, line):
        """Snapshot named variables from every worker's namespace:
        ``%dist_checkpoint ckpt/step100 params opt_state``.  With
        ``--background`` the save overlaps subsequent cells; poll it
        with ``%dist_checkpoint --status``."""
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_checkpoint, line)
        if args.status:
            try:
                resps = self._comm.send_to_all(
                    "checkpoint", {"action": "status"}, timeout=60)
            except Exception as e:
                print(f"❌ checkpoint status failed: {e}")
                return
            for r in sorted(resps):
                d = resps[r].data
                state = d.get("error") or d.get("status")
                extra = ""
                if d.get("status") == "done":
                    total = sum(v.get("bytes", 0) for v in
                                d.get("summary", {}).values())
                    extra = f" ({total / 1e6:.1f} MB)"
                print(f"🔹 Rank {r}: {state}{extra}")
            if DistributedMagics._bg_ckpt_path is not None:
                for r, m in resps.items():
                    if m.data.get("error"):
                        # A failed rank save disqualifies the whole
                        # checkpoint as a heal target.
                        DistributedMagics._clear_bg_ckpt()
                        break
                    if m.data.get("status") == "done":
                        DistributedMagics._bg_ckpt_done.add(r)
                if (DistributedMagics._bg_ckpt_path is not None
                        and DistributedMagics._bg_ckpt_done
                        >= set(range(self._world))):
                    # Every rank finished cleanly: the background save
                    # is now a valid auto-heal restore target.
                    DistributedMagics._last_ckpt_path = \
                        DistributedMagics._bg_ckpt_path
                    DistributedMagics._clear_bg_ckpt()
            return
        if not args.path or not args.names:
            print("usage: %dist_checkpoint <path> <names...> "
                  "[--background] [--fetch DIR] | "
                  "%dist_checkpoint --status")
            return
        if args.fetch and args.background:
            # A background save has nothing on disk to ship yet; the
            # user can fetch once --status shows every rank done.
            print("❌ --fetch needs a sync save (drop --background)")
            return
        try:
            resps = self._comm.send_to_all(
                "checkpoint", {"action": "save", "path": args.path,
                               "names": args.names,
                               "background": args.background},
                timeout=600)
        except Exception as e:
            print(f"❌ checkpoint failed: {e}")
            return
        verb = (f"background save started → {args.path} "
                f"(poll: %dist_checkpoint --status)"
                if args.background else f"saved → {args.path}")
        for r in sorted(resps):
            prev = resps[r].data.get("previous_error")
            if prev:
                print(f"⚠️  Rank {r}: {prev}")
        if self._report_checkpoint(resps, verb):
            # The supervisor restores the most recent COMPLETED
            # checkpoint after an auto-heal respawn; a background save
            # only qualifies once a --status poll shows every rank done.
            if args.background:
                DistributedMagics._bg_ckpt_path = args.path
                DistributedMagics._bg_ckpt_done = set()
            else:
                DistributedMagics._last_ckpt_path = args.path
                # This sync save is now the newest completed
                # checkpoint: drop any older background save still
                # pending promotion, or a later --status poll would
                # overwrite the heal target with stale state.
                DistributedMagics._clear_bg_ckpt()
                if args.fetch:
                    try:
                        total = self._fetch_ckpt(args.path, args.fetch)
                    except Exception as e:
                        print(f"❌ fetch failed: {e}")
                        return
                    print(f"✅ fetched {self._world} rank shards → "
                          f"{args.fetch} [{total / 1e6:.1f} MB over "
                          f"the bulk plane]")

    @magic_arguments()
    @argument("path", help="checkpoint directory written by "
                           "%%dist_checkpoint")
    @argument("names", nargs="*", help="names to restore (default: all)")
    @argument("--ship", default=None, metavar="LOCAL_DIR",
              help="first push this coordinator-local checkpoint "
                   "(rank_<r>/ subdirs, e.g. from --fetch) to every "
                   "rank's <path> over the chunked bulk plane, then "
                   "restore — moves a checkpoint into a world with no "
                   "shared filesystem")
    @line_magic
    def dist_restore(self, line):
        """Load checkpointed variables back into every worker's
        namespace: ``%dist_restore ckpt/step100 [params ...]``."""
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_restore, line)
        if args.ship:
            try:
                total = self._ship_ckpt(args.ship, args.path)
            except Exception as e:
                print(f"❌ ship failed: {e}")
                return
            print(f"📦 shipped {args.ship} → {self._world} ranks at "
                  f"{args.path} [{total / 1e6:.1f} MB over the bulk "
                  f"plane]")
        try:
            resps = self._comm.send_to_all(
                "checkpoint", {"action": "restore", "path": args.path,
                               "names": args.names or None}, timeout=600)
        except Exception as e:
            print(f"❌ restore failed: {e}")
            return
        if self._report_checkpoint(resps, f"restored ← {args.path}"):
            self._sync_ide_quietly()
        else:
            # Help the user see what the checkpoint actually holds
            # (single-host: the coordinator shares the filesystem).
            from ..runtime import checkpoint as ckpt_mod
            meta = ckpt_mod.info(args.path)
            if meta["ranks"]:
                for r, m in sorted(meta["ranks"].items()):
                    print(f"   rank {r} has: {', '.join(m['names'])} "
                          f"(saved from world of {m['world_size']})")
            else:
                print(f"   no checkpoint data found under {args.path!r}")

    # One rank's shard on disk (runtime/checkpoint.py layout): the
    # array payload, its manifest, and optional pickled aux state.
    _CKPT_FILES = ("manifest.json", "arrays.npz", "aux.pkl")

    def _fetch_ckpt(self, remote_path: str, local_dir: str) -> int:
        """Gather every rank's checkpoint shard to ``local_dir`` over
        the chunked bulk plane.  Returns total bytes moved."""
        import os
        from ..messaging import xfer
        total = 0
        for r in range(self._world):
            sub = f"rank_{r}"
            for fname in self._CKPT_FILES:
                src = os.path.join(remote_path, sub, fname)
                dst = os.path.join(local_dir, sub, fname)
                try:
                    stats = xfer.pull_file(self._comm, r, src, dst)
                except xfer.XferError as e:
                    if fname == "aux.pkl":
                        continue    # shard had no non-array state
                    raise RuntimeError(f"rank {r} {fname}: {e}")
                total += stats.get("bytes", 0)
        return total

    def _ship_ckpt(self, local_dir: str, remote_path: str) -> int:
        """Push a coordinator-local checkpoint (``rank_<r>/`` subdirs)
        to each rank's filesystem at ``remote_path``.  Returns total
        bytes moved."""
        import os
        from ..messaging import xfer
        total = 0
        for r in range(self._world):
            sub = f"rank_{r}"
            src_dir = os.path.join(local_dir, sub)
            if not os.path.isdir(src_dir):
                raise RuntimeError(
                    f"{src_dir} missing — need one rank_<r>/ shard "
                    f"per worker (write them with %dist_checkpoint "
                    f"--fetch)")
            for fname in self._CKPT_FILES:
                src = os.path.join(src_dir, fname)
                if not os.path.exists(src):
                    continue
                stats = xfer.push_file(
                    self._comm, [r], src,
                    os.path.join(remote_path, sub, fname))
                total += stats.get("bytes", 0)
        return total

    def _report_checkpoint(self, resps: dict, verb: str) -> bool:
        """Print per-rank checkpoint results; True if all ranks ok."""
        ok = True
        for rank in sorted(resps):
            data = resps[rank].data
            if data.get("error"):
                print(f"❌ rank {rank}: {data['error']}")
                ok = False
        if ok:
            summary = resps[min(resps)].data.get("summary", {})
            total = sum(s["bytes"] for s in summary.values())
            names = ", ".join(f"{n} ({s['leaves']} leaves)"
                              for n, s in sorted(summary.items()))
            print(f"✅ {len(resps)} ranks {verb}: {names} "
                  f"[{total / 1e6:.1f} MB/rank]")
        return ok

    # ==================================================================
    # profiling (TPU-idiomatic; SURVEY §5.1 suggested %dist_profile)

    @magic_arguments()
    @argument("action", choices=["start", "stop"])
    @argument("--log-dir", default="/tmp/nbd_profile",
              help="per-worker trace dir (suffixed with the rank)")
    @line_magic
    def dist_profile(self, line):
        """jax.profiler traces on every worker; view in TensorBoard/
        Perfetto."""
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_profile, line)
        try:
            # One broadcast; each worker suffixes its own rank directory.
            self._comm.send_to_all(
                "profile", {"action": args.action,
                            "log_dir": args.log_dir}, timeout=60)
        except Exception as e:
            print(f"❌ profile {args.action} failed: {e}")
            return
        if args.action == "start":
            print(f"🔬 profiling started → {args.log_dir}/rank*/")
        else:
            print(f"🔬 profiling stopped; traces in {args.log_dir}/rank*/")

    # ==================================================================
    # observability: cross-rank span tracing + metrics (ISSUE 2)

    @magic_arguments()
    @argument("action", nargs="?", default="status",
              choices=["start", "stop", "save", "status"])
    @argument("path", nargs="?", default="nbd_trace.json",
              help="output file for `save` (Chrome-trace JSON; load in "
                   "ui.perfetto.dev)")
    @line_magic
    def dist_trace(self, line):
        """Cross-rank span tracing: ``%dist_trace start`` records
        coordinator spans around every request and worker spans around
        handler dispatch / cell execution / checkpoints / eager
        collectives, all under ONE trace id propagated in the wire
        envelope; ``save`` merges coordinator + all ranks onto the
        coordinator's timebase (per-rank clock offsets estimated from
        request RTTs) into one Perfetto-loadable file, with any active
        fault plan's decisions folded in as instant events.  Off by
        default with near-zero overhead."""
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_trace, line)
        comm = self._comm
        tr = comm.tracer
        if args.action == "start":
            import uuid
            tid = uuid.uuid4().hex[:16]
            try:
                # Workers first (adopting the shared trace id), so the
                # coordinator never stamps a request that lands on a
                # not-yet-tracing worker.
                comm.send_to_all("trace", {"action": "start",
                                           "trace_id": tid}, timeout=30)
            except Exception as e:
                print(f"❌ starting worker tracers failed: {e}")
                return
            tr.start(trace_id=tid)
            print(f"📡 tracing ON (trace {tid}) — run cells, then "
                  f"%dist_trace save <path>")
            return
        if args.action == "stop":
            n = tr.stop()
            try:
                resps = comm.send_to_all("trace", {"action": "stop"},
                                         timeout=30)
                per_rank = {r: resps[r].data.get("spans")
                            for r in sorted(resps)}
            except Exception as e:
                per_rank = f"<worker stop failed: {e}>"
            print(f"📡 tracing OFF — buffered spans: coordinator {n}, "
                  f"workers {per_rank} (%dist_trace save still works)")
            return
        if args.action == "status":
            state = "ON" if tr.enabled else "off"
            print(f"coordinator: tracing {state}, {len(tr)} spans "
                  f"buffered"
                  + (f", trace {tr.trace_id}" if tr.trace_id else ""))
            try:
                resps = comm.send_to_all("trace", {"action": "status"},
                                         timeout=30)
                for r in sorted(resps):
                    d = resps[r].data
                    print(f"🔹 rank {r}: {d.get('status')} "
                          f"({d.get('spans', 0)} spans)")
            except Exception as e:
                print(f"⚠️ worker-side status failed: {e}")
            return
        # save: collect per-rank dumps + fault events, merge on the
        # coordinator's timebase, write one Chrome-trace JSON.
        from ..observability import export as obs_export
        try:
            resps = comm.send_to_all("trace", {"action": "dump"},
                                     timeout=120)
        except Exception as e:
            print(f"❌ collecting worker traces failed: {e}")
            return
        rank_dumps = {r: m.data.get("trace") or {}
                      for r, m in resps.items()}
        rank_faults = {r: m.data.get("fault_events") or []
                       for r, m in resps.items()}
        plan = comm.fault_plan()
        cdump = tr.dump()
        offsets = comm.clock.offsets()
        merged = obs_export.merge_trace(
            cdump, rank_dumps, offsets,
            coordinator_faults=plan.events() if plan is not None else [],
            rank_faults=rank_faults)
        try:
            n = obs_export.save_trace(args.path, merged)
        except OSError as e:
            print(f"❌ could not write {args.path}: {e}")
            return
        n_spans = {r: len(d.get("spans", [])) for r, d in
                   sorted(rank_dumps.items())}
        offs = {r: round(o * 1e3, 3) for r, o in sorted(offsets.items())}
        print(f"✅ {n} events → {args.path} (coordinator "
              f"{len(cdump['spans'])} spans, ranks {n_spans}, "
              f"clock offsets {offs} ms) — load in ui.perfetto.dev")

    @magic_arguments()
    @argument("--prom", action="store_true",
              help="print Prometheus exposition text instead of the "
                   "summary")
    @argument("--save", default=None,
              help="also write the full JSON snapshot (coordinator + "
                   "per-rank) to this path")
    @line_magic
    def dist_metrics(self, line):
        """One coherent view of the session's metrics: wire messages /
        bytes, retries, dedup hits, cell and collective durations,
        fault injections, supervisor transitions — from the
        coordinator's registry and every rank's, with resilience
        counters mirrored in at snapshot time."""
        if not self._require_cluster():
            return
        args = parse_argstring(self.dist_metrics, line)
        comm = self._comm
        from ..observability import flightrec as _flightrec
        from ..observability import latency as _lat_mod
        from ..observability import metrics as obs_metrics
        reg = obs_metrics.registry()
        # Mirror coordinator-side resilience state into the registry so
        # the export is self-contained — including the flight ring's
        # health and the clock estimator's per-rank offsets (ISSUE 13
        # satellites: evidence-loss and skew visibility).
        _flightrec.export_health(reg)
        _lat_mod.export_clock_metrics(comm.clock, reg)
        now = time.time()
        for r in comm.connected_ranks():
            seen = comm.last_seen(r)
            if seen is not None:
                reg.gauge("nbd_heartbeat_staleness_seconds",
                          "seconds since this rank was last heard",
                          {"rank": str(r)}).set(round(now - seen, 3))
        plan = comm.fault_plan()
        if plan is not None:
            for action, c in plan.counters.items():
                reg.gauge("nbd_fault_injections",
                          "fault-plan decisions by action",
                          {"action": action}).set(c)
        sup = DistributedMagics._supervisor
        if sup is not None:
            reg.gauge("nbd_supervisor_transitions",
                      "supervisor state transitions observed "
                      "(monotonic)").set(
                sup.status().get("transitions", 0))
        try:
            resps = comm.send_to_all(
                "metrics",
                {"format": "prometheus" if args.prom else "json"},
                timeout=30)
        except Exception as e:
            print(f"❌ metrics fetch failed: {e}")
            return
        if args.prom:
            print("── coordinator ──")
            print(reg.prometheus_text(), end="")
            for r in sorted(resps):
                print(f"── rank {r} ──")
                print(resps[r].data.get("text", ""), end="")
            return
        coord = reg.to_json()
        rank_json = {r: resps[r].data.get("metrics", {})
                     for r in sorted(resps)}
        if args.save:
            import json
            with open(args.save, "w") as f:
                json.dump({"coordinator": coord,
                           "ranks": {str(r): v
                                     for r, v in rank_json.items()}}, f,
                          indent=1)
            print(f"✅ full snapshot → {args.save}")

        def _total(snap: dict, name: str) -> float:
            """Sum every series of ``name`` across counters+gauges."""
            tot = 0.0
            for sect in ("counters", "gauges"):
                for k, v in snap.get(sect, {}).items():
                    if k == name or k.startswith(name + "{"):
                        tot += v
            return tot

        def _hist(snap: dict, name: str) -> tuple[int, float]:
            count, total = 0, 0.0
            for k, v in snap.get("histograms", {}).items():
                if k == name or k.startswith(name + "{"):
                    count += v.get("count", 0)
                    total += v.get("sum", 0.0)
            return count, total

        print(f"📊 coordinator: wire tx/rx "
              f"{_total(coord, 'nbd_wire_messages_total'):.0f} msgs · "
              f"{_total(coord, 'nbd_wire_bytes_total') / 1e6:.2f} MB · "
              f"retries {_total(coord, 'nbd_retries_total'):.0f}")
        for r in sorted(rank_json):
            snap = rank_json[r]
            cells, cell_s = _hist(snap, "nbd_cell_seconds")
            colls, coll_s = _hist(snap, "nbd_collective_seconds")
            print(f"🔹 rank {r}: cells {cells} ({cell_s:.2f}s) · "
                  f"collectives {colls} ({coll_s:.2f}s) · dedup "
                  f"{_total(snap, 'nbd_dedup_hits'):.0f} · wire "
                  f"{_total(snap, 'nbd_wire_messages_total'):.0f} msgs "
                  f"{_total(snap, 'nbd_wire_bytes_total') / 1e6:.2f} MB"
                  + (f" · faults "
                     f"{_total(snap, 'nbd_fault_injections'):.0f}"
                     if _total(snap, "nbd_fault_injections") else "")
                  + (f" · parked "
                     f"{_total(snap, 'nbd_mailbox_parked'):.0f}"
                     if _total(snap, "nbd_mailbox_parked") else "")
                  + (f" · orphan transitions "
                     f"{_total(snap, 'nbd_orphan_transitions'):.0f}"
                     if _total(snap, "nbd_orphan_transitions") else ""))

    @magic_arguments()
    @argument("--last", type=int, default=0,
              help="also render a waterfall for the last N cells")
    @argument("--save", default=None,
              help="write the summary + raw stage records JSON here")
    @line_magic
    def dist_lat(self, line):
        """The latency observatory (ISSUE 13): WHERE each cell's
        wall-clock went, as eight contiguous stages (vet → queue →
        wire → dispatch → compile → execute → reply → deliver) stamped
        by the coordinator and workers and clock-corrected onto one
        timebase.  Default: per-stage p50/p95/p99 table over the
        recent-cells ring (``NBD_LAT_RING``); ``--last N`` adds an
        ASCII waterfall per cell.  In tenant mode the observatory
        lives in the gateway daemon — this reads its pool-status
        latency block.  ``NBD_LAT=0`` disables stamping entirely."""
        args = parse_argstring(self.dist_lat, line)
        from ..observability import latency as lat_mod
        if DistributedMagics._tenant is not None:
            client = DistributedMagics._tenant
            try:
                st = client.pool_status()
            except Exception as e:
                print(f"❌ pool status failed: {e}")
                return
            block = st.get("latency") or {}
            n_recs = len(block.get("records") or ())
            if args.last > n_recs or (args.save and n_recs
                                      < lat_mod.DEFAULT_RING):
                # The gateway ships a bounded tail of its ring in the
                # status payload — say so instead of silently
                # rendering/saving fewer records than asked for.
                print(f"ℹ️ tenant mode: the gateway's status payload "
                      f"carries its last {n_recs} record(s); the full "
                      f"ring is on the daemon's /latency.json "
                      f"(%dist_pool start --metrics-port)")
        elif self._comm is not None:
            block = self._comm.lat.status_block(
                records=max(args.last, 32))
        else:
            print("❌ No cluster. %dist_init (or %dist_attach "
                  "--tenant) first.")
            return
        print(lat_mod.format_stage_table(block.get("summary") or {}))
        if args.last:
            recs = (block.get("records") or [])[-args.last:]
            print(lat_mod.format_waterfall(recs))
        if args.save:
            import json
            with open(args.save, "w") as f:
                json.dump(block, f, indent=1)
            print(f"✅ latency snapshot → {args.save}")

    # ==================================================================
    # flight recorder: live telemetry + crash postmortems (ISSUE 3)

    @staticmethod
    def _fmt_gb(n) -> str:
        return "-" if n is None else f"{n / 1e9:.2f}"

    @line_magic
    def dist_top(self, line):
        """Live per-rank dashboard from the PUSH path: process state,
        busy cell, heartbeat age, HBM in-use/limit/peak, live buffer
        and compile counts, dedup hits — all read from heartbeat
        piggybacks and the process table, so it renders instantly even
        while every worker is busy mid-cell (a ``get_status`` probe
        would stall behind the serial request loop)."""
        if DistributedMagics._tenant is not None:
            # Tenant mode: the pool view IS the dashboard.
            return self.dist_status(line)
        if self._pm is None or self._comm is None:
            print("❌ No cluster. %dist_init to start one.")
            return
        from ..runtime.worker import HEARTBEAT_INTERVAL_S
        comm, pm = self._comm, self._pm
        sup_states = {}
        if DistributedMagics._supervisor is not None:
            sup_states = DistributedMagics._supervisor.status()["states"]
        proc = pm.get_status()
        now = time.time()
        # Tenant column (gateway pools): only when some rank's busy
        # ping is tenant-attributed — single-kernel sessions keep the
        # pre-pool layout.
        tenants_seen = any(
            (comm.last_ping(r) or (0, {}))[1].get("busy_tenant")
            for r in range(self._world))
        print(f"⏱  cluster top · {self._world} workers · backend="
              f"{pm.backend} · {time.strftime('%H:%M:%S')}")
        # Serving KV column only when some rank reports a decode
        # server — idle clusters keep the pre-serving layout.
        kv_seen = any((comm.last_ping(r) or (0, {}))[1].get("srv")
                      for r in range(self._world))
        # Guard column (ISSUE 19) only when some rank's ping carries a
        # TrainGuard snapshot — guard-free sessions keep their layout.
        guard_seen = any((comm.last_ping(r) or (0, {}))[1].get("tg")
                         for r in range(self._world))
        hdr = (f"{'rank':<5}{'state':<11}{'busy':<18}"
               + (f"{'tenant':<11}" if tenants_seen else "")
               + f"{'hb-age':<8}"
               f"{'col#':<7}{'HBM use/limit GB':<18}{'peak':<7}"
               + (f"{'kv':<12}{'frag':<6}" if kv_seen else "")
               + (f"{'guard':<16}" if guard_seen else "")
               + f"{'bufs':<6}{'compiles':<9}{'dedup':<6}")
        print(hdr)
        print("─" * len(hdr))
        for r in range(self._world):
            p = proc.get(r) or {}
            ping = comm.last_ping(r)
            tel = comm.last_telemetry(r) or {}
            if not p.get("running", False):
                state = f"✖ dead({p.get('returncode')})"
            elif sup_states.get(r) in ("degraded", "healing"):
                state = "◐ " + sup_states[r]
            elif (ping is not None
                    and now - ping[0] > 3 * HEARTBEAT_INTERVAL_S):
                state = "◐ stale"
            else:
                state = "● alive"
            busy = "-"
            if ping is not None and ping[1].get("busy_s") is not None:
                busy = (f"{ping[1].get('busy_type')} "
                        f"{ping[1]['busy_s'] + (now - ping[0]):.1f}s")
                rep = ping[1].get("rep")
                if rep:
                    # Step-loop progress (ISSUE 14): one dispatch, k
                    # steps — the per-step view without a probe.
                    busy = (f"step {rep.get('i')}/{rep.get('k')} "
                            f"{rep.get('sps', 0)}/s")
                    if rep.get("last") is not None:
                        busy += f" {rep['last']:g}"
            tcol = ""
            if tenants_seen:
                tcol = f"{ping[1].get('busy_tenant') or '-':<11}" \
                    if ping is not None else f"{'-':<11}"
            hb = f"{now - ping[0]:.1f}s" if ping is not None else "-"
            # Collective-stream position (hang watchdog piggyback):
            # "#7*" = entered collective 7 and still inside it — the
            # cross-rank skew on this column IS the hang signature.
            col = "-"
            if ping is not None and ping[1].get("col"):
                c = ping[1]["col"]
                col = (f"#{c.get('seq')}"
                       + ("*" if c.get("in") else ""))
            from ..observability.telemetry import hbm_totals
            hbm = hbm_totals(tel) or {}
            mem = (f"{self._fmt_gb(hbm.get('in_use'))}"
                   f"/{self._fmt_gb(hbm.get('limit'))}"
                   if hbm.get("in_use") is not None else "-")
            peak = self._fmt_gb(hbm.get("peak"))
            kvcol = ""
            if kv_seen:
                srv = (ping[1].get("srv") or {}) if ping else {}
                kvb = srv.get("kvb") or ()
                if len(kvb) == 2:
                    kvcol = f"{f'{kvb[0]}/{kvb[1]}blk':<12}"
                elif srv:
                    kvcol = (f"{srv.get('occ', 0)}"
                             f"/{srv.get('slots', 0)}")
                    kvcol = f"{kvcol:<12}"
                else:
                    kvcol = f"{'-':<12}"
                # Fragmentation (ISSUE 18): the rank's largest
                # contiguous free-block run — 40 free blocks in runs
                # of 1 admit very differently from one 40-run.
                frag = srv.get("frag")
                kvcol += (f"{frag:<6}" if frag is not None
                          else f"{'-':<6}")
            gcol = ""
            if guard_seen:
                tg = (ping[1].get("tg") or {}) if ping else {}
                if tg:
                    # skips · last audit verdict (· rollbacks / 🔶
                    # quarantine suspects when present): the at-a-
                    # glance "is anything eating my steps" cell.
                    g = f"s{tg.get('sk', 0)} {tg.get('v', '?')}"
                    if tg.get("rb"):
                        g += f" rb{tg['rb']}"
                    if tg.get("qr"):
                        g += f" 🔶{tg['qr']}"
                    gcol = f"{g:<16}"
                else:
                    gcol = f"{'-':<16}"
            print(f"{r:<5}{state:<11}{busy:<18}{tcol}{hb:<8}{col:<7}"
                  f"{mem:<18}"
                  f"{peak:<7}{kvcol}{gcol}{str(tel.get('bufs', '-')):<6}"
                  f"{str(tel.get('compiles', '-')):<9}"
                  f"{str(tel.get('dedup', '-')):<6}")
        print(f"coordinator: retries sent {comm.retries_sent} · "
              f"run dir {_knobs.get_str('NBD_RUN_DIR', '(unset)')}")

    @magic_arguments()
    @argument("--last", action="store_true",
              help="show the newest bundle's report instead of "
                   "capturing a fresh one")
    @argument("--save", default=None,
              help="capture the bundle into this directory")
    @line_magic
    def dist_postmortem(self, line):
        """Crash postmortems from the always-on flight recorder.

        Default: capture a fresh bundle NOW — recover every process's
        flight ring (including rings left by dead/SIGKILLed workers),
        attach the last heartbeat telemetry per rank, coordinator
        spans, and fault-plan decisions, merge everything into one
        clock-aligned Chrome trace, and print the report.  ``--last``
        re-prints the newest existing bundle (e.g. the one the
        supervisor captured before auto-healing); ``--save DIR``
        captures into a directory of your choosing."""
        args = parse_argstring(self.dist_postmortem, line)
        from ..observability import postmortem as pm_mod
        if args.last:
            sup = DistributedMagics._supervisor
            bundle = None
            if sup is not None and sup.last_postmortem is not None:
                bundle = sup.last_postmortem["dir"]
            else:
                bundles = pm_mod.list_bundles()
                bundle = bundles[-1] if bundles else None
            if bundle is None:
                print("❌ no postmortem bundle captured yet in this "
                      "run (%dist_postmortem captures one on demand)")
                return
            try:
                import os as _os
                with open(_os.path.join(bundle, "report.txt")) as f:
                    print(f.read())
            except OSError as e:
                print(f"❌ could not read {bundle}: {e}")
            return
        if self._comm is None:
            print("❌ no coordinator in this session — use "
                  "%dist_postmortem --last to view an existing bundle")
            return
        dead = []
        if self._pm is not None:
            alive = set(self._pm.alive_ranks())
            dead = sorted(set(range(self._world)) - alive)
        # A capture taken mid-hang keeps the doctor's diagnosis next
        # to the black boxes (read-only: no stack-dump signal here —
        # the bundle must not perturb what it records).
        hang = None
        wd = DistributedMagics._watchdog
        if wd is not None and (wd.last_verdicts or wd.status()["active"]):
            from ..resilience.watchdog import hang_report
            try:
                hang = hang_report(self._comm, self._pm, wd,
                                   dump_stacks=False)
            except Exception:
                hang = None
        manifest = pm_mod.capture(self._comm, dead, out_dir=args.save,
                                  reason="on demand (%dist_postmortem)",
                                  hang_report=hang)
        if manifest is None:
            print("❌ postmortem capture failed (is the run directory "
                  "writable?)")
            return
        try:
            import os as _os
            with open(_os.path.join(manifest["dir"], "report.txt")) as f:
                print(f.read())
        except OSError:
            pass
        print(f"✅ bundle → {manifest['dir']} (trace.json loads in "
              f"ui.perfetto.dev)")

    # ==================================================================
    # timeline magics (reference: magic.py:1778-1870)

    @line_magic
    def timeline_show(self, line):
        print(self._timeline.summary())

    @magic_arguments()
    @argument("path", nargs="?", default="nbd_timeline.json")
    @line_magic
    def timeline_save(self, line):
        args = parse_argstring(self.timeline_save, line)
        n = self._timeline.save(args.path)
        print(f"✅ saved {n} cell records → {args.path}")

    @line_magic
    def timeline_clear(self, line):
        self._timeline.clear()
        print("✅ timeline cleared")

    @line_magic
    def timeline_debug(self, line):
        """Dump every record's raw internals (reference:
        %timeline_debug, magic.py:1778-1870)."""
        print(self._timeline.debug_dump())

    @line_magic
    def timeline_sidecar(self, line):
        """``%timeline_sidecar on [path] | off`` — auto-flush the
        timeline to a sidecar JSON after every cell; the server-side
        ``pre_save_hook`` (nbdistributed_tpu.jupyter_hooks) folds it
        into the notebook's ``metadata.execution_timelines`` at save,
        closing the reference's in-notebook persistence
        (reference: magic.py:196-233) without its classic-frontend-
        only injected JS.  With no explicit path, the notebook's own
        path is taken from ``JPY_SESSION_NAME`` when the front-end
        provides it."""
        import os

        parts = line.split(None, 1)
        mode = parts[0] if parts else "on"
        if mode == "off":
            old = DistributedMagics._sidecar
            DistributedMagics._sidecar = None
            # Remove the file too: a stale sidecar would keep being
            # embedded into the notebook on every later save.
            if old:
                try:
                    os.remove(old)
                except OSError:
                    pass
            print("✅ timeline sidecar off (file removed; a timeline "
                  "already embedded by an earlier save stays in the "
                  "notebook's metadata until overwritten)")
            return
        if mode != "on":
            print("usage: %timeline_sidecar on [path] | off")
            return
        if len(parts) > 1:
            # Everything after "on" is the path (spaces allowed;
            # surrounding quotes stripped).
            nb_path = parts[1].strip().strip("'\"")
        else:
            nb_path = os.environ.get("JPY_SESSION_NAME")
            if not nb_path:
                print("❌ no notebook path available (JPY_SESSION_NAME "
                      "unset — older front-end?); pass one explicitly: "
                      "%timeline_sidecar on my_notebook.ipynb")
                return
            if not os.path.isabs(nb_path):
                # JPY_SESSION_NAME is server-root-relative
                # ('sub/nb.ipynb') while this kernel runs in the
                # notebook's own directory — resolve the BASENAME in
                # the cwd so the kernel writes the same file the
                # server-side pre_save_hook (which resolves the full
                # API path against the server root) will read.
                nb_path = os.path.basename(nb_path)
        from ..jupyter_hooks import sidecar_path
        DistributedMagics._sidecar = sidecar_path(nb_path)
        if not self._flush_sidecar():
            # The per-cell flush is fail-open; the explicit 'on' is
            # the one moment to fail loudly instead of advertising a
            # sidecar that can never be written (a stale file from an
            # earlier session must not mask the failure — hence the
            # return value, not an existence probe).
            bad = DistributedMagics._sidecar
            DistributedMagics._sidecar = None
            print(f"❌ could not write {bad} (missing directory or "
                  f"permissions?); sidecar NOT enabled")
            return
        print(f"✅ timeline sidecar → {DistributedMagics._sidecar} "
              f"(enable the pre_save_hook in jupyter_server_config.py "
              f"to embed it into the notebook at save)")

    # ==================================================================
    # shutdown / reset (tiered, reference: magic.py:810-1040)

    @classmethod
    def shutdown_all(cls) -> None:
        """Polite tier: control-plane shutdown broadcast, then process
        teardown (reference: magic.py:1005-1036)."""
        sup = cls._supervisor
        if sup is not None and not sup.on_own_thread():
            # A user-initiated shutdown ends supervision; when the
            # SUPERVISOR is the caller (mid-heal, tearing down the old
            # world before respawning), it must stay alive.
            sup.stop()
            cls._supervisor = None
        wd = cls._watchdog
        if wd is not None and not wd.on_own_thread() \
                and not cls._healing:
            # Same own-thread rule: a watchdog-driven heal goes through
            # this teardown; the watchdog re-binds to the healed world
            # (its heal callback returns the fresh pair) instead of
            # stopping itself mid-ladder.  During ANY %dist_heal
            # (_healing) the instance likewise survives so the
            # replayed %dist_init re-binds it with its customized
            # policy and history intact.
            wd.stop()
            cls._watchdog = None
        # An in-flight background save dies with its world; its
        # per-rank doneness must not leak into the next world and
        # promote a half-written checkpoint as the heal target.
        cls._clear_bg_ckpt()
        if cls._pm is not None:
            cls._pm.quiesce()  # planned exits are not deaths
        if cls._comm is not None:
            try:
                cls._comm.post(cls._comm.connected_ranks(), "shutdown")
                time.sleep(0.3)
            except Exception:
                pass
            try:
                cls._comm.shutdown()
            except Exception:
                pass
        if cls._pm is not None:
            try:
                cls._pm.shutdown()
            except Exception:
                pass
        if cls._metrics_httpd is not None:
            try:
                cls._metrics_httpd.close()
            except Exception:
                pass
            cls._metrics_httpd = None
        inst = cls._instance
        if inst is not None:
            try:
                inst._disable_auto_mode()
            except Exception:
                cls._auto_active = False
            try:
                # Raising stubs and stale mirrors must not outlive the
                # cluster they point at.
                proxies.remove_proxies(inst.shell.user_ns,
                                       cls._proxy_registry)
            except Exception:
                pass
        # Window futures still pending at teardown resolve through the
        # handles' death/disconnect aborts; the executor itself dies
        # with the comm it wraps.
        cls._async_exec = None
        cls._comm = None
        cls._pm = None
        cls._world = 0

    @classmethod
    def _nuclear_shutdown(cls) -> None:
        """Last-resort sweep for orphaned workers (reference:
        magic.py:878-961 pkills by pattern; same idea, our module name)."""
        import subprocess
        subprocess.run(["pkill", "-9", "-f",
                        "nbdistributed_tpu.runtime.worker"],
                       capture_output=True)

    @classmethod
    def _end_durable_session(cls, token: str | None, epoch: int) -> None:
        """EXPLICIT fleet teardown ends the durable session (manifest
        removed, so nothing adopts or GC-protects the remains) — but
        only when THIS kernel still owns it: a fenced-out stale
        coordinator's %dist_shutdown must not delete the manifest of a
        session that was handed to a newer epoch (the filesystem-plane
        twin of the workers' epoch fence).  A kernel exit deliberately
        does not come through here — it merely orphans the fleet,
        which is what %dist_attach resumes."""
        from ..resilience import session as session_mod
        d = _knobs.get_str("NBD_RUN_DIR")
        if not d or token is None:
            return
        m = session_mod.read_manifest(d)
        if m is None:
            return
        if m.get("token") != token:
            return  # another session's manifest — not ours to remove
        if int(m.get("epoch") or 0) > epoch:
            print("⚠️ this session was reattached by a newer "
                  "coordinator (manifest epoch "
                  f"{m.get('epoch')} > ours {epoch}); leaving its "
                  "manifest in place")
            return
        session_mod.end_session(d)

    @classmethod
    def _session_identity(cls) -> tuple[str | None, int]:
        comm = cls._comm
        return (getattr(comm, "session_token", None) if comm else None,
                int(getattr(comm, "session_epoch", 0) or 0)
                if comm else 0)

    @line_magic
    def dist_shutdown(self, line):
        """Stop all workers (reference: magic.py:810-837).  This is the
        explicit fleet teardown of a durable session: workers and the
        session manifest are destroyed.  (Exiting/restarting the kernel
        WITHOUT this magic leaves the fleet orphaned-but-alive for
        NBD_ORPHAN_TTL_S — reattach with %dist_attach.)"""
        if DistributedMagics._tenant is not None:
            # Tenant mode: the POOL belongs to every tenant — this
            # kernel only detaches.  In-flight results will park for
            # a future %dist_attach --tenant; %dist_pool stop ends
            # the pool itself.
            name = DistributedMagics._drop_tenant_state(detach=True)
            print(f"✅ detached tenant {name!r} from the pool (the "
                  "pool keeps running — %dist_pool stop ends it; "
                  f"%dist_attach --tenant {name} resumes this "
                  "tenant)")
            return
        had = self._world
        token, epoch = self._session_identity()
        self.shutdown_all()
        self._nuclear_shutdown()
        self._end_durable_session(token, epoch)
        print(f"✅ shut down {had} workers" if had else "✅ nothing to "
              "shut down")

    @line_magic
    def dist_reset(self, line):
        """Full reset for a fresh start (reference: magic.py:963-1003)."""
        token, epoch = self._session_identity()
        self.shutdown_all()
        self._nuclear_shutdown()
        self._end_durable_session(token, epoch)
        DistributedMagics._timeline = Timeline()
        print("✅ reset complete — %dist_init to start a new cluster")
