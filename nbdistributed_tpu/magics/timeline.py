"""Measured cell-execution timeline.

The reference's timeline subsystem (reference: magic.py:32-60 dataclasses,
magic.py:109-396 hooks, magic.py:1316-1474 recording) tracked every cell
but *estimated* per-line durations from keywords (magic.py:1394-1423 —
import=5ms, torch=3ms...) and persisted via injected browser JavaScript
that only worked in the classic notebook (magic.py:196-233).

This rebuild keeps the surface (``%timeline_*`` magics, per-cell records)
but records only measured quantities: coordinator wall-clock per cell and
the per-rank ``duration_s`` the workers measure around user code
(executor.execute_cell).  Persistence is a plain JSON file — frontend-
agnostic, diffable, and loadable for replay.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field


@dataclass
class CellRecord:
    """One distributed cell execution (reference: CellExecution,
    magic.py:44-60 — minus the estimated per-line events)."""

    index: int
    code: str
    target_ranks: list[int]
    started_at: float
    wall_s: float = 0.0
    rank_duration_s: dict[int, float] = field(default_factory=dict)
    rank_status: dict[int, str] = field(default_factory=dict)
    kind: str = "distributed"  # distributed | rank | sync | local
    # Span ids when a %dist_trace session was active during this cell
    # (observability/spans.py) — the bridge from a timeline row to the
    # matching span tree in the merged Perfetto trace.
    trace_id: str | None = None
    span_id: str | None = None


class Timeline:
    def __init__(self):
        self.records: list[CellRecord] = []

    def start(self, code: str, target_ranks: list[int],
              kind: str = "distributed") -> CellRecord:
        rec = CellRecord(index=len(self.records), code=code,
                         target_ranks=list(target_ranks),
                         started_at=time.time(), kind=kind)
        self.records.append(rec)
        return rec

    def finish(self, rec: CellRecord, responses: dict | None) -> None:
        rec.wall_s = time.time() - rec.started_at
        for rank, msg in (responses or {}).items():
            data = msg.data if hasattr(msg, "data") else msg
            if isinstance(data, dict):
                if "duration_s" in data:
                    rec.rank_duration_s[rank] = round(data["duration_s"], 6)
                rec.rank_status[rank] = ("error" if data.get("error")
                                         else "success")

    def record_local(self, code: str, started_at: float, wall_s: float,
                     ok: bool = True) -> CellRecord:
        """Append a completed *local* cell (ran in the kernel, not on
        workers).  Fed by the IPython pre/post_run_cell hooks so the
        timeline covers every cell of the session, like the reference's
        (reference: magic.py:123-130, 647-707)."""
        rec = CellRecord(index=len(self.records), code=code,
                         target_ranks=[], started_at=started_at,
                         wall_s=round(wall_s, 6), kind="local")
        rec.rank_status = {} if ok else {-1: "error"}
        self.records.append(rec)
        return rec

    def clear(self) -> None:
        self.records.clear()

    def debug_dump(self) -> str:
        """Raw per-record internals (reference: %timeline_debug,
        magic.py:1778-1870)."""
        out = [f"timeline: {len(self.records)} records"]
        for r in self.records:
            out.append(json.dumps(asdict(r), indent=2, default=str))
        return "\n".join(out)

    def payload(self) -> dict:
        """The persisted form — shared by :meth:`save`, the sidecar
        flush (%timeline_sidecar), and the notebook-metadata
        pre_save_hook (jupyter_hooks.py)."""
        return {"version": 1,
                "records": [asdict(r) for r in self.records]}

    def save(self, path: str) -> int:
        payload = self.payload()
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
        return len(payload["records"])

    def summary(self) -> str:
        if not self.records:
            return "timeline: no distributed cells recorded"
        lines = ["idx  kind         wall_s   ranks  max_rank_s  status"]
        for r in self.records:
            worst = max(r.rank_duration_s.values(), default=0.0)
            status = ("error" if "error" in r.rank_status.values()
                      else "ok" if r.rank_status else "-")
            preview = r.code.strip().splitlines()[0][:38] if r.code.strip() \
                else ""
            lines.append(
                f"{r.index:<4d} {r.kind:<12s} {r.wall_s:<8.3f} "
                f"{len(r.target_ranks):<6d} {worst:<11.4f} {status:<7s}"
                f" {preview}")
        return "\n".join(lines)
