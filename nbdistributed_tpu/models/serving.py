"""Continuous-batching decode server: staggered admission over a fixed
slot pool, one shared forward per step.

The reference framework has no serving path at all (its users call HF
``generate`` per prompt in cells); this is the TPU-native serving loop
the KV-cache machinery was built to support.  Design:

* **Static shapes, dynamic occupancy.**  The cache is one
  ``(L, max_batch, Hkv, max_len, D)`` pool; a request occupies a batch
  *slot* for its lifetime.  Admission, completion, and re-use never
  change any array shape — XLA compiles exactly two programs (prefill
  per prompt bucket, one decode step) no matter how requests arrive.
* **Per-slot cache pointers.**  The decode step runs ALL slots in one
  ``forward_with_cache`` call with a per-row ``(B,)`` ``cache_len`` —
  the same machinery batched speculative decoding uses
  (speculative.py) — so requests at different depths share every
  matmul.  Decode-step cost is one B-row forward regardless of how
  staggered the batch is: that sharing is the whole point of
  continuous batching.
* **Inactive slots freeze exactly like finished speculative streams:**
  their advance is masked to zero, their (idempotent) cache writes
  land at a frozen position, and for MoE configs ``row_mask`` keeps
  them out of expert capacity dispatch, so an empty or finished slot
  never perturbs a live one.
* **Prefill-on-admit** runs the prompt as a single-row forward into
  the slot's cache rows, right-padded to a length *bucket* (one
  compile per bucket, ``pad_to`` granularity).  Pad positions write
  garbage cache slots beyond the prompt — harmless by the write-then-
  attend order: a decode step at position ``p`` overwrites slot ``p``
  before any query attends it, and attention masks ``t <= p``.  Pads
  are masked out of MoE expert dispatch (``token_mask``) so they can
  never consume capacity slots and evict real prompt tokens, and the
  lm_head runs only at the last real position (``last_index``).

**Speculative serving** (``draft_params``/``draft_cfg``/``gamma``):
every step runs one draft-propose / target-verify round
(:func:`~.speculative.spec_round`) — the draft proposes ``gamma``
tokens per slot, ONE batched target forward verifies every slot's
candidates, and each active request emits its accepted prefix + the
correction/bonus token (1..gamma+1 tokens per step, diverging freely
per slot).  Greedy speculative serving reproduces the target's own
greedy decode per request — the draft only affects speed.  Budget
and EOS cut a stream mid-round by truncating its emission; the
slot's stale device state dies with the slot.

Greedy serving is bit-identical per request to a standalone
:func:`~.generate.generate` call (asserted in the tests): admission
order, batch occupancy, and other requests' traffic cannot change any
request's tokens for the dense family.  For MoE, a request served
*alone* matches generate exactly — pads are masked out of expert
dispatch AND admission runs at the exact prompt length (expert
capacity is shape-derived, so a padded bucket would inflate it past
the solo run's; the cost is one admission compile per distinct
prompt length for MoE configs).  Multiple live MoE requests pool
expert capacity across rows — batched-decode semantics, the same
caveat as batched speculative decoding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .generate import (_sample, forward_with_cache, init_kv_cache,
                       kv_cache_shardings)
from .transformer import TransformerConfig


class DecodeServer:
    """Slot-pool continuous-batching server around one model.

    Host-side orchestration (admission queue, completion, output
    collection) wraps two jitted device programs: a per-bucket prefill
    and the shared decode step.  Use::

        srv = DecodeServer(params, cfg, max_batch=8, max_len=512)
        rid = srv.submit([1, 2, 3], max_new_tokens=16)
        while not srv.done():
            srv.step()   # plain: 1 token per active request;
                         # speculative mode: 1..gamma+1 per request
        tokens = srv.outputs[rid]

    ``prefill_chunk=N`` (dense family) admits long prompts in
    fixed-size segments through one compiled (1, N) program —
    admission activation memory O(N) instead of O(S_prompt), no
    per-bucket compiles (see :meth:`_run_prefill`).

    :meth:`cache_prefix` registers a shared system prompt: its KV
    block is prefilled once, and matching submissions admit by one
    HBM copy + suffix-only prefill (see the method docstring).

    ``kv_block_tokens=N`` switches the cache to **paged** storage
    (ISSUE 17, :mod:`.paged_kv`): the pool holds ``kv_blocks`` fixed-
    size physical blocks, each request reserves
    ``ceil((prompt + max_new) / N)`` of them at admission, and
    capacity is measured in blocks rather than slots — short requests
    stop reserving ``max_len`` of KV each.  Exhaustion leaves
    requests pending (never a silent wedge — the gateway's accounting
    allocator issues the explicit verdicts).  ``interleave_prefill=
    True`` (requires ``prefill_chunk``) admits long prompts one chunk
    per :meth:`step` interleaved with decode, bounding the prefill
    work any single tick can add — the chunked-prefill TPOT
    guarantee.
    """

    def __init__(self, params, cfg: TransformerConfig, *,
                 max_batch: int, max_len: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None, eos_id: int | None = None,
                 kv_quantized: bool = False, mesh=None,
                 ep_axis: str = "ep", pad_to: int = 64, key=None,
                 draft_params=None, draft_cfg=None, gamma: int = 4,
                 prefill_chunk: int | None = None,
                 kv_block_tokens: int | None = None,
                 kv_blocks: int | None = None,
                 interleave_prefill: bool = False):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if pad_to < 1:
            raise ValueError(f"pad_to must be >= 1, got {pad_to}")
        if top_k is not None and not 1 <= top_k <= cfg.vocab_size:
            raise ValueError(f"top_k must be in [1, vocab_size="
                             f"{cfg.vocab_size}], got {top_k}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError("pass both draft_params and draft_cfg, "
                             "or neither")
        if kv_block_tokens is not None and kv_block_tokens < 1:
            raise ValueError(f"kv_block_tokens must be >= 1, got "
                             f"{kv_block_tokens}")
        if kv_block_tokens is None and kv_blocks is not None:
            raise ValueError("kv_blocks needs kv_block_tokens (paged "
                             "mode is enabled by the block size)")
        if kv_block_tokens is not None and draft_cfg is not None:
            # A speculative round writes gamma+1 positions per step;
            # the paged scatter writes back exactly one block per slot.
            # Compose them when the fused paged kernel lands, not by
            # silently corrupting cross-block rounds.
            raise ValueError("paged KV serving does not compose with "
                             "speculative decoding yet")
        if interleave_prefill and prefill_chunk is None:
            raise ValueError("interleave_prefill needs prefill_chunk "
                             "(the per-step prefill work bound)")
        if draft_cfg is not None:
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError("target and draft must share a "
                                 "vocabulary")
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
        from .moe import MoEConfig
        if isinstance(cfg, MoEConfig):
            # Expert capacity is computed from the *static* token count
            # of the prefill shape: a padded bucket would inflate it
            # past what a solo generate() run of the same prompt gets,
            # and capacity changes which tokens drop — silently
            # breaking the solo-request exactness guarantee.  MoE
            # admission therefore compiles per distinct prompt length
            # (pad_to=1); dense configs keep the bucket economy.
            pad_to = 1
            if prefill_chunk is not None:
                # Chunked admission derives capacity from the CHUNK's
                # token count — again not a solo run's.  Same reason.
                raise ValueError(
                    "prefill_chunk is a dense-family option: MoE "
                    "expert capacity is shape-derived, so per-chunk "
                    "capacity would differ from a solo run's and "
                    "change which tokens drop")
        self._params = params
        self._cfg = cfg
        self._mesh = mesh
        self._ep_axis = ep_axis
        self._kv_quantized = kv_quantized
        self._B = max_batch
        self._T = max_len
        self._pad_to = pad_to
        self._prefill_chunk = prefill_chunk
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        self._eos = eos_id
        self._key = key if key is not None else jax.random.PRNGKey(0)

        # Paged mode (ISSUE 17): the cache pool is (L, n_blocks+1,
        # Hkv, block_tokens, D) physical blocks instead of per-slot
        # max_len rows; self._cache holds the pool either way (it is
        # donated through the same jitted programs).
        if kv_block_tokens is not None:
            from .paged_kv import PagedKVCache, make_paged_pool
            if kv_blocks is None:
                # Derived default: exactly the dense pool's capacity,
                # so paging with no explicit budget never refuses a
                # request the dense server would have taken.
                kv_blocks = max_batch * (
                    -(-max_len // kv_block_tokens))
            self._paged = PagedKVCache(
                slots=max_batch, max_len=max_len, n_blocks=kv_blocks,
                block_tokens=kv_block_tokens)
            self._cache = make_paged_pool(
                cfg, kv_blocks, kv_block_tokens, mesh=mesh,
                quantized=kv_quantized)
        else:
            self._paged = None
            self._cache = init_kv_cache(cfg, max_batch, max_len,
                                        mesh=mesh,
                                        quantized=kv_quantized)
        self._lens = jnp.zeros((max_batch,), jnp.int32)
        self._last = jnp.zeros((max_batch,), jnp.int32)
        self._active = jnp.zeros((max_batch,), bool)

        # Speculative mode: a draft model proposes gamma tokens per
        # step, the target verifies them in ONE batched forward —
        # every step emits 1..gamma+1 tokens per active slot.
        self._draft_params = draft_params
        self._draft_cfg = draft_cfg
        self._gamma = gamma
        if draft_cfg is not None:
            self._cache_d = init_kv_cache(draft_cfg, max_batch,
                                          max_len, mesh=mesh,
                                          quantized=kv_quantized)
            self._lens_d = jnp.zeros((max_batch,), jnp.int32)
            self._prefill_d = self._make_prefill(draft_cfg)
            self._spec_fn = self._jit_spec_step()
            self._spec_many_fn = self._jit_spec_many()

        # Prefix cache: shared prompt prefixes prefilled ONCE into
        # dedicated 1-slot KV blocks; admission copies the block
        # (HBM-to-HBM, zero FLOPs) and prefills only the suffix.
        self._prefixes: dict[int, tuple] = {}    # pid -> (tokens, ...)
        self._next_pid = 0
        self._absorb_fn = jax.jit(
            lambda cache, pfx, slot: jax.tree_util.tree_map(
                lambda c, p: jax.lax.dynamic_update_slice(
                    c, p, (0, slot) + (0,) * (c.ndim - 2)),
                cache, pfx),
            donate_argnums=(0,))

        # Host-side bookkeeping.
        self._free = list(range(max_batch))
        self._slot_req: dict[int, int] = {}      # slot -> request id
        self._budget: dict[int, int] = {}        # request id -> remaining
        self._pending: list[tuple[int, list[int], int]] = []
        self._next_id = 0
        self.outputs: dict[int, list[int]] = {}
        self.prompts: dict[int, list[int]] = {}
        self._finished: set[int] = set()
        # Interleaved chunked prefill (ISSUE 17): slots whose prompt
        # is still streaming in, insertion-ordered.  Each step()
        # advances AT MOST ONE chunk of the oldest entry before
        # decoding, so a long prompt can never starve active streams'
        # TPOT — prefill work per tick is bounded by prefill_chunk.
        self._interleave = bool(interleave_prefill)
        self._prefilling: dict[int, list] = {}   # slot -> [rid, prompt,
        #                                          budget, written]
        # Utilization telemetry (ISSUE 18): cumulative prompt tokens
        # written by prefill vs tokens emitted by decode — the worker
        # differences successive snapshots to report each tick's
        # prefill/decode token split to the serving observatory.
        self.prefill_tokens_total = 0
        self.decode_tokens_total = 0

        if self._paged is not None:
            self._prefill_fn = self._make_prefill_paged()
            self._step_fn = self._jit_step_paged()
            self._step_many_fn = None
        else:
            self._prefill_fn = self._make_prefill()
            self._step_fn = self._jit_step()
            self._step_many_fn = self._jit_step_many()

    # ---- jitted programs -------------------------------------------------

    def _make_prefill(self, cfg=None):
        cfg = cfg if cfg is not None else self._cfg
        mesh, ep_axis = self._mesh, self._ep_axis

        def fn(params, cache, prompt, slot, start, length):
            """prompt (1, s_pad) right-padded; writes the slot's cache
            rows at offset ``start`` and returns (updated cache,
            logits at the segment's last REAL token).  ``start`` is 0
            for whole-prompt (bucketed) admission; chunked admission
            streams fixed-size segments at increasing offsets through
            this one compiled shape.  token_mask keeps the pad
            positions out of MoE expert dispatch (they would consume
            capacity slots and could evict real prompt tokens);
            last_index gathers the hidden state at the last REAL token
            before the lm_head, so pads never touch the
            (d_model x vocab) matmul either."""
            row = jax.tree_util.tree_map(
                lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, 1),
                cache)
            s_pad = prompt.shape[1]
            mask = (jnp.arange(s_pad)[None, :] < length)
            logits, row = forward_with_cache(
                params, prompt, row, start, cfg, mesh=mesh,
                ep_axis=ep_axis, token_mask=mask,
                last_index=(length - 1)[None])
            cache = jax.tree_util.tree_map(
                lambda c, r: jax.lax.dynamic_update_slice_in_dim(
                    c, r, slot, 1), cache, row)
            return cache, logits[0, 0]                 # (V,)

        # The cache pool is donated: admission updates it in place
        # instead of copying (L, B, Hkv, max_len, D) per request.
        # One jit serves every prompt bucket — jax.jit retraces (and
        # caches) per input shape, so padding to pad_to multiples
        # bounds the compile count.
        return jax.jit(fn, donate_argnums=(1,))

    def _make_step(self):
        cfg, mesh, ep_axis = self._cfg, self._mesh, self._ep_axis
        temperature, top_k, top_p = (self._temperature, self._top_k,
                                     self._top_p)

        def fn(params, cache, lens, last, active, key):
            logits, cache = forward_with_cache(
                params, last[:, None], cache, lens, cfg, mesh=mesh,
                ep_axis=ep_axis, row_mask=active)
            nxt = _sample(logits[:, -1], temperature, key, top_k, top_p)
            nxt = jnp.where(active, nxt, last)
            lens = lens + active.astype(lens.dtype)
            return cache, lens, nxt

        return fn

    def _jit_step(self):
        # Donated cache: the decode step rewrites the pool in place.
        return jax.jit(self._make_step(), donate_argnums=(1,))

    def _make_prefill_paged(self):
        """Paged prefill, shaped like the dense one so
        :meth:`_run_prefill` (bucketing + chunk streaming) drives both:
        gather the slot's blocks into a dense row, run the same
        forward, scatter the whole row back to its physical blocks.
        The wrapper resolves the slot's block table host-side; the
        jitted inner program takes the ids as data, so one compile
        serves every slot and every (re)allocation."""
        from .paged_kv import gather_row, scatter_row

        cfg, mesh, ep_axis = self._cfg, self._mesh, self._ep_axis

        def fn(params, pool, row_ids, prompt, start, length):
            row = gather_row(pool, row_ids)
            s_pad = prompt.shape[1]
            mask = (jnp.arange(s_pad)[None, :] < length)
            logits, row = forward_with_cache(
                params, prompt, row, start, cfg, mesh=mesh,
                ep_axis=ep_axis, token_mask=mask,
                last_index=(length - 1)[None])
            pool = scatter_row(pool, row, row_ids)
            return pool, logits[0, 0]                  # (V,)

        jit_fn = jax.jit(fn, donate_argnums=(1,))

        def wrapper(params, pool, prompt, slot, start, length):
            return jit_fn(params, pool,
                          self._paged.device_row(int(slot)), prompt,
                          start, length)

        return wrapper

    def _jit_step_paged(self):
        """The paged decode step: gather table-selected blocks to a
        dense view, run the SAME step computation, scatter back only
        the one block per active slot the step wrote (inactive slots
        redirect to the trash block — their frozen-position write must
        never land in a block reallocated to another request)."""
        from .paged_kv import gather_dense, scatter_step

        step = self._make_step()
        bt = self._paged.block_tokens
        trash = self._paged.trash

        def fn(params, pool, table, lens, last, active, key):
            dense = gather_dense(pool, table)
            pos = lens                    # position this step writes
            dense, new_lens, nxt = step(params, dense, lens, last,
                                        active, key)
            pool = scatter_step(pool, dense, table, pos, active,
                                trash, bt)
            return pool, new_lens, nxt

        return jax.jit(fn, donate_argnums=(1,))

    def _jit_step_many(self):
        step = self._make_step()

        def many(params, cache, lens, last, active, keys):
            def body(carry, k):
                cache, lens, last = carry
                cache, lens, nxt = step(params, cache, lens, last,
                                        active, k)
                return (cache, lens, nxt), nxt

            (cache, lens, last), toks = jax.lax.scan(
                body, (cache, lens, last), keys)
            return cache, lens, last, toks        # toks (n, B)

        return jax.jit(many, donate_argnums=(1,))

    def _jit_spec_many(self):
        from .speculative import spec_round

        cfg, dcfg = self._cfg, self._draft_cfg
        gamma, temperature = self._gamma, self._temperature
        mesh, ep_axis = self._mesh, self._ep_axis
        top_k, top_p = self._top_k, self._top_p
        T = self._T

        def fn(params, draft_params, cache_t, lens_t, cache_d, lens_d,
               last, active, keys):
            def body(carry, key):
                cache_t, lens_t, cache_d, lens_d, last = carry
                # Self-freeze before the cache could overflow: a round
                # writes at positions < lens + gamma + 1.  submit()
                # guarantees prompt + budget + gamma + 1 <= max_len,
                # so a stream always reaches its budget before
                # freezing here (the freeze only stops budget-overrun
                # rounds whose tokens the host discards anyway).
                act = active & (lens_t + gamma + 1 <= T)
                (cache_t, lens_t, cache_d, lens_d, _k, cand, n_acc,
                 new_last) = spec_round(
                    params, draft_params, cfg, dcfg, gamma=gamma,
                    temperature=temperature, cache_t=cache_t,
                    len_t=lens_t, cache_d=cache_d, len_d=lens_d,
                    last_tok=last, key=key, active=act, mesh=mesh,
                    ep_axis=ep_axis, top_k=top_k, top_p=top_p)
                return ((cache_t, lens_t, cache_d, lens_d, new_last),
                        (cand, n_acc, act))

            carry = (cache_t, lens_t, cache_d, lens_d, last)
            (cache_t, lens_t, cache_d, lens_d, last), \
                (cands, n_accs, acts) = jax.lax.scan(body, carry, keys)
            return (cache_t, lens_t, cache_d, lens_d, last, cands,
                    n_accs, acts)

        return jax.jit(fn, donate_argnums=(2, 4))

    def _jit_spec_step(self):
        from .speculative import spec_round

        cfg, dcfg = self._cfg, self._draft_cfg
        gamma, temperature = self._gamma, self._temperature
        mesh, ep_axis = self._mesh, self._ep_axis
        top_k, top_p = self._top_k, self._top_p

        def fn(params, draft_params, cache_t, lens_t, cache_d, lens_d,
               last, active, key):
            (cache_t, lens_t, cache_d, lens_d, key, cand, n_acc,
             new_last) = spec_round(
                params, draft_params, cfg, dcfg, gamma=gamma,
                temperature=temperature, cache_t=cache_t,
                len_t=lens_t, cache_d=cache_d, len_d=lens_d,
                last_tok=last, key=key, active=active, mesh=mesh,
                ep_axis=ep_axis, top_k=top_k, top_p=top_p)
            return cache_t, lens_t, cache_d, lens_d, cand, n_acc, \
                new_last

        # Both cache pools donated (updated in place each round).
        return jax.jit(fn, donate_argnums=(2, 4))

    # ---- host-side API ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> int:
        """Queue a request; returns its id.  Admitted to a slot on this
        call if one is free, else at the next :meth:`step`."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        need = len(prompt) + max_new_tokens
        if self._draft_cfg is not None:
            # A final speculative round can write up to gamma + 1
            # cache slots past the budget before the slot finishes.
            need += self._gamma + 1
        if need > self._T:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens})"
                + (f" + speculative headroom ({self._gamma + 1})"
                   if self._draft_cfg is not None else "")
                + f" exceeds max_len {self._T}")
        rid = self._next_id
        self._next_id += 1
        self.prompts[rid] = prompt
        self.outputs[rid] = []
        self._pending.append((rid, prompt, max_new_tokens))
        self._admit_pending()
        return rid

    def _bucket(self, n: int) -> int:
        return -(-n // self._pad_to) * self._pad_to

    def _sample_key(self):
        if self._temperature == 0.0:
            return self._key
        self._key, k = jax.random.split(self._key)
        return k

    def _run_prefill(self, prefill_fn, params, cache, prompt: list,
                     slot: int, start: int = 0):
        """Prefill one slot; returns (cache, last-real-token logits).

        Default: one bucketed whole-prompt forward (compile count
        bounded by distinct buckets).  With ``prefill_chunk`` and a
        longer prompt: fixed-size segments stream through ONE compiled
        (1, chunk) program at increasing cache offsets — admission
        activation memory drops from O(S_prompt) to O(chunk) and long
        prompts stop minting per-bucket compiles.  The final segment
        (padded to the chunk) carries the logits; a causal forward
        makes chunked and single-shot prefill the same computation
        (same argument as :func:`~.generate.prefill_chunked`).

        ``start``: cache offset of the first token — 0 for whole
        prompts; the prefix length for suffix-only admission after a
        :meth:`cache_prefix` hit (the attention machinery already
        supports arbitrary offsets for chunked admission)."""
        L = len(prompt)
        ck = self._prefill_chunk
        if ck is None or L <= ck:
            s_pad = min(self._bucket(L), self._T - start)
            padded = jnp.asarray(prompt + [0] * (s_pad - L),
                                 jnp.int32)[None, :]
            return prefill_fn(params, cache, padded, jnp.int32(slot),
                              jnp.int32(start), jnp.int32(L))
        n_full = L // ck
        if L % ck == 0:
            n_full -= 1        # keep the last full chunk as the tail
        for i in range(n_full):
            seg = jnp.asarray(prompt[i * ck:(i + 1) * ck],
                              jnp.int32)[None, :]
            cache, _ = prefill_fn(params, cache, seg, jnp.int32(slot),
                                  jnp.int32(start + i * ck),
                                  jnp.int32(ck))
        tail = prompt[n_full * ck:]
        # Clamp the tail's pad so the padded write never reaches past
        # max_len (dynamic_update_slice would CLAMP the start index
        # and silently shift the write onto earlier cache rows).
        seg_len = min(ck, self._T - start - n_full * ck)
        seg = jnp.asarray(tail + [0] * (seg_len - len(tail)),
                          jnp.int32)[None, :]
        return prefill_fn(params, cache, seg, jnp.int32(slot),
                          jnp.int32(start + n_full * ck),
                          jnp.int32(len(tail)))

    def cache_prefix(self, tokens) -> int:
        """Prefill a shared prompt prefix ONCE into a dedicated 1-slot
        KV block; returns a prefix id.  Subsequent :meth:`submit`
        calls whose prompt starts with these tokens admit by COPYING
        the block into their slot (one HBM-to-HBM
        ``dynamic_update_slice``, zero FLOPs) and prefilling only the
        suffix — the standard continuous-batching treatment of shared
        system prompts.  Exactness is free: causal attention makes a
        position's K/V depend only on tokens at or before it, and RoPE
        positions are absolute, so the copied rows are bit-identical
        to a full prefill's.

        Dense family only: MoE expert capacity is shape-derived, so a
        suffix-length prefill would change which tokens drop vs a solo
        run (the same reason MoE rejects ``prefill_chunk``).
        """
        from .moe import MoEConfig
        if isinstance(self._cfg, MoEConfig):
            raise ValueError(
                "prefix caching is a dense-family option: MoE expert "
                "capacity is shape-derived, so suffix prefill would "
                "differ from a solo run and change which tokens drop")
        if self._paged is not None:
            raise ValueError(
                "prefix caching is not paged yet: the absorb copy "
                "assumes contiguous per-slot cache rows — register "
                "prefixes on a dense server")
        toks = [int(t) for t in tokens]
        if not toks:
            raise ValueError("empty prefix")
        if len(toks) >= self._T:
            raise ValueError(f"prefix ({len(toks)}) must leave room "
                             f"under max_len {self._T}")
        # Shard the prefix buffer like the pool along the KV-head (tp)
        # axis so the prefill forward and the absorb copy keep the
        # mesh layout; batch (size 1) and tokens stay replicated — a
        # 1-slot buffer can't split over dp, and its bucket length
        # need not divide sp (GSPMD localizes the copy into the
        # sp-sharded pool).
        rules = None
        if self._mesh is not None:
            rules = kv_cache_shardings(
                dp_axis=None,
                tp_axis="tp" if "tp" in self._mesh.shape else None,
                sp_axis=None, quantized=self._kv_quantized)

        def build(cfg, params, prefill_fn):
            # Size the scratch buffer for the PADDED writes (bucketed
            # or chunk-aligned), not just the real rows — an
            # undersized buffer would make dynamic_update_slice clamp
            # the write offset and shift rows.
            ck = self._prefill_chunk
            t_buf = self._bucket(len(toks))
            if ck is not None and len(toks) > ck:
                t_buf = max(t_buf, -(-len(toks) // ck) * ck)
            buf = init_kv_cache(cfg, 1, min(t_buf, self._T),
                                mesh=self._mesh, rules=rules,
                                quantized=self._kv_quantized)
            buf, last_logits = self._run_prefill(prefill_fn, params,
                                                 buf, toks, 0)
            # Keep only the real rows: the copy into a slot must not
            # drag pad garbage past the suffix's overwrite range.
            buf = jax.tree_util.tree_map(
                lambda c: c[:, :, :, :len(toks)], buf)
            return buf, last_logits

        buf_t, last_logits = build(self._cfg, self._params,
                                   self._prefill_fn)
        buf_d = (build(self._draft_cfg, self._draft_params,
                       self._prefill_d)[0]
                 if self._draft_cfg is not None else None)
        pid = self._next_pid
        self._next_pid += 1
        self._prefixes[pid] = (toks, buf_t, buf_d, last_logits)
        return pid

    def drop_prefix(self, pid: int) -> None:
        """Free a cached prefix's KV block (in-flight requests that
        already absorbed it are unaffected — the copy is by value)."""
        if pid not in self._prefixes:
            raise KeyError(f"unknown prefix id {pid}")
        del self._prefixes[pid]

    def _match_prefix(self, prompt: list):
        """Longest registered prefix the prompt starts with, or None."""
        best = None
        for pid, (toks, *_rest) in self._prefixes.items():
            n = len(toks)
            if n <= len(prompt) and prompt[:n] == toks:
                if best is None or n > len(self._prefixes[best][0]):
                    best = pid
        return best

    def _admit_pending(self) -> None:
        while self._pending and self._free:
            rid, prompt, budget = self._pending[0]
            slot = self._free[0]
            if self._paged is not None:
                # Worst-case block reservation at admission, so a
                # stream can never stall mid-decode on allocation.
                # Exhaustion leaves the request PENDING — it admits
                # when finishing streams free blocks.  The gateway's
                # accounting allocator normally prevents reaching
                # this; it is the worker-side backstop.
                from ..serving_fast.paging import BlocksExhausted
                try:
                    self._paged.alloc(slot, len(prompt) + budget)
                except BlocksExhausted:
                    break
            self._pending.pop(0)
            self._free.pop(0)
            if (self._interleave
                    and len(prompt) > self._prefill_chunk):
                # Long prompt: stream it in chunk-by-chunk across
                # decode ticks instead of stalling the batch for one
                # monolithic prefill.  The slot is reserved (and its
                # blocks held) but stays inactive until the last
                # chunk; lens tracks the written offset so the decode
                # step's frozen-position write for this inactive row
                # always lands exactly where the NEXT chunk will
                # write (dense pool; the paged scatter redirects
                # inactive rows to trash anyway).
                self._prefilling[slot] = [rid, prompt, budget, 0]
                self._lens = self._lens.at[slot].set(0)
                continue
            self._admit_now(slot, rid, prompt, budget)

    def _admit_now(self, slot: int, rid: int, prompt: list[int],
                   budget: int) -> None:
        pid = self._match_prefix(prompt)
        if pid is not None:
            ptoks, buf_t, buf_d, plogits = self._prefixes[pid]
            n_pfx = len(ptoks)
            suffix = prompt[n_pfx:]
            self._cache = self._absorb_fn(self._cache, buf_t,
                                          jnp.int32(slot))
            if suffix:
                self._cache, last_logits = self._run_prefill(
                    self._prefill_fn, self._params, self._cache,
                    suffix, slot, start=n_pfx)
            else:
                last_logits = plogits
            if self._draft_cfg is not None:
                self._cache_d = self._absorb_fn(
                    self._cache_d, buf_d, jnp.int32(slot))
                if suffix:
                    self._cache_d, _ = self._run_prefill(
                        self._prefill_d, self._draft_params,
                        self._cache_d, suffix, slot, start=n_pfx)
        else:
            self._cache, last_logits = self._run_prefill(
                self._prefill_fn, self._params, self._cache,
                prompt, slot)
            if self._draft_cfg is not None:
                # Draft cache prefills the same prompt (its seed
                # logits are discarded — the target seeds the
                # stream).
                self._cache_d, _ = self._run_prefill(
                    self._prefill_d, self._draft_params,
                    self._cache_d, prompt, slot)
        tok = int(_sample(last_logits[None], self._temperature,
                          self._sample_key(), self._top_k,
                          self._top_p)[0])
        self.outputs[rid].append(tok)
        self.prefill_tokens_total += len(prompt)
        self._lens = self._lens.at[slot].set(len(prompt))
        self._last = self._last.at[slot].set(tok)
        if self._draft_cfg is not None:
            self._lens_d = self._lens_d.at[slot].set(len(prompt))
        done = (budget == 1
                or (self._eos is not None and tok == self._eos))
        if done:
            self._finish(slot, rid)
        else:
            self._slot_req[slot] = rid
            self._budget[rid] = budget - 1
            self._active = self._active.at[slot].set(True)

    def _finish(self, slot: int, rid: int) -> None:
        self._finished.add(rid)
        self._slot_req.pop(slot, None)
        self._budget.pop(rid, None)
        self._active = self._active.at[slot].set(False)
        self._free.append(slot)
        if self._paged is not None:
            self._paged.free(slot)

    def _advance_prefill(self) -> None:
        """Advance AT MOST ONE chunk of the oldest mid-prefill prompt
        — the per-tick prefill work bound that keeps long prompts from
        starving active streams' TPOT.  The final (possibly partial)
        chunk samples the first token and activates the slot; the
        segmentation matches :meth:`_run_prefill` exactly (full chunks,
        then a tail run at its real length), so the stream is
        bit-identical to a monolithic admission."""
        if not self._prefilling:
            return
        slot, st = next(iter(self._prefilling.items()))
        rid, prompt, budget, written = st
        ck = self._prefill_chunk
        remaining = len(prompt) - written
        if remaining > ck:
            seg = jnp.asarray(prompt[written:written + ck],
                              jnp.int32)[None, :]
            self._cache, _ = self._prefill_fn(
                self._params, self._cache, seg, jnp.int32(slot),
                jnp.int32(written), jnp.int32(ck))
            st[3] = written + ck
            self.prefill_tokens_total += ck
            # Keep lens at the written frontier: the decode step's
            # frozen-position write for this inactive row lands where
            # the next chunk will overwrite it (dense pool).
            self._lens = self._lens.at[slot].set(st[3])
            return
        # Final segment: pad to the chunk shape, clamp so the padded
        # write never reaches past max_len (same rule as
        # _run_prefill's tail).
        tail = prompt[written:]
        seg_len = min(ck, self._T - written)
        seg = jnp.asarray(tail + [0] * (seg_len - len(tail)),
                          jnp.int32)[None, :]
        self._cache, last_logits = self._prefill_fn(
            self._params, self._cache, seg, jnp.int32(slot),
            jnp.int32(written), jnp.int32(len(tail)))
        del self._prefilling[slot]
        self.prefill_tokens_total += len(tail)
        tok = int(_sample(last_logits[None], self._temperature,
                          self._sample_key(), self._top_k,
                          self._top_p)[0])
        self.outputs[rid].append(tok)
        self._lens = self._lens.at[slot].set(len(prompt))
        self._last = self._last.at[slot].set(tok)
        if budget == 1 or (self._eos is not None
                           and tok == self._eos):
            self._finish(slot, rid)
        else:
            self._slot_req[slot] = rid
            self._budget[rid] = budget - 1
            self._active = self._active.at[slot].set(True)

    def cancel(self, rid: int) -> bool:
        """Abort an in-flight request NOW: drop it from the pending
        queue, the prefill stream, or its active slot, freeing the
        slot and (paged mode) its KV blocks.  Returns False for
        unknown/already-finished ids.  The shed/release path uses
        this — a shed request must not pin blocks until its stream
        would have ended."""
        for i, (r, _p, _b) in enumerate(self._pending):
            if r == rid:
                self._pending.pop(i)
                self._finished.add(rid)
                return True
        for slot, st in list(self._prefilling.items()):
            if st[0] == rid:
                del self._prefilling[slot]
                self._finish(slot, rid)
                return True
        for slot, r in list(self._slot_req.items()):
            if r == rid:
                self._finish(slot, rid)
                return True
        return False

    def step(self) -> dict[int, list[int]]:
        """One decode step for every active slot; returns
        {request_id: tokens emitted this step} — one token per step in
        plain mode, 1..gamma+1 in speculative mode.  Admits pending
        requests first, then advances at most one mid-prefill chunk
        (interleave mode)."""
        self._admit_pending()
        self._advance_prefill()
        if not self._slot_req:
            return {}
        if self._draft_cfg is not None:
            return self._spec_step()
        if self._paged is not None:
            self._cache, self._lens, nxt = self._step_fn(
                self._params, self._cache,
                self._paged.device_table(), self._lens, self._last,
                self._active, self._sample_key())
        else:
            self._cache, self._lens, nxt = self._step_fn(
                self._params, self._cache, self._lens, self._last,
                self._active, self._sample_key())
        self._last = nxt
        toks = jax.device_get(nxt)
        emitted: dict[int, list[int]] = {}
        for slot, rid in list(self._slot_req.items()):
            emitted[rid] = self._emit(slot, rid, [int(toks[slot])])
        self._admit_pending()
        return emitted

    def _spec_step(self) -> dict[int, list[int]]:
        """One speculative round: draft proposes gamma tokens per
        slot, ONE target forward verifies all slots' candidates.
        Per-slot acceptance lengths diverge freely; budget/EOS cut a
        stream mid-round by truncating its emission and finishing the
        slot (its device-side cache state beyond the cut is stale but
        dies with the slot — re-admission prefills from 0)."""
        (self._cache, self._lens, self._cache_d, self._lens_d,
         cand, n_acc, new_last) = self._spec_fn(
            self._params, self._draft_params, self._cache, self._lens,
            self._cache_d, self._lens_d, self._last, self._active,
            self._sample_key())
        self._last = new_last
        cand_h, acc_h = jax.device_get((cand, n_acc))
        emitted: dict[int, list[int]] = {}
        for slot, rid in list(self._slot_req.items()):
            emitted[rid] = self._emit(
                slot, rid,
                [int(t) for t in cand_h[slot][: int(acc_h[slot]) + 1]])
        self._admit_pending()
        return emitted

    def _emit(self, slot: int, rid: int, toks: list[int]) -> list[int]:
        """Budget-then-EOS truncation + bookkeeping for a multi-token
        emission — the ONE definition of the cut semantics, shared by
        the speculative round and step_many (both can overshoot
        device-side; the surplus is discarded here and the slot's
        stale device state dies with the slot)."""
        toks = toks[: self._budget[rid]]
        if self._eos is not None and self._eos in toks:
            toks = toks[: toks.index(self._eos) + 1]
        self.outputs[rid].extend(toks)
        self.decode_tokens_total += len(toks)
        self._budget[rid] -= len(toks)
        if (self._budget[rid] == 0
                or (self._eos is not None and toks
                    and toks[-1] == self._eos)):
            self._finish(slot, rid)
        return toks

    def step_many(self, n: int) -> dict[int, list[int]]:
        """Run ``n`` plain decode steps in ONE device program
        (``lax.scan``) and apply budget/EOS host-side afterwards.

        Amortizes the per-step host round-trip that dominates
        single-step serving over a high-latency link (the axon tunnel
        adds ~70 ms per sync): tokens stream back every ``n`` steps
        instead of every step.  Trade-offs, by construction: pending
        requests admit only at scan boundaries (up to ``n`` steps of
        admission latency), and a slot whose stream hits EOS or its
        budget mid-scan keeps computing to the boundary (its surplus
        tokens are discarded host-side; its surplus cache state is
        stale-but-dead exactly like a mid-round speculative cut).
        The emitted tokens are bit-identical to ``n`` successive
        :meth:`step` calls in greedy mode.  Plain mode only —
        speculative serving already emits multiple tokens per step.
        """
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if self._draft_cfg is not None:
            raise ValueError("step_many is for plain serving; use "
                             "spec_step_many on a speculative server")
        if self._paged is not None:
            raise ValueError(
                "step_many is a dense-pool fast path; paged serving "
                "steps host-side per tick (the serve_step driver "
                "loops step())")
        self._admit_pending()
        if not self._slot_req:
            return {}
        keys = jax.random.split(self._sample_key(), n)
        (self._cache, self._lens, self._last,
         toks) = self._step_many_fn(
            self._params, self._cache, self._lens, self._last,
            self._active, keys)
        toks_h = jax.device_get(toks)              # (n, B)
        emitted: dict[int, list[int]] = {}
        for slot, rid in list(self._slot_req.items()):
            emitted[rid] = self._emit(
                slot, rid, [int(t) for t in toks_h[:, slot]])
        self._admit_pending()
        return emitted

    def spec_step_many(self, n: int) -> dict[int, list[int]]:
        """Run ``n`` speculative rounds in ONE device program
        (``lax.scan`` over :func:`~.speculative.spec_round`) — up to
        ``n·(gamma+1)`` tokens per slot per host sync.

        The speculative analog of :meth:`step_many`, with the same
        trade-offs: admission only at scan boundaries, and budget/EOS
        cuts applied host-side after the scan (surplus rounds'
        tokens are discarded; surplus cache state is stale-but-dead).
        Rows additionally self-freeze device-side when another round
        could write past ``max_len`` — that bound only triggers past
        the stream's budget, so emissions are bit-identical to ``n``
        successive :meth:`step` calls in greedy mode."""
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if self._draft_cfg is None:
            raise ValueError("spec_step_many needs a speculative "
                             "server (draft_params/draft_cfg); use "
                             "step_many for plain serving")
        self._admit_pending()
        if not self._slot_req:
            return {}
        keys = jax.random.split(self._sample_key(), n)
        (self._cache, self._lens, self._cache_d, self._lens_d,
         self._last, cands, n_accs, acts) = self._spec_many_fn(
            self._params, self._draft_params, self._cache, self._lens,
            self._cache_d, self._lens_d, self._last, self._active,
            keys)
        cands_h, accs_h, acts_h = jax.device_get(
            (cands, n_accs, acts))                 # (n,B,g+1),(n,B),(n,B)
        emitted: dict[int, list[int]] = {}
        for slot, rid in list(self._slot_req.items()):
            toks: list[int] = []
            for r in range(n):
                if acts_h[r, slot]:
                    toks.extend(
                        int(t) for t in
                        cands_h[r, slot][: int(accs_h[r, slot]) + 1])
            emitted[rid] = self._emit(slot, rid, toks)
        self._admit_pending()
        return emitted

    def release(self, rid: int) -> list[int]:
        """Drop a finished request's host-side record (prompt, output,
        finished flag) and return its tokens — the eviction API that
        keeps a long-running server's host memory bounded.  Unknown or
        already-released ids raise (a silent [] would be
        indistinguishable from a request that emitted nothing)."""
        if rid in self._budget \
                or any(r == rid for r, _, _ in self._pending) \
                or any(st[0] == rid
                       for st in self._prefilling.values()):
            raise ValueError(f"request {rid} is still in flight")
        if rid not in self.outputs:
            raise KeyError(f"unknown or already-released request {rid}")
        toks = self.outputs.pop(rid)
        self.prompts.pop(rid, None)
        self._finished.discard(rid)
        return toks

    def done(self) -> bool:
        return (not self._slot_req and not self._pending
                and not self._prefilling)

    def run_until_done(self, max_steps: int | None = None):
        """Drive :meth:`step` until every request finishes; returns
        ``self.outputs``."""
        steps = 0
        while not self.done():
            self.step()
            steps += 1
            if max_steps is not None and steps > max_steps:
                raise RuntimeError(
                    f"server not drained after {max_steps} steps")
        return self.outputs

    @property
    def finished(self):
        return set(self._finished)

    @property
    def n_active(self) -> int:
        return len(self._slot_req)

    def prefill_progress(self) -> dict[int, tuple[int, int]]:
        """Mid-prefill streams: ``{request_id: (tokens_written,
        prompt_len)}`` — the serve_step reply forwards this so the
        gateway's observatory can annotate prefill[chunk i/n]."""
        return {st[0]: (st[3], len(st[1]))
                for st in self._prefilling.values()}

    def kv_snapshot(self) -> dict | None:
        """Paged-mode block occupancy (``{"blocks", "block_tokens",
        "used", "free", "owners"}``), None on a dense server — the
        worker's heartbeat telemetry and status surfaces read this."""
        return (self._paged.snapshot() if self._paged is not None
                else None)
