"""Autoregressive generation with a KV cache — both model families.

The reference framework has no inference path of its own (its users call
HF ``model.generate`` in cells); a first-party TPU decode loop is part
of making the model families usable interactively.  The attention stack
is shared between the dense and MoE transformers, so one cached forward
serves both (the feed-forward branch dispatches on the config type).
Design for XLA:

* static shapes everywhere — the cache is a fixed ``max_len`` ring of
  zeros, new K/V written by ``lax.dynamic_update_slice``; attention
  masks against global positions instead of slicing a traced length;
* the whole decode loop is one ``lax.scan`` (one compile, no Python
  per-token dispatch); prefill is one batched forward over the prompt;
* grouped-query attention against the cache without materializing
  repeated KV heads (grouped einsum, fp32 accumulation);
* tensor-parallel ready: :func:`kv_cache_shardings` shards the cache
  over KV heads on the ``tp`` axis, matching
  :func:`~nbdistributed_tpu.models.transformer.param_shardings`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..utils.compat import shard_map
from .transformer import (TransformerConfig, _mlp_block, _rms_norm,
                          _rope, qlinear)

_NEG_INF = -1e30


# ----------------------------------------------------------------------
# cache

def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int, *,
                  mesh=None, rules: dict | None = None,
                  quantized: bool = False):
    """Zeroed (L, B, Hkv, max_len, Dh) K and V buffers.

    The cache is **heads-major**: (token, head-dim) are the minor two
    axes, which is what the Pallas decode kernel's block specs tile
    (Mosaic requires the last two block dims divisible by (8, 128) or
    equal to the array's — a (B, T, Hkv, D) layout puts the tiny Hkv
    extent in the sublane slot, which real-TPU lowering rejects; the
    CPU interpreter does not enforce this, so only on-chip runs catch
    it).  It is also the natural TPU tiling: D on lanes, tokens on
    sublanes.

    With ``mesh``, the buffers are laid out by ``rules`` (default:
    :func:`kv_cache_shardings` restricted to the axes the mesh has) so
    the decode loop keeps the cache sharded like the parameters.

    ``quantized=True`` stores the cache **int8** with per-(token,
    kv-head) fp32 scales (``k_s``/``v_s``, (L, B, Hkv, T, 1)): at long
    context the cache — not the weights — dominates decode HBM traffic,
    and the scales commute through both attention matmuls (see
    ops/decode.py), so the kernel streams half the bytes."""
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    if quantized:
        sshape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, 1)
        cache = {"k": jnp.zeros(shape, jnp.int8),
                 "v": jnp.zeros(shape, jnp.int8),
                 "k_s": jnp.zeros(sshape, jnp.float32),
                 "v_s": jnp.zeros(sshape, jnp.float32)}
    else:
        cache = {"k": jnp.zeros(shape, cfg.dtype),
                 "v": jnp.zeros(shape, cfg.dtype)}
    if mesh is not None:
        if rules is None:
            rules = kv_cache_shardings(
                dp_axis="dp" if "dp" in mesh.shape else None,
                tp_axis="tp" if "tp" in mesh.shape else None,
                sp_axis="sp" if "sp" in mesh.shape else None,
                quantized=quantized)
        missing = set(cache) - set(rules)
        if missing:
            hint = (" — a quantized cache needs scale specs too (see "
                    "kv_cache_shardings(quantized=True))"
                    if missing & {"k_s", "v_s"} else "")
            raise ValueError(f"cache sharding rules missing specs for "
                             f"{sorted(missing)}{hint}")
        cache = {name: jax.device_put(
            buf, NamedSharding(mesh, rules[name]))
            for name, buf in cache.items()}
    return cache


def kv_cache_shardings(dp_axis: str | None = "dp",
                       tp_axis: str | None = "tp",
                       sp_axis: str | None = None,
                       quantized: bool = False):
    """PartitionSpec for the cache: batch over dp, KV heads over tp,
    and optionally the TOKEN axis over ``sp_axis`` — sequence-parallel
    decode for contexts whose cache outgrows one chip's HBM (each
    shard holds a T/n slice; the decode kernel combines shards by
    log-sum-exp, see :func:`_flash_decode_on_mesh`).  Both the int8
    scales and the heads-major K/V buffers carry the KV heads at
    axis 2 and tokens at axis 3."""
    spec = P(None, dp_axis, tp_axis, sp_axis, None)
    rules = {"k": spec, "v": spec}
    if quantized:
        rules["k_s"] = spec
        rules["v_s"] = spec
    return rules


def _quantize_kv(x):
    """Per-(token, kv-head) symmetric int8 for a new K or V slab.

    x: (B, Hkv, S, D) heads-major -> (q8 int8 same shape, scales
    (B, Hkv, S, 1) fp32) — both already in the cache layout.  The int8
    core is quant.quantize_weight (one scheme for weights and cache)."""
    from .quant import quantize_weight
    qw = quantize_weight(x, axis=-1)
    return qw["q8"], qw["s"]


def _dequantize_kv(q8, s):
    """Inverse of :func:`_quantize_kv`: int8 (B, Hkv, T, D) + scales
    (B, Hkv, T, 1) -> fp32 (B, Hkv, T, D)."""
    return q8.astype(jnp.float32) * s


# ----------------------------------------------------------------------
# cache-aware forward

def _cached_attention(q, kc, vc, positions, scale, window=None):
    """GQA attention of new-token queries against the full cache.

    q: (B, S, H, Dh) — S new tokens; kc/vc: (B, Hkv, T, Dh) — the
    whole heads-major cache buffer; positions: (B, S) global positions
    of the queries.  Valid keys are exactly cache slots t <= position
    (later slots are unwritten zeros and masked out by the same
    comparison).
    """
    B, S, H, Dh = q.shape
    Hkv, T = kc.shape[1], kc.shape[2]
    group = H // Hkv
    qg = (q.astype(jnp.float32) * scale).reshape(B, S, Hkv, group, Dh)
    s = jnp.einsum("bskgd,bktd->bkgst", qg, kc.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    t_idx = jnp.arange(T)
    mask = t_idx[None, None, :] <= positions[:, :, None]  # (B,S,T)
    if window is not None:
        mask = mask & (t_idx[None, None, :]
                       > positions[:, :, None] - window)
    s = jnp.where(mask[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgst,bktd->bskgd", p, vc.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    return o.reshape(B, S, H * Dh).astype(q.dtype)


def _flash_decode_on_mesh(q, kc, vc, pos, mesh, scale, window=None,
                          k_s=None, v_s=None):
    """Run the Pallas decode kernel under GSPMD via shard_map: batch
    over ``dp``, heads over ``tp``, and the cache's TOKEN axis over
    ``sp`` (sequence-parallel decode — other mesh axes replicated).

    The GQA grouping survives head sharding because q-head block
    [t·H/tp, (t+1)·H/tp) maps exactly onto kv-head block
    [t·Hkv/tp, (t+1)·Hkv/tp) — each shard keeps the full group ratio,
    so the local kernel call is the global computation.

    With an ``sp`` axis, each shard runs the kernel over its local
    T/n cache slice at shifted positions (``pos − shard·T/n``; the
    sliding-window bound is offset-invariant, so ``window`` composes
    unchanged) and the shards merge by log-sum-exp:
    ``o = Σ exp(lse_i − m)·o_i / Σ exp(lse_i − m)`` with
    ``m = max_i lse_i`` — exactly the flash inter-block combine, run
    across chips (one fused psum over ICI per layer per step).  A
    shard wholly past ``pos`` reports ``lse = −inf`` and weighs zero.

    q: (B, H, Dh); kc/vc: (B, Hkv, T, Dh) heads-major; pos: (B,);
    optional int8 cache scales k_s/v_s: (B, Hkv, T, 1).
    """
    from ..ops.decode import flash_decode_attention

    dp = "dp" if "dp" in mesh.shape else None
    tp = "tp" if "tp" in mesh.shape else None
    sp = "sp" if "sp" in mesh.shape else None
    qspec = P(dp, tp, None)
    cspec = P(dp, tp, sp, None)
    sspec = P(dp, tp, sp, None)

    def inner(q, kc, vc, pos, *scales):
        ks, vs = scales if scales else (None, None)
        if sp is None:
            return flash_decode_attention(q, kc, vc, pos, scale=scale,
                                          window=window, k_s=ks,
                                          v_s=vs)
        t_loc = kc.shape[2]
        pos_loc = pos - jax.lax.axis_index(sp) * t_loc
        o, lse = flash_decode_attention(q, kc, vc, pos_loc,
                                        scale=scale, window=window,
                                        k_s=ks, v_s=vs,
                                        return_lse=True)
        lse = lse[..., None]                            # (B, H, 1)
        m = jax.lax.pmax(lse, sp)
        w = jnp.exp(lse - m)
        # ONE psum on the hot path (per layer per step): the weight
        # column rides as an extra feature of the weighted output.
        both = jax.lax.psum(
            jnp.concatenate([o.astype(jnp.float32) * w, w], axis=-1),
            sp)
        num, den = both[..., :-1], both[..., -1:]
        return (num / jnp.maximum(den, 1e-30)).astype(q.dtype)

    quant = k_s is not None
    in_specs = ((qspec, cspec, cspec, P(dp))
                + ((sspec, sspec) if quant else ()))
    args = (q, kc, vc, pos) + ((k_s, v_s) if quant else ())
    return shard_map(inner, mesh=mesh, in_specs=in_specs,
                         out_specs=qspec, check_vma=False)(*args)


def _can_flash_decode_on_mesh(mesh, B, H, Hkv, T=None):
    """The sharded kernel needs each shard to hold whole head groups,
    whole batch rows, and (under ``sp``) equal token slices."""
    tp_n = mesh.shape.get("tp", 1)
    dp_n = mesh.shape.get("dp", 1)
    sp_n = mesh.shape.get("sp", 1)
    return (H % tp_n == 0 and Hkv % tp_n == 0 and B % dp_n == 0
            and (T is None or T % sp_n == 0))


def _make_mlp_fn(cfg: TransformerConfig, mesh, ep_axis: str,
                 token_mask=None):
    """The per-layer feed-forward branch: dense SwiGLU, or the MoE
    layer when the config is a :class:`~.moe.MoEConfig` (sharing
    ``moe._moe_mlp_block`` so the two paths can never diverge).
    ``token_mask`` reaches only the MoE dispatch (dense SwiGLU is
    per-token, so inactive tokens cannot couple anything there)."""
    from .moe import MoEConfig, _moe_mlp_block

    if isinstance(cfg, MoEConfig):
        def mlp(x, layer):
            x, _aux = _moe_mlp_block(x, layer, cfg, mesh, ep_axis,
                                     token_mask=token_mask)
            return x

        return mlp
    return lambda x, layer: _mlp_block(x, layer, cfg)


def forward_with_cache(params: dict, tokens, cache: dict, cache_len,
                       cfg: TransformerConfig, *,
                       last_only: bool = False, last_index=None,
                       mesh=None, ep_axis: str = "ep", row_mask=None,
                       token_mask=None):
    """Run ``tokens`` (B, S) through the model, reading/writing the KV
    cache at offset ``cache_len`` (traced scalar ok, or a per-row
    ``(B,)`` vector when the streams in the batch sit at different
    logical lengths — batched speculative decoding advances each
    stream by its own acceptance count).

    Works for both model families: the attention stack is shared and
    the feed-forward branch dispatches on the config (dense SwiGLU vs
    expert-parallel MoE — ``mesh`` routes the expert all-to-alls).

    Returns (logits fp32, updated cache): (B, S, vocab), or (B, 1,
    vocab) with ``last_only`` — prefill for generation needs only the
    final position, which skips S-1 of the (d_model × vocab) lm_head
    matmul.  ``last_index`` (B,) generalizes that to a per-row
    position (right-padded prompts whose last real token is not at
    S-1: the serving admission path), gathering the hidden state
    before final-norm/lm_head so the padded positions never touch
    the (d_model × vocab) matmul.  Covers both prefill (S = prompt
    length, cache_len = 0) and decode (S = 1).

    ``token_mask`` (B, S) bool marks which positions are *real*: pad
    positions must not enter MoE expert dispatch, where they would
    consume capacity slots and could evict real tokens (dense SwiGLU
    is per-token, so the mask only reaches the expert router).
    ``row_mask`` (B,) is the whole-row shorthand the decode step uses
    for inactive streams; passing both ANDs them.
    """
    B, S = tokens.shape
    H, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    cache_len = jnp.asarray(cache_len)
    per_row = cache_len.ndim == 1  # per-stream cache pointers
    offs = cache_len[:, None] if per_row else cache_len
    positions = offs + jnp.broadcast_to(jnp.arange(S), (B, S))
    x = params["embed"][tokens].astype(cfg.dtype)
    scale = 1.0 / float(cfg.head_dim) ** 0.5
    # row_mask (B,) bool: inactive batch rows (finished speculative
    # streams) must not couple to live rows — only MoE capacity
    # dispatch can couple rows, so the mask feeds the expert router.
    if row_mask is not None:
        rows = jnp.broadcast_to(row_mask[:, None], (B, S))
        token_mask = rows if token_mask is None else token_mask & rows
    mlp = _make_mlp_fn(cfg, mesh, ep_axis, token_mask=token_mask)
    kv_quantized = "k_s" in cache

    def write_kv(buf, new):
        """Insert S new entries at the cache pointer: one slice update
        for a shared scalar pointer, a per-row (vmapped, scatter-
        lowered) update for per-stream pointers.  K/V buffers and int8
        scales share the heads-major layout — the token axis sits at
        -2 for both (D or the singleton scale at -1)."""
        if per_row:
            return jax.vmap(lambda c, u, s: jax.lax.dynamic_update_slice(
                c, u, (0, s, 0)))(buf, new, cache_len)
        return jax.lax.dynamic_update_slice(buf, new,
                                            (0, 0, cache_len, 0))

    def layer_step(x, inputs):
        if kv_quantized:
            layer, kc, vc, ks, vs = inputs
        else:
            (layer, kc, vc), ks, vs = inputs, None, None
        h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = _rope(qlinear(h, layer["wq"]).reshape(B, S, H, Dh),
                  positions, cfg.rope_theta)
        k = _rope(qlinear(h, layer["wk"]).reshape(B, S, Hkv, Dh),
                  positions, cfg.rope_theta)
        v = qlinear(h, layer["wv"]).reshape(B, S, Hkv, Dh)
        # Heads-major for the cache: (B, S, Hkv, Dh) -> (B, Hkv, S, Dh).
        kT = k.transpose(0, 2, 1, 3)
        vT = v.transpose(0, 2, 1, 3)
        if kv_quantized:
            k8, k_sc = _quantize_kv(kT)
            v8, v_sc = _quantize_kv(vT)
            kc = write_kv(kc, k8)
            vc = write_kv(vc, v8)
            ks = write_kv(ks, k_sc)
            vs = write_kv(vs, v_sc)
        else:
            kc = write_kv(kc, kT.astype(kc.dtype))
            vc = write_kv(vc, vT.astype(vc.dtype))
        window = getattr(cfg, "sliding_window", None)
        if S == 1 and cfg.use_flash and mesh is None:
            # Decode hot path: fused Pallas kernel streams the cache
            # once with the masked online softmax (ops/decode.py); an
            # int8 cache streams at half width with its scales
            # commuted through the matmuls.
            from ..ops.decode import flash_decode_attention
            o = flash_decode_attention(
                q[:, 0], kc, vc, positions[:, 0], scale=scale,
                window=window, k_s=ks, v_s=vs).reshape(B, 1, H * Dh)
        elif (S == 1 and cfg.use_flash and mesh is not None
              and _can_flash_decode_on_mesh(mesh, B, H, Hkv,
                                            kc.shape[2])):
            # Same kernel under GSPMD: shard_map carves the batch over
            # dp and the (already tp-sharded) heads over tp, so the
            # kernel runs on local shards instead of forcing GSPMD to
            # replicate a raw pallas_call.
            o = _flash_decode_on_mesh(
                q[:, 0], kc, vc, positions[:, 0], mesh,
                scale, window, ks, vs).reshape(B, 1, H * Dh)
        else:
            if kv_quantized:
                # Compat/prefill path: dequantize for the einsum.
                kc_a = _dequantize_kv(kc, ks)
                vc_a = _dequantize_kv(vc, vs)
            else:
                kc_a, vc_a = kc, vc
            o = _cached_attention(q, kc_a, vc_a, positions, scale,
                                  window=window)
        x = x + qlinear(o, layer["wo"])
        x = mlp(x, layer)
        new_cache = ((kc, vc, ks, vs) if kv_quantized else (kc, vc))
        return x, new_cache

    if kv_quantized:
        xs = (params["layers"], cache["k"], cache["v"],
              cache["k_s"], cache["v_s"])
        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer_step, x, xs)
        new = {"k": k_new, "v": v_new, "k_s": ks_new, "v_s": vs_new}
    else:
        x, (k_new, v_new) = jax.lax.scan(
            layer_step, x, (params["layers"], cache["k"], cache["v"]))
        new = {"k": k_new, "v": v_new}
    if last_index is not None:
        idx = jnp.asarray(last_index, jnp.int32).reshape(B, 1, 1)
        x = jnp.take_along_axis(x, jnp.broadcast_to(
            idx, (B, 1, x.shape[-1])), axis=1)         # (B, 1, D)
    elif last_only:
        x = x[:, -1:]
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = qlinear(x, params["lm_head"]).astype(jnp.float32)
    return logits, new


# ----------------------------------------------------------------------
# sampling + the decode loop

def truncate_logits(logits, top_k: int | None = None,
                    top_p: float | None = None):
    """Mask ``logits`` (…, vocab) outside the ``top_k`` largest and/or
    the smallest ``top_p`` nucleus (Holtzman et al. 2019) to ``-inf``.

    Both filters are static-shape (sort + mask, no data-dependent
    shapes) so every consumer jits and scans.  Callers apply
    temperature *before* filtering — the nucleus depends on it.
    Shared by :func:`_sample` and the speculative path (which filters
    draft AND target distributions with the same knobs, making the
    accepted output distribution equal the truncated target's)."""
    if top_k is not None:
        # Mask everything below the k-th largest logit per row.
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        # Nucleus: keep the smallest prefix of the sorted distribution
        # with cumulative probability >= top_p.  The shifted cumsum
        # keeps every token whose *preceding* mass is < top_p, so the
        # top-1 token always survives.
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs
        cutoff_idx = jnp.sum((cum < top_p).astype(jnp.int32), axis=-1,
                             keepdims=True) - 1
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def _sample(logits, temperature: float, key, top_k: int | None = None,
            top_p: float | None = None):
    """logits: (B, vocab) -> (B,) int32.

    Greedy at ``temperature == 0``; otherwise categorical over the
    temperature-scaled logits, optionally truncated by
    :func:`truncate_logits`."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = truncate_logits(logits / temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(params: dict, prompt, cfg: TransformerConfig,
             max_new_tokens: int, *, temperature: float = 0.0,
             top_k: int | None = None, top_p: float | None = None,
             key=None, max_len: int | None = None, mesh=None,
             ep_axis: str = "ep", kv_quantized: bool = False):
    """Generate ``max_new_tokens`` continuations of ``prompt`` (B, S0).

    Greedy when ``temperature == 0`` (default), else categorical
    sampling with ``key`` (required), optionally truncated by ``top_k``
    and/or nucleus ``top_p`` (see :func:`_sample`).  With ``mesh``, the
    KV cache is created sharded (batch over ``dp``, KV heads over
    ``tp`` — pass tensor-parallel params sharded by
    ``param_shardings``).  Returns (B, S0+max_new_tokens) tokens.
    Jit-compatible: wrap in ``jax.jit`` with ``static_argnums``/closure
    for cfg and max_new_tokens, or use :func:`make_generate_fn`.
    """
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got "
                         f"{max_new_tokens}")
    if max_new_tokens == 0:
        return prompt
    if prompt.shape[1] == 0:
        raise ValueError("cannot generate from an empty prompt "
                         "(S == 0)")
    if temperature != 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    if top_k is not None and not 1 <= top_k <= cfg.vocab_size:
        raise ValueError(f"top_k must be in [1, vocab_size="
                         f"{cfg.vocab_size}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if key is None:
        key = jax.random.PRNGKey(0)
    B, S0 = prompt.shape
    T = max_len if max_len is not None else S0 + max_new_tokens
    if T < S0 + max_new_tokens:
        raise ValueError(f"max_len {T} < prompt {S0} + new "
                         f"{max_new_tokens}")
    cache = init_kv_cache(cfg, B, T, mesh=mesh,
                          quantized=kv_quantized)
    logits, cache = forward_with_cache(params, prompt, cache, 0, cfg,
                                       last_only=True, mesh=mesh,
                                       ep_axis=ep_axis)
    key, k0 = jax.random.split(key)
    tok = _sample(logits[:, -1], temperature, k0, top_k, top_p)

    def step(carry, i):
        cache, tok, key = carry
        logits, cache = forward_with_cache(
            params, tok[:, None], cache, S0 + i, cfg, mesh=mesh,
            ep_axis=ep_axis)
        key, ks = jax.random.split(key)
        nxt = _sample(logits[:, -1], temperature, ks, top_k, top_p)
        return (cache, nxt, key), tok

    (_, last, _), toks = jax.lax.scan(
        step, (cache, tok, key), jnp.arange(max_new_tokens - 1))
    out = jnp.moveaxis(toks, 0, 1) if max_new_tokens > 1 \
        else jnp.zeros((B, 0), jnp.int32)
    return jnp.concatenate([prompt, out, last[:, None]], axis=1)


def make_generate_fn(cfg: TransformerConfig, max_new_tokens: int, *,
                     temperature: float = 0.0, top_k: int | None = None,
                     top_p: float | None = None,
                     max_len: int | None = None,
                     mesh=None, ep_axis: str = "ep",
                     kv_quantized: bool = False):
    """A jitted ``(params, prompt, key) -> tokens`` closure."""

    def fn(params, prompt, key=None):
        return generate(params, prompt, cfg, max_new_tokens,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, key=key, max_len=max_len,
                        mesh=mesh, ep_axis=ep_axis,
                        kv_quantized=kv_quantized)

    return jax.jit(fn)


def prefill_chunked(params: dict, tokens, cache: dict,
                    cfg: TransformerConfig, *, chunk: int,
                    mesh=None, ep_axis: str = "ep"):
    """Prefill a long prompt in fixed-size chunks: peak activation
    memory during prefill drops from O(S_prompt) to O(chunk) while the
    KV cache fills identically (causal attention makes chunked and
    single-shot prefill mathematically the same computation).

    tokens: (B, S) with S divisible by ``chunk``.  Returns
    (last_logits (B, 1, V), cache) — the same contract ``last_only``
    prefill has, ready for the decode loop.  Wrap in ``jax.jit``
    (the chunk loop is a ``lax.scan``: one compile at chunk shape).
    """
    B, S = tokens.shape
    if S == 0:
        raise ValueError("cannot prefill an empty prompt (S == 0): the "
                         "zero-length scan would return all-zero "
                         "logits and seed decode with token 0")
    if S % chunk:
        raise ValueError(f"prompt length {S} not divisible by chunk "
                         f"{chunk}")
    n_chunks = S // chunk
    chunks = tokens.reshape(B, n_chunks, chunk).transpose(1, 0, 2)

    def step(carry, inp):
        cache, _ = carry
        i, tok = inp
        logits, cache = forward_with_cache(
            params, tok, cache, i * chunk, cfg, last_only=True,
            mesh=mesh, ep_axis=ep_axis)
        return (cache, logits), None

    zero_logits = jnp.zeros((B, 1, cfg.vocab_size), jnp.float32)
    (cache, last_logits), _ = jax.lax.scan(
        step, (cache, zero_logits), (jnp.arange(n_chunks), chunks))
    return last_logits, cache
