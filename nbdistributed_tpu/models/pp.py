"""Pipeline-parallel training for the transformer family.

Integrates the generic GPipe schedule (parallel/pipeline.py:
``pipeline_forward`` — stages as a mesh axis, microbatches hopping via
``ppermute`` under one ``lax.scan``) with the real model: the L
scan-stacked decoder layers are re-chunked into ``n_stages`` contiguous
stage slices, each stage applies its L/n_stages layers, and the
embedding / final norm / lm_head / loss stay outside the pipelined
region (they are position-wise or single matmuls — GSPMD handles them
as usual).  The backward pipelines in reverse through the transposed
ppermutes, so ``jax.grad`` of the pipelined loss is the whole training
story — no separate backward schedule to write.

The reference has no pipeline parallelism at all (SURVEY §2.3); this is
the model-integrated completion of the library-level strategy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..parallel.pipeline import pipeline_forward, shard_stage_params
from .transformer import (TransformerConfig, _rms_norm,
                          apply_optimizer_updates, make_layer_fn,
                          qlinear, shifted_xent)


def pp_stage_params(params: dict, n_stages: int) -> dict:
    """Re-chunk the (L, ...) layer stack into (n_stages, L/n_stages,
    ...) stage slices (``layers_pp``); everything else passes through.
    Shard ``layers_pp`` over the ``pp`` axis with
    :func:`~nbdistributed_tpu.parallel.pipeline.shard_stage_params`."""
    L = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible into {n_stages} "
                         f"pipeline stages")
    out = dict(params)
    out["layers_pp"] = jax.tree_util.tree_map(
        lambda a: a.reshape(n_stages, L // n_stages, *a.shape[1:]),
        out.pop("layers"))
    return out


def pp_unstage_params(params_pp: dict) -> dict:
    """Inverse of :func:`pp_stage_params`."""
    out = dict(params_pp)
    out["layers"] = jax.tree_util.tree_map(
        lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]),
        out.pop("layers_pp"))
    return out


def _stage_fn(cfg: TransformerConfig, positions):
    """One pipeline stage = scan over this stage's layer slice (the
    per-layer recipe is transformer.make_layer_fn — one definition)."""
    one_layer = make_layer_fn(cfg, positions)

    def stage(stage_layers, x):
        return jax.lax.scan(lambda x, l: (one_layer(x, l), None),
                            x, stage_layers)[0]

    return stage


def _reject_segments(batch) -> None:
    """Packed-document batches are not plumbed through the pipelined
    losses yet; silently reading only batch["tokens"] would reintroduce
    the cross-document attention leak segment masking exists to stop —
    fail loudly instead (the sp path does the same)."""
    if isinstance(batch, dict) and batch.get("segments") is not None:
        raise ValueError(
            'batch["segments"] (packed documents) is not supported by '
            "the pipelined losses yet — use the plain or dp/tp train "
            "steps for packed batches, or drop the segments")


def pp_loss_fn(params_pp: dict, batch, cfg: TransformerConfig, mesh,
               *, pp_axis: str = "pp",
               n_microbatches: int | None = None):
    """Next-token cross-entropy with the layer stack pipelined over
    ``mesh[pp_axis]``.  Same logits-shift tail as
    ``transformer.loss_fn`` (shared ``shifted_xent``); batch rows are
    the microbatch unit, so ``n_microbatches`` (default: n_stages)
    must divide the batch size."""
    _reject_segments(batch)
    tokens = batch["tokens"]
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    x = params_pp["embed"][tokens].astype(cfg.dtype)
    # Microbatches slice the batch dim, so each microbatch's positions
    # are the same broadcast arange — safe to close over per-microbatch
    # shape (B/n_micro, S).
    n_stages = mesh.shape[pp_axis]
    n_micro = n_microbatches if n_microbatches is not None else n_stages
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by {n_micro} "
                         f"microbatches")
    mb_positions = positions[: B // n_micro]
    y = pipeline_forward(_stage_fn(cfg, mb_positions),
                         params_pp["layers_pp"], x, mesh, axis=pp_axis,
                         n_microbatches=n_micro)
    y = _rms_norm(y, params_pp["final_norm"], cfg.norm_eps)
    logits = qlinear(y, params_pp["lm_head"]).astype(jnp.float32)
    return shifted_xent(logits, tokens)


def make_pp_train_step(cfg: TransformerConfig, optimizer, mesh, *,
                       pp_axis: str = "pp",
                       n_microbatches: int | None = None):
    """Returns ``step(params_pp, opt_state, batch) -> (params_pp,
    opt_state, loss)`` with the layer stack pipelined.  Prepare params
    with :func:`pp_stage_params` + ``shard_stage_params`` on
    ``layers_pp`` (embed/norms/lm_head replicate); jit as usual."""

    def step(params_pp, opt_state, batch):
        loss, grads = jax.value_and_grad(pp_loss_fn)(
            params_pp, batch, cfg, mesh, pp_axis=pp_axis,
            n_microbatches=n_microbatches)
        updates, opt_state = optimizer.update(grads, opt_state,
                                              params_pp)
        return (apply_optimizer_updates(params_pp, updates), opt_state,
                loss)

    return step


def make_pp_1f1b_train_step(cfg: TransformerConfig, optimizer, mesh, *,
                            pp_axis: str = "pp",
                            n_microbatches: int | None = None,
                            batch_axis: str | None = None):
    """The 1F1B (PipeDream-flush) analog of :func:`make_pp_train_step`:
    same contract, O(stages) in-flight activations instead of O(M).

    The pipelined region covers the layer stack; the embedding (below)
    and final-norm + lm_head + loss (above) train too: the tail rides
    ``make_pipeline_1f1b_full``'s tail-parameter gradients, and the
    embedding gradient is folded per microbatch by a scatter-add
    ``dx_sink`` as each input-cotangent exits stage 0's backward — no
    O(M) dx buffer.  Loss and gradients match
    :func:`make_pp_train_step` (same per-microbatch-mean caveat as the
    GPipe path: equal microbatch sizes make the mean exact)."""
    from ..parallel.pipeline import make_pipeline_1f1b_full

    n_stages = mesh.shape[pp_axis]
    n_micro = (n_microbatches if n_microbatches is not None
               else n_stages)
    # The pipeline fn is jit-wrapped per construction; cache it by the
    # shapes it closes over so eager (un-jitted) step() calls reuse the
    # compiled program instead of rebuilding it every training step.
    fn_cache: dict = {}

    def step(params_pp, opt_state, batch):
        _reject_segments(batch)
        tokens = batch["tokens"]
        B, S = tokens.shape
        if B % n_micro:
            raise ValueError(f"batch {B} not divisible by {n_micro} "
                             f"microbatches")
        # (1, S): broadcasts over ANY local row count — with a dp
        # batch_axis each shard sees B/n_micro/dp rows, so a
        # full-row-count positions array would mis-broadcast in RoPE.
        mb_positions = jnp.arange(S)[None]

        def tail_fn(tp, y, bt_m):
            y = _rms_norm(y, tp["final_norm"], cfg.norm_eps)
            logits = qlinear(y, tp["lm_head"]).astype(jnp.float32)
            return shifted_xent(logits, bt_m["tokens"])

        embed = params_pp["embed"]
        # Close over shape/dtype only: capturing the embed ARRAY in the
        # cached lambda would pin the first call's (vocab, d_model)
        # matrix alive for the step function's lifetime (and
        # zeros_like would drag its Auto-mesh sharding into the Manual
        # shard_map region).
        e_shape, e_dtype = embed.shape, embed.dtype

        def dx_sink(acc, dx, bt_m):
            return acc.at[bt_m["tokens"]].add(dx.astype(acc.dtype))

        key = (B, S, e_shape, str(e_dtype))
        if key not in fn_cache:
            fn_cache[key] = make_pipeline_1f1b_full(
                _stage_fn(cfg, mb_positions), tail_fn, mesh,
                axis=pp_axis, n_microbatches=n_micro, dx_sink=dx_sink,
                dx_init=lambda: jnp.zeros(e_shape, e_dtype),
                batch_axis=batch_axis)
        fn = fn_cache[key]
        x = embed[tokens].astype(cfg.dtype)
        tp = {"final_norm": params_pp["final_norm"],
              "lm_head": params_pp["lm_head"]}
        loss, g_layers, g_tail, g_embed = fn(
            tp, params_pp["layers_pp"], x, batch)
        grads = {"embed": g_embed, "layers_pp": g_layers, **g_tail}
        updates, opt_state = optimizer.update(grads, opt_state,
                                              params_pp)
        return (apply_optimizer_updates(params_pp, updates), opt_state,
                loss)

    return step


def pp_apply_shardings(params_pp: dict, mesh, *, pp_axis: str = "pp"):
    """Place ``layers_pp`` stage-sharded over ``pp_axis`` and replicate
    the rest — the standard layout for :func:`make_pp_train_step`."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = dict(params_pp)
    out["layers_pp"] = shard_stage_params(params_pp["layers_pp"], mesh,
                                          axis=pp_axis)
    rep = NamedSharding(mesh, P())
    for name in ("embed", "final_norm", "lm_head"):
        out[name] = jax.device_put(params_pp[name], rep)
    return out
