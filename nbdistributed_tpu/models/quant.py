"""Int8 weight-only quantization for inference.

The reference has no quantization story (users bring torch models); on
TPU, single-batch decode is HBM-bandwidth-bound — every step streams
every weight matrix once — so storing weights int8 halves the dominant
traffic and roughly doubles decode throughput headroom.

Design (TPU-first):

* **Symmetric per-output-channel** scales: ``W ≈ q8 * s`` with
  ``s[o] = max|W[:, o]| / 127``.  Because ``s`` is constant along the
  contraction dim, it commutes with the matmul:
  ``x @ (q8 * s) == (x @ q8) * s`` — so the kernel-visible weight is
  the *raw int8 array* (half the HBM bytes) and the rescale is one
  cheap per-column multiply on the much smaller activation.  XLA fuses
  the int8→bf16 convert into the dot's operand read (VMEM), so no
  dequantized copy ever exists in HBM.
* Quantized leaves keep the pytree structure: a targeted weight becomes
  ``{"q8": int8 (..., d_in, d_out), "s": fp32 (..., 1, d_out)}``.
  ``lax.scan`` over stacked layers slices both members along L like any
  other pytree subtree, and sharding rules map onto the same Megatron
  splits (``quantized_shardings``).
* The matmul sites in the model dispatch through
  :func:`transformer.qlinear`, so the same forward / KV-cache decode
  path serves fp and quantized params; training stays full-precision
  (quantize after training / loading).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .lora import ATTN_TARGETS  # one definition, shared with LoRA
from .transformer import is_quantized  # noqa: F401  (re-export)

# Weights worth quantizing: all the big matmuls.  Norm gains stay fp32,
# the embedding stays fp (it is a gather, not a matmul; its lm_head tie
# is separate here).
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w, *, axis: int = -2) -> dict:
    """Symmetric per-output-channel int8 quantization of one weight.

    ``axis`` is the contraction (d_in) axis reduced over when choosing
    scales; the last axis is the output-channel axis the scales follow.
    """
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q8 = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return {"q8": q8, "s": s}


def dequantize_weight(qw: dict, dtype=jnp.float32):
    return (qw["q8"].astype(jnp.float32) * qw["s"]).astype(dtype)


def quantize_params(params: dict, targets=DEFAULT_TARGETS,
                    quantize_lm_head: bool = True) -> dict:
    """Params pytree with the targeted per-layer weights (and optionally
    ``lm_head``) replaced by int8 ``{"q8", "s"}`` leaves.  Everything
    else (embed, norms) is passed through by reference."""
    layers = dict(params["layers"])
    for name in targets:
        if name not in layers:
            raise ValueError(f"unknown quantization target {name!r}; "
                             f"layer weights: {sorted(params['layers'])}")
        layers[name] = quantize_weight(layers[name])
    out = dict(params)
    out["layers"] = layers
    if quantize_lm_head:
        out["lm_head"] = quantize_weight(params["lm_head"])
    return out


def _q_spec(spec: P) -> dict:
    """Spec pair for a quantized leaf: ``q8`` keeps the weight's spec;
    ``s`` (shaped (..., 1, d_out)) keeps the leading/output entries
    with the contraction entry pinned to None (its axis is size 1)."""
    return {"q8": spec, "s": P(*spec[:-2], None, spec[-1])}


def quantized_shardings(rules: dict, targets=DEFAULT_TARGETS,
                        quantize_lm_head: bool = True) -> dict:
    """Map tensor-parallel rules onto a quantized pytree (see
    :func:`_q_spec`).  ``targets``/``quantize_lm_head`` must match what
    was passed to :func:`quantize_params`, or device_put will die on a
    pytree structure mismatch far from the mistake."""
    layers = dict(rules["layers"])
    for name in targets:
        if name not in layers:
            raise ValueError(f"unknown quantization target {name!r}; "
                             f"layer weights: {sorted(rules['layers'])}")
        layers[name] = _q_spec(layers[name])
    out = dict(rules)
    out["layers"] = layers
    if quantize_lm_head:
        out["lm_head"] = _q_spec(rules["lm_head"])
    return out


EXPERT_TARGETS = ("w_gate", "w_up", "w_down")


def quantize_moe_params(params: dict,
                        quantize_lm_head: bool = True) -> dict:
    """MoE-family variant: attention projections + the expert SwiGLU
    weights (the bulk of a Mixtral-class model's bytes) go int8; the
    router stays fp32 (tiny, and routing is precision-sensitive).
    ``parallel.expert.moe_ffn`` dispatches on the quantized leaves the
    same way ``qlinear`` does."""
    out = quantize_params(params, targets=ATTN_TARGETS,
                          quantize_lm_head=quantize_lm_head)
    moe = dict(out["layers"]["moe"])
    for name in EXPERT_TARGETS:
        moe[name] = quantize_weight(moe[name])
    out["layers"]["moe"] = moe
    return out


def quantized_moe_shardings(rules: dict,
                            quantize_lm_head: bool = True) -> dict:
    """Sharding rules matching :func:`quantize_moe_params` (same
    structural transform as :func:`quantized_shardings`, applied to the
    attention weights and the ``moe`` expert subtree)."""
    out = quantized_shardings(rules, targets=ATTN_TARGETS,
                              quantize_lm_head=quantize_lm_head)
    moe = dict(out["layers"]["moe"])
    for name in EXPERT_TARGETS:
        moe[name] = _q_spec(moe[name])
    out["layers"]["moe"] = moe
    return out


def quantization_error(params: dict, qparams: dict) -> dict:
    """Per-weight relative Frobenius error of the quantization — a
    quick fidelity report (int8 per-channel is typically ~0.2-0.5%)."""
    report = {}

    def _rel(w, qw):
        wf = w.astype(jnp.float32)
        err = dequantize_weight(qw) - wf
        return float(jnp.linalg.norm(err) / jnp.linalg.norm(wf))

    def _walk(prefix, ref_tree, q_tree):
        for name, leaf in q_tree.items():
            if is_quantized(leaf):
                report[prefix + name] = _rel(ref_tree[name], leaf)
            elif isinstance(leaf, dict):
                # Nested weight groups (the MoE 'moe' subtree).
                _walk(prefix + name + ".", ref_tree[name], leaf)

    _walk("", params["layers"], qparams["layers"])
    if is_quantized(qparams.get("lm_head")):
        report["lm_head"] = _rel(params["lm_head"], qparams["lm_head"])
    return report
