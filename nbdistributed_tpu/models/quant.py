"""Int8 weight-only quantization for inference.

The reference has no quantization story (users bring torch models); on
TPU, single-batch decode is HBM-bandwidth-bound — every step streams
every weight matrix once — so storing weights int8 halves the dominant
traffic and roughly doubles decode throughput headroom.

Design (TPU-first):

* **Symmetric per-output-channel** scales: ``W ≈ q8 * s`` with
  ``s[o] = max|W[:, o]| / 127``.  Because ``s`` is constant along the
  contraction dim, it commutes with the matmul:
  ``x @ (q8 * s) == (x @ q8) * s`` — so the kernel-visible weight is
  the *raw int8 array* (half the HBM bytes) and the rescale is one
  cheap per-column multiply on the much smaller activation.  XLA fuses
  the int8→bf16 convert into the dot's operand read (VMEM), so no
  dequantized copy ever exists in HBM.
* Quantized leaves keep the pytree structure: a targeted weight becomes
  ``{"q8": int8 (..., d_in, d_out), "s": fp32 (..., 1, d_out)}``.
  ``lax.scan`` over stacked layers slices both members along L like any
  other pytree subtree, and sharding rules map onto the same Megatron
  splits (``quantized_shardings``).
* The matmul sites in the model dispatch through
  :func:`transformer.qlinear`, so the same forward / KV-cache decode
  path serves fp and quantized params; training stays full-precision
  (quantize after training / loading).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .lora import ATTN_TARGETS  # one definition, shared with LoRA
from .transformer import (_pack_nibbles,  # noqa: F401  (re-exports)
                          _unpack_nibbles, is_quantized, is_quantized4)

# Weights worth quantizing: all the big matmuls.  Norm gains stay fp32,
# the embedding stays fp (it is a gather, not a matmul; its lm_head tie
# is separate here).
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w, *, axis: int = -2) -> dict:
    """Symmetric per-output-channel int8 quantization of one weight.

    ``axis`` is the contraction (d_in) axis reduced over when choosing
    scales; the last axis is the output-channel axis the scales follow.
    """
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q8 = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return {"q8": q8, "s": s}


def dequantize_weight(qw: dict, dtype=jnp.float32):
    return (qw["q8"].astype(jnp.float32) * qw["s"]).astype(dtype)


# ---------------------------------------------------------------- int4
# Int4 weight-only: HALF the int8 bytes again — decode streams every
# weight per token, so bytes/token is the throughput.  Two design
# points differ from int8:
#
# * **Grouped scales**: 15 levels need finer scale granularity than
#   per-output-channel; scales are per (contraction-group, out-channel)
#   with ``group`` input rows per scale (default 64 — divides every
#   family config's d_model/d_ff).  Grouped scales no longer commute
#   with the whole matmul, so qlinear's int4 path runs one small
#   batched einsum per group block and combines with the scales after
#   (decode is bandwidth-bound; the extra reduction is noise).
# * **Explicit nibble packing in uint8** (two weights per byte along
#   the contraction axis), NOT the native jnp.int4 dtype: jax arrays
#   report int4 at one byte per element on the backends here, so the
#   native dtype's HBM claim is unverifiable off-chip — the packed
#   uint8 array is exactly d_in/2 x d_out bytes on every backend, and
#   the unpack (shift/mask/sign-extend) is elementwise arithmetic XLA
#   fuses into the consumer.  The pack/unpack pair is defined beside
#   its qlinear consumer in transformer.py (single definition of the
#   layout) and re-exported here.


def quantize_weight4(w, *, group: int = 64) -> dict:
    """Symmetric per-(group, output-channel) int4 quantization:
    ``{"q4": uint8 (..., d_in/2, d_out) nibble-packed,
    "s": fp32 (..., G, 1, d_out)}`` with ``G = d_in // group``."""
    wf = w.astype(jnp.float32)
    d_in = wf.shape[-2]
    if d_in % group or group % 2:
        raise ValueError(f"group {group} must be even and divide "
                         f"d_in {d_in}")
    g_shape = (*wf.shape[:-2], d_in // group, group, wf.shape[-1])
    wg = wf.reshape(g_shape)
    amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(wg / s), -7, 7).astype(jnp.int32)
    q = q.reshape(wf.shape)
    return {"q4": _pack_nibbles(q), "s": s}


def dequantize_weight4(qw: dict, dtype=jnp.float32):
    q = _unpack_nibbles(qw["q4"], jnp.float32)
    s = qw["s"]
    G = s.shape[-3]
    d_in = q.shape[-2]
    wg = q.reshape(*q.shape[:-2], G, d_in // G, q.shape[-1]) * s
    return wg.reshape(*q.shape[:-2], d_in, q.shape[-1]).astype(dtype)


def quantize_params4(params: dict, targets=DEFAULT_TARGETS,
                     quantize_lm_head: bool = True,
                     group: int = 64) -> dict:
    """Int4 variant of :func:`quantize_params` (same pytree
    transform; leaves become ``{"q4", "s"}``)."""
    return _map_targets(
        params, lambda w: quantize_weight4(w, group=group), targets,
        quantize_lm_head)


def _q_spec4(spec: P) -> dict:
    """Spec pair for an int4 leaf: the packed array keeps the weight's
    spec (packing halves the contraction extent, never its sharding);
    the grouped scale replicates over the contraction shard — G is
    d_in/group and need not divide a tp axis (wo at smol scale has
    G=9), and scales are ~1.5 % of the weight bytes, so replication
    costs nothing where uneven sharding would refuse to place."""
    return {"q4": spec, "s": P(*spec[:-2], None, None, spec[-1])}


def quantized_shardings4(rules: dict, targets=DEFAULT_TARGETS,
                         quantize_lm_head: bool = True) -> dict:
    """Sharding rules matching :func:`quantize_params4`."""
    return _map_targets(rules, _q_spec4, targets, quantize_lm_head)


def _map_targets(tree: dict, leaf_fn, targets,
                 include_lm_head: bool) -> dict:
    """Apply ``leaf_fn`` to the targeted ``layers`` weights (and
    optionally ``lm_head``) of a params-or-rules pytree — the single
    structural transform all four quantize/sharding variants share.
    Everything else passes through by reference."""
    layers = dict(tree["layers"])
    for name in targets:
        if name not in layers:
            raise ValueError(f"unknown quantization target {name!r}; "
                             f"layer weights: {sorted(tree['layers'])}")
        layers[name] = leaf_fn(layers[name])
    out = dict(tree)
    out["layers"] = layers
    if include_lm_head:
        out["lm_head"] = leaf_fn(tree["lm_head"])
    return out


def quantize_params(params: dict, targets=DEFAULT_TARGETS,
                    quantize_lm_head: bool = True) -> dict:
    """Params pytree with the targeted per-layer weights (and optionally
    ``lm_head``) replaced by int8 ``{"q8", "s"}`` leaves.  Everything
    else (embed, norms) is passed through by reference."""
    return _map_targets(params, quantize_weight, targets,
                        quantize_lm_head)


def _q_spec(spec: P) -> dict:
    """Spec pair for a quantized leaf: ``q8`` keeps the weight's spec;
    ``s`` (shaped (..., 1, d_out)) keeps the leading/output entries
    with the contraction entry pinned to None (its axis is size 1)."""
    return {"q8": spec, "s": P(*spec[:-2], None, spec[-1])}


def quantized_shardings(rules: dict, targets=DEFAULT_TARGETS,
                        quantize_lm_head: bool = True) -> dict:
    """Map tensor-parallel rules onto a quantized pytree (see
    :func:`_q_spec`).  ``targets``/``quantize_lm_head`` must match what
    was passed to :func:`quantize_params`, or device_put will die on a
    pytree structure mismatch far from the mistake."""
    return _map_targets(rules, _q_spec, targets, quantize_lm_head)


EXPERT_TARGETS = ("w_gate", "w_up", "w_down")


def quantize_moe_params(params: dict,
                        quantize_lm_head: bool = True) -> dict:
    """MoE-family variant: attention projections + the expert SwiGLU
    weights (the bulk of a Mixtral-class model's bytes) go int8; the
    router stays fp32 (tiny, and routing is precision-sensitive).
    ``parallel.expert.moe_ffn`` dispatches on the quantized leaves the
    same way ``qlinear`` does."""
    out = quantize_params(params, targets=ATTN_TARGETS,
                          quantize_lm_head=quantize_lm_head)
    moe = dict(out["layers"]["moe"])
    for name in EXPERT_TARGETS:
        moe[name] = quantize_weight(moe[name])
    out["layers"]["moe"] = moe
    return out


def quantized_moe_shardings(rules: dict,
                            quantize_lm_head: bool = True) -> dict:
    """Sharding rules matching :func:`quantize_moe_params` (same
    structural transform as :func:`quantized_shardings`, applied to the
    attention weights and the ``moe`` expert subtree)."""
    out = quantized_shardings(rules, targets=ATTN_TARGETS,
                              quantize_lm_head=quantize_lm_head)
    moe = dict(out["layers"]["moe"])
    for name in EXPERT_TARGETS:
        moe[name] = _q_spec(moe[name])
    out["layers"]["moe"] = moe
    return out


def quantization_error(params: dict, qparams: dict) -> dict:
    """Per-weight relative Frobenius error of the quantization — a
    quick fidelity report (int8 per-channel is typically ~0.2-0.5 %;
    int4 group-64 ~2-4 %).  Handles both leaf kinds."""
    report = {}

    def _deq(qw):
        return (dequantize_weight4(qw) if is_quantized4(qw)
                else dequantize_weight(qw))

    def _rel(w, qw):
        wf = w.astype(jnp.float32)
        err = _deq(qw) - wf
        return float(jnp.linalg.norm(err) / jnp.linalg.norm(wf))

    def _walk(prefix, ref_tree, q_tree):
        for name, leaf in q_tree.items():
            if is_quantized(leaf) or is_quantized4(leaf):
                report[prefix + name] = _rel(ref_tree[name], leaf)
            elif isinstance(leaf, dict):
                # Nested weight groups (the MoE 'moe' subtree).
                _walk(prefix + name + ".", ref_tree[name], leaf)

    _walk("", params["layers"], qparams["layers"])
    head = qparams.get("lm_head")
    if is_quantized(head) or is_quantized4(head):
        report["lm_head"] = _rel(params["lm_head"], head)
    return report
