"""Mixture-of-experts decoder transformer (Mixtral-style), TPU-first.

Beyond-parity model family (the reference ships no models and no MoE —
SURVEY §2.3); reuses the dense family's attention/RMSNorm/rotary stack
(:mod:`nbdistributed_tpu.models.transformer`) and swaps the SwiGLU MLP
for the expert-parallel MoE layer
(:mod:`nbdistributed_tpu.parallel.expert`).  Layers are stacked on a
leading (n_layers,) axis and scanned, with the load-balance aux loss
accumulated through the scan carry.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.expert import init_moe_params, moe_ffn, moe_param_shardings
from ..utils import fan_in_normal
from .transformer import (TransformerConfig, _attention_block,
                          _preset, _rms_norm, is_quantized,
                          is_quantized4, qlinear,
                          shifted_xent)


@dataclasses.dataclass(frozen=True)
class MoEConfig(TransformerConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    lb_coef: float = 0.01
    # "dense" = one-hot dispatch einsums (O(T^2) in tokens, the
    # oracle); "sparse" = sort/segment routing (linear in tokens,
    # bit-identical drops); "dropless" = MegaBlocks-style ragged_dot
    # grouped matmuls (no per-expert capacity, no drops; over an ep
    # mesh axis it becomes the shard-capacity hybrid — static
    # per-shard exchange, drops only at whole-shard overflow) — see
    # parallel/expert.moe_ffn for the FLOP accounting and semantics.
    moe_dispatch: str = "dense"

    def num_params(self) -> int:
        emb = self.vocab_size * self.d_model
        attn = (self.d_model * self.n_heads * self.head_dim
                + 2 * self.d_model * self.n_kv_heads * self.head_dim
                + self.n_heads * self.head_dim * self.d_model)
        router = self.d_model * self.n_experts
        experts = self.n_experts * 3 * self.d_model * self.d_ff
        norms = 2 * self.d_model
        return (emb * 2 + self.d_model
                + self.n_layers * (attn + router + experts + norms))


def tiny_moe_config(**kw) -> MoEConfig:
    # Caller kwargs override the preset (same contract as the dense
    # factories — shared _preset helper).
    return _preset(kw, cls=MoEConfig, vocab_size=512, d_model=128,
                   n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256,
                   max_seq_len=256, n_experts=4, top_k=2)


def mixtral_8x7b_config(**kw) -> MoEConfig:
    return _preset(kw, cls=MoEConfig, vocab_size=32000, d_model=4096,
                   n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
                   max_seq_len=4096, n_experts=8, top_k=2)


def init_moe_model(key, cfg: MoEConfig) -> dict:
    """Parameter pytree; per-layer arrays carry a leading (n_layers,)
    axis (attention identical to the dense family, MLP -> experts)."""
    k_emb, k_attn, k_moe, k_out = jax.random.split(key, 4)
    D, H, Hkv, Dh, L = (cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
                        cfg.head_dim, cfg.n_layers)

    def normal(k, shape, fan_in):
        return fan_in_normal(k, shape, fan_in, cfg.dtype)

    ks = jax.random.split(k_attn, 4)
    moe = jax.vmap(lambda k: init_moe_params(
        k, D, cfg.d_ff, cfg.n_experts, cfg.dtype))(
            jax.random.split(k_moe, L))
    return {
        "embed": normal(k_emb, (cfg.vocab_size, D), 1.0),
        "layers": {
            "attn_norm": jnp.ones((L, D), jnp.float32),
            "wq": normal(ks[0], (L, D, H * Dh), D),
            "wk": normal(ks[1], (L, D, Hkv * Dh), D),
            "wv": normal(ks[2], (L, D, Hkv * Dh), D),
            "wo": normal(ks[3], (L, H * Dh, D), H * Dh),
            "mlp_norm": jnp.ones((L, D), jnp.float32),
            "moe": moe,
        },
        "final_norm": jnp.ones((D,), jnp.float32),
        "lm_head": normal(k_out, (D, cfg.vocab_size), D),
    }


def moe_model_shardings(cfg: MoEConfig, ep_axis: str = "ep",
                        tp_axis: str | None = "tp") -> dict:
    """Sharding rules: attention tensor-parallel over ``tp`` (as in the
    dense family), experts over ``ep``."""
    return {
        "embed": P(None, tp_axis),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, tp_axis),
            "wk": P(None, None, tp_axis),
            "wv": P(None, None, tp_axis),
            "wo": P(None, tp_axis, None),
            "mlp_norm": P(None, None),
            "moe": moe_param_shardings(ep_axis, None, leading=(None,)),
        },
        "final_norm": P(None),
        "lm_head": P(None, tp_axis),
    }


def _moe_mlp_block(x, layer, cfg: MoEConfig, mesh, ep_axis: str,
                  token_mask=None, token_axes: tuple = ("dp",)):
    """The MoE feed-forward residual block (the expert analog of
    ``transformer._mlp_block``) — the single definition shared by the
    training forward and the cached generation path.  ``token_mask``:
    masked tokens pass through the residual untouched and take no
    expert capacity (see expert.moe_ffn).  ``token_axes``: the mesh
    axes the flattened token dim is sharded over — the training
    forward adds the sequence-parallel axis so the hierarchical
    dropless path keeps its routing sorts sequence-sharded (the
    decode path's per-step tokens are dp-sharded only)."""
    h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    y, layer_aux = moe_ffn(h, layer["moe"], top_k=cfg.top_k,
                           capacity_factor=cfg.capacity_factor,
                           mesh=mesh, ep_axis=ep_axis,
                           dispatch_mode=cfg.moe_dispatch,
                           token_mask=token_mask,
                           token_axes=token_axes)
    return x + y, layer_aux


def moe_forward_hidden(params: dict, tokens, cfg: MoEConfig, *,
                       mesh=None, ep_axis: str = "ep", positions=None,
                       sp=None, segment_ids=None):
    """tokens (B, S) int32 -> (final-norm hidden (B, S, D) in
    ``cfg.dtype``, aux scalar) — everything before the lm_head, for
    the chunked-vocab loss tail (ops/xent.py)."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    x = params["embed"][tokens].astype(cfg.dtype)

    tok_axes = ("dp",) + ((sp.axis,) if sp is not None else ())

    def layer_step(carry, layer):
        x, aux = carry
        x = _attention_block(x, layer, cfg, positions, sp,
                             segment_ids)
        x, layer_aux = _moe_mlp_block(x, layer, cfg, mesh, ep_axis,
                                      token_axes=tok_axes)
        return (x, aux + layer_aux), None

    (x, aux), _ = jax.lax.scan(layer_step, (x, jnp.float32(0.0)),
                               params["layers"])
    return _rms_norm(x, params["final_norm"], cfg.norm_eps), \
        aux / cfg.n_layers


def moe_forward(params: dict, tokens, cfg: MoEConfig, *, mesh=None,
                ep_axis: str = "ep", positions=None, sp=None,
                segment_ids=None):
    """tokens (B, S) int32 -> (logits (B, S, vocab) fp32, aux scalar).

    ``sp`` (a ``transformer.SeqParallel``) routes attention through
    ring/Ulysses sequence parallelism, exactly as in the dense family —
    the MoE dispatch is token-wise, so GSPMD keeps it sequence-sharded
    for free.  Composes with ``mesh``/``ep_axis`` expert placement.
    ``segment_ids``: packed-document attention masking (the attention
    stack is shared with the dense family); expert dispatch is
    unaffected — every real token routes regardless of its document."""
    x, aux = moe_forward_hidden(params, tokens, cfg, mesh=mesh,
                                ep_axis=ep_axis, positions=positions,
                                sp=sp, segment_ids=segment_ids)
    return qlinear(x, params["lm_head"]).astype(jnp.float32), aux


def moe_loss_fn(params, batch, cfg: MoEConfig, *, mesh=None,
                ep_axis: str = "ep", sp=None):
    """Next-token cross-entropy + load-balance auxiliary.  Same
    logits-shift convention as the dense family (shared
    ``shifted_xent``): the forward runs on all S tokens, keeping S
    divisible by a sequence-parallel axis.  Vs the old input-shift
    convention: the xent term is identical for the dense model always
    and for MoE at lossless capacity (capacity_factor >=
    n_experts/top_k — the extra final position cannot evict anyone);
    under tight capacity the final tokens compete for expert slots
    like any others.  The load-balance *aux* term is never bit-equal —
    it now averages router stats over T = B*S tokens instead of
    B*(S-1) (and capacity itself scales with T) — a deliberate, tiny
    objective change, not an oversight.

    ``batch["segments"]`` engages the packed-document contract as in
    the dense family: cross-document attention masked, per-document
    RoPE restart, boundary targets dropped."""
    from .transformer import packed_positions

    tokens = batch["tokens"]
    seg = batch.get("segments") if isinstance(batch, dict) else None
    positions = packed_positions(seg) if seg is not None else None
    if (cfg.ce_chunk is not None and sp is None and mesh is None
            and not is_quantized(params["lm_head"])
            and not is_quantized4(params["lm_head"])):
        # Chunked-vocab tail, same contract as the dense family
        # (transformer.loss_fn): the (B, S, V) logits never
        # materialize; tests pin the two paths equal.
        from ..ops.xent import shifted_chunked_xent
        x, aux = moe_forward_hidden(params, tokens, cfg, mesh=mesh,
                                    ep_axis=ep_axis,
                                    positions=positions, sp=sp,
                                    segment_ids=seg)
        return (shifted_chunked_xent(x, params["lm_head"], tokens,
                                     segment_ids=seg,
                                     chunk=cfg.ce_chunk)
                + cfg.lb_coef * aux)
    logits, aux = moe_forward(params, tokens, cfg, mesh=mesh,
                              ep_axis=ep_axis, positions=positions,
                              sp=sp, segment_ids=seg)
    return (shifted_xent(logits, tokens, segment_ids=seg)
            + cfg.lb_coef * aux)
