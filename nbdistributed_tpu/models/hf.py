"""HuggingFace interop: load Llama-family checkpoints into this
framework's transformer.

The reference's whole demo workflow is HF-centric (its notebook loads
SmolLM2-135M with ``transformers`` and trains it through Accelerate —
reference: 00_accelerate.ipynb cells 10, 28), so a user switching to
this framework needs their HF checkpoints to come along.  This module
converts any Llama-architecture ``transformers`` model (Llama 1/2/3,
SmolLM2, TinyLlama, ...) into the layer-stacked pytree that
:func:`~nbdistributed_tpu.models.transformer.forward` consumes — after
which every TPU path here applies: tp/dp sharding via
:func:`param_shardings`, flash attention, the KV-cache generate loop,
checkpointing.

Conventions verified against ``transformers`` (tests/unit/test_hf.py
checks logits parity against the torch forward):

* torch ``nn.Linear`` stores (out_features, in_features); our params
  right-multiply, so every projection transposes.
* Head ordering: HF's q/k/v rows are [head0 x Dh, head1 x Dh, ...] —
  transposing preserves our ``reshape(B, S, H, Dh)`` grouping.
* RoPE: HF's rotate-half with cos/sin repeated over both halves is
  algebraically identical to our half-split form (same
  theta^(-2i/head_dim) frequencies).
* ``tie_word_embeddings`` (SmolLM2 does) -> ``lm_head = embed.T``.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from .transformer import TransformerConfig


def config_from_hf(hf_config) -> TransformerConfig:
    """Map a ``transformers`` Llama-family config onto
    :class:`TransformerConfig`.  Rejects rope-scaling variants this
    forward does not implement rather than silently mis-rotating."""
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        rope_type = (scaling.get("rope_type")
                     or scaling.get("type") or "?")
        if rope_type != "default":
            raise ValueError(
                f"rope_scaling type {rope_type!r} is not supported "
                "(plain rotary only); use a base-rope checkpoint")
    if getattr(hf_config, "attention_bias", False):
        raise ValueError("attention_bias=True checkpoints are not "
                         "supported (Llama family uses bias-free "
                         "projections)")
    if getattr(hf_config, "mlp_bias", False):
        raise ValueError("mlp_bias=True checkpoints are not supported")
    head_dim = getattr(hf_config, "head_dim", None)
    expect = hf_config.hidden_size // hf_config.num_attention_heads
    if head_dim is not None and head_dim != expect:
        raise ValueError(
            f"head_dim {head_dim} != hidden_size/n_heads {expect}: "
            "decoupled head_dim is not supported")
    # Some HF configs (e.g. Qwen2) carry sliding_window but gate it
    # off with use_sliding_window=False.
    window = getattr(hf_config, "sliding_window", None)
    if not getattr(hf_config, "use_sliding_window", True):
        window = None
    return TransformerConfig(
        sliding_window=window,
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        d_ff=hf_config.intermediate_size,
        max_seq_len=getattr(hf_config, "max_position_embeddings", 2048),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
    )


def _np(t) -> np.ndarray:
    """torch tensor (any dtype/device) -> float32 numpy."""
    return t.detach().to("cpu").float().numpy()


def _stack(sd, fmt: str, L: int, transpose: bool,
           dtype=jnp.float32) -> jnp.ndarray:
    """Stack L per-layer tensors, casting each layer to ``dtype``
    before stacking so the fp32 transient is one layer, not the whole
    (L, ...) stack (matters at Mixtral/Llama-7B scale)."""
    arrs = [jnp.asarray(_np(sd[fmt.format(i)]).T if transpose
                        else _np(sd[fmt.format(i)]), dtype)
            for i in range(L)]
    return jnp.stack(arrs)


def _attn_and_embed(sd, L: int, dtype):
    """The conversion both families share: embed, (possibly tied)
    lm_head, attention projections, and the two per-layer norms —
    one definition so a naming/tying fix reaches dense and MoE alike."""
    embed = _np(sd["model.embed_tokens.weight"])          # (V, D)
    if "lm_head.weight" in sd:
        lm_head = _np(sd["lm_head.weight"]).T             # (D, V)
    else:
        lm_head = embed.T                                  # tied
    layers = {
        "attn_norm": _stack(
            sd, "model.layers.{}.input_layernorm.weight", L, False),
        "wq": _stack(sd, "model.layers.{}.self_attn.q_proj.weight",
                     L, True, dtype),
        "wk": _stack(sd, "model.layers.{}.self_attn.k_proj.weight",
                     L, True, dtype),
        "wv": _stack(sd, "model.layers.{}.self_attn.v_proj.weight",
                     L, True, dtype),
        "wo": _stack(sd, "model.layers.{}.self_attn.o_proj.weight",
                     L, True, dtype),
        "mlp_norm": _stack(
            sd, "model.layers.{}.post_attention_layernorm.weight", L,
            False),
    }
    return {
        "embed": jnp.asarray(embed, dtype),
        "layers": layers,
        "final_norm": jnp.asarray(_np(sd["model.norm.weight"]),
                                  jnp.float32),
        "lm_head": jnp.asarray(lm_head, dtype),
    }


def params_from_hf(model, cfg: TransformerConfig | None = None, *,
                   dtype: Any = jnp.bfloat16) -> tuple[dict, Any]:
    """Convert a ``transformers`` ``LlamaForCausalLM``-shaped model (or
    anything with the same ``state_dict()`` naming) into this
    framework's pytree.

    Returns ``(params, cfg)`` with weights cast to ``dtype`` (norms
    stay fp32, matching :func:`init_params`).  The conversion stacks
    per-layer tensors along a leading (n_layers,) axis for the
    ``lax.scan`` forward.
    """
    if cfg is None:
        cfg = config_from_hf(model.config)
    cfg = TransformerConfig(**{**cfg.__dict__, "dtype": dtype})
    sd = model.state_dict()
    L = cfg.n_layers
    params = _attn_and_embed(sd, L, dtype)
    params["layers"].update({
        "w_gate": _stack(sd, "model.layers.{}.mlp.gate_proj.weight",
                         L, True, dtype),
        "w_up": _stack(sd, "model.layers.{}.mlp.up_proj.weight",
                       L, True, dtype),
        "w_down": _stack(sd, "model.layers.{}.mlp.down_proj.weight",
                         L, True, dtype),
    })
    return params, cfg


def moe_config_from_hf(hf_config, *,
                       capacity_factor: float | None = None):
    """Map a ``transformers`` Mixtral-family config onto
    :class:`~nbdistributed_tpu.models.moe.MoEConfig`.

    HF Mixtral routes without capacity limits; this framework's
    dispatch is capacity-bounded, so the default ``capacity_factor``
    is the *lossless* value ``n_experts / top_k`` (no token ever
    dropped — logits match the torch forward).  Pass a tighter factor
    to trade exactness for bounded expert memory."""
    from .moe import MoEConfig

    E = hf_config.num_local_experts
    k = hf_config.num_experts_per_tok
    base = config_from_hf(hf_config)
    if capacity_factor is None:
        capacity_factor = E / k
    return MoEConfig(**{**base.__dict__, "n_experts": E, "top_k": k,
                        "capacity_factor": capacity_factor,
                        "lb_coef": float(getattr(
                            hf_config, "router_aux_loss_coef", 0.01))})


def moe_params_from_hf(model, *, dtype: Any = jnp.bfloat16,
                       capacity_factor: float | None = None):
    """Convert a ``transformers`` ``MixtralForCausalLM``-shaped model
    into the MoE-family pytree (attention exactly as the dense
    conversion; router fp32 transposed; per-expert w1/w3/w2 →
    w_gate/w_up/w_down stacked on a leading E axis).  Returns
    ``(params, cfg)``."""
    cfg = moe_config_from_hf(model.config,
                             capacity_factor=capacity_factor)
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": dtype})
    sd = model.state_dict()
    L, E = cfg.n_layers, cfg.n_experts

    def stack_experts(w: str):
        # (L, E, in, out) from per-expert torch (out, in) tensors —
        # cast to the target dtype PER LAYER so the fp32 transient is
        # one (E, in, out) slab, not the whole L*E expert stack (at
        # Mixtral-8x7B scale the difference is ~100 GB of host RAM).
        per_layer = [jnp.asarray(np.stack([
            _np(sd[f"model.layers.{i}.block_sparse_moe.experts.{e}"
                   f".{w}.weight"]).T for e in range(E)]), dtype)
            for i in range(L)]
        return jnp.stack(per_layer)

    params = _attn_and_embed(sd, L, dtype)
    params["layers"]["moe"] = {
        # Router stays fp32 (gating is numerically delicate; _stack's
        # default dtype).
        "router": _stack(
            sd, "model.layers.{}.block_sparse_moe.gate.weight", L,
            True),
        "w_gate": stack_experts("w1"),
        "w_up": stack_experts("w3"),
        "w_down": stack_experts("w2"),
    }
    return params, cfg


def load_hf_pretrained(name_or_path: str, *,
                       dtype: Any = jnp.bfloat16) -> tuple[dict, Any]:
    """``from_pretrained`` (local path or cached hub name, torch CPU)
    -> (params, cfg).  Dispatches on architecture: Mixtral-family
    checkpoints convert through :func:`moe_params_from_hf`, Llama
    family through :func:`params_from_hf`.  The heavyweight torch
    model is freed before returning."""
    from transformers import AutoModelForCausalLM

    # Load in the checkpoint's own dtype: forcing fp32 would double a
    # Mixtral-class model's host footprint before conversion (the
    # per-tensor fp32 hop happens inside _np, one tensor at a time).
    model = AutoModelForCausalLM.from_pretrained(
        name_or_path, dtype="auto", low_cpu_mem_usage=True)
    try:
        if getattr(model.config, "num_local_experts", None):
            return moe_params_from_hf(model, dtype=dtype)
        return params_from_hf(model, dtype=dtype)
    finally:
        del model
