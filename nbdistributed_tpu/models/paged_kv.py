"""Paged KV storage: fixed-size blocks under the dense decode path.

The dense serving cache is one ``(L, max_batch, Hkv, max_len, D)``
pool — every slot reserves ``max_len`` tokens of KV for its whole
lifetime, so a server sized for long contexts wastes almost all of its
cache on short chats.  This module pages that storage: the pool
becomes ``(L, n_blocks + 1, Hkv, block_tokens, D)`` — one "batch row"
per fixed-size *block* — and each slot holds a table of physical block
ids covering exactly ``ceil((prompt + max_new) / block_tokens)``
blocks.  Capacity is then measured in blocks (the
:class:`~..serving_fast.paging.BlockAllocator` arithmetic the gateway
uses for admission), so ``max_batch`` can exceed what a dense pool of
the same HBM could hold and short requests stop reserving long-context
KV.  The int8/int4 quantized layout comes for free: the pool is built
by the same :func:`~.generate.init_kv_cache` (values + per-token
scales), and every helper here tree-maps over the cache dict, so
paged + quantized compose without new code.

**Compute path (stated honestly).**  The attention kernels are
unchanged: each step *gathers* the table-selected blocks into a dense
``(L, S, Hkv, T', D)`` view, runs the existing
:func:`~.generate.forward_with_cache`, and *scatters* back only what
changed (decode: the one block containing the written position per
active slot; prefill: the slot's whole row).  The gather is one
``jnp.take`` per cache leaf — XLA fuses it, but the dense view is
materialized per step, so paging here buys *capacity accounting and
admission semantics*, not peak-HBM-per-step; a fused paged-attention
kernel (block tables consumed inside the Pallas decode kernel,
ops/decode.py) is the stated next step on the roadmap.

**The trash block.**  Physical block ``n_blocks`` is never allocated.
Unallocated table entries point at it, and the decode scatter
redirects *inactive* slots there, so a freed-and-reallocated block can
never be corrupted by a stale slot's frozen-position write (the dense
pool tolerates those because admission re-prefills the whole row;
a paged block may be owned by someone else by then).  Garbage in the
trash block — or in allocated-but-unwritten blocks — is unreachable by
attention: positions ``> cache_len`` are masked, and a slot's
``cache_len`` never passes its allocated token count.

Exactness: gather ∘ scatter is the identity on the blocks a slot owns,
so a paged greedy decode is bit-identical to the dense server's (and
to a solo :func:`~.generate.generate`) — asserted by the paged-decode
unit tests, including the quantized round-trip tolerance.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..serving_fast.paging import BlockAllocator, blocks_needed
from .generate import init_kv_cache, kv_cache_shardings


def make_paged_pool(cfg, n_blocks: int, block_tokens: int, *,
                    mesh=None, quantized: bool = False):
    """The physical block pool: ``init_kv_cache`` with the batch axis
    repurposed as blocks (+1 trash block).  With a mesh, only the
    KV-head (tp) axis is sharded — block ids are dynamic gather
    indices, so the block axis stays replicated and GSPMD keeps the
    gather local per shard."""
    rules = None
    if mesh is not None:
        rules = kv_cache_shardings(
            dp_axis=None,
            tp_axis="tp" if "tp" in mesh.shape else None,
            sp_axis=None, quantized=quantized)
    return init_kv_cache(cfg, int(n_blocks) + 1, int(block_tokens),
                         mesh=mesh, rules=rules, quantized=quantized)


def gather_dense(pool, table):
    """Table-select every slot's blocks into a dense cache view.

    pool leaves ``(L, NB+1, Hkv, bt, D)``, table ``(S, MB)`` physical
    ids -> dense leaves ``(L, S, Hkv, MB*bt, D)`` — the exact layout
    ``forward_with_cache`` expects, with ``T' = MB*bt``.
    """
    def one(c):
        g = jnp.take(c, table, axis=1)        # (L, S, MB, Hkv, bt, D)
        g = jnp.transpose(g, (0, 1, 3, 2, 4, 5))
        sh = g.shape
        return g.reshape(sh[0], sh[1], sh[2], sh[3] * sh[4], sh[5])
    return jax.tree_util.tree_map(one, pool)


def gather_row(pool, row_ids):
    """One slot's blocks as a dense ``(L, 1, Hkv, MB*bt, D)`` row —
    the prefill working view."""
    def one(c):
        g = jnp.take(c, row_ids, axis=1)      # (L, MB, Hkv, bt, D)
        g = jnp.transpose(g, (0, 2, 1, 3, 4))
        sh = g.shape
        return g.reshape(sh[0], sh[1], sh[2] * sh[3],
                         sh[4])[:, None]
    return jax.tree_util.tree_map(one, pool)


def scatter_row(pool, row, row_ids):
    """Write a slot's whole dense row back to its physical blocks.
    Trash-mapped ids receive the row's pad garbage — harmless by
    construction (see module docstring)."""
    def one(c, r):
        sh = c.shape                          # (L, NB+1, Hkv, bt, D)
        r = r[:, 0]                           # (L, Hkv, MB*bt, D)
        r = r.reshape(sh[0], sh[2], -1, sh[3], sh[4])
        r = jnp.transpose(r, (0, 2, 1, 3, 4))  # (L, MB, Hkv, bt, D)
        return c.at[:, row_ids].set(r)
    return jax.tree_util.tree_map(one, pool, row)


def scatter_step(pool, dense, table, pos, active, trash: int,
                 block_tokens: int):
    """Write back the ONE block per slot that a decode step touched.

    ``pos`` is the position the step wrote (pre-increment ``lens``).
    Inactive slots are redirected to the trash block — their frozen-
    position write must never land in a block that may have been
    reallocated to another request.
    """
    blk_log = pos // block_tokens                       # (S,)
    phys = jnp.take_along_axis(table, blk_log[:, None],
                               axis=1)[:, 0]            # (S,)
    phys = jnp.where(active, phys, trash)

    def one(c, d):
        sh = c.shape                          # (L, NB+1, Hkv, bt, D)
        d = d.reshape(d.shape[0], d.shape[1], d.shape[2], -1,
                      block_tokens, d.shape[-1])
        blk = jnp.take_along_axis(
            d, blk_log[None, :, None, None, None, None],
            axis=3)[:, :, :, 0]               # (L, S, Hkv, bt, D)
        return c.at[:, phys].set(blk)
    return jax.tree_util.tree_map(one, pool, dense)


def apply_moves(pool, moves: dict[int, int]):
    """Apply a :meth:`BlockAllocator.defrag` move map to the physical
    pool with ONE gather per leaf: ``new[dst] = old[src]``.  The map is
    read atomically, so chains of moves (a live block compacting into
    another live block's vacated id) are safe."""
    if not moves:
        return pool
    n = jax.tree_util.tree_leaves(pool)[0].shape[1]
    src = np.arange(n)
    for old, new in moves.items():
        src[new] = old
    src = jnp.asarray(src, jnp.int32)
    return jax.tree_util.tree_map(
        lambda c: jnp.take(c, src, axis=1), pool)


class PagedKVCache:
    """Host-side paging state for one decode server: the block
    allocator (owner = slot id) plus per-slot block tables, with
    cached device mirrors.  The physical pool itself lives in the
    server (it is donated through the jitted step/prefill programs —
    a second reference here would dangle)."""

    def __init__(self, *, slots: int, max_len: int, n_blocks: int,
                 block_tokens: int):
        self.slots = int(slots)
        self.block_tokens = int(block_tokens)
        self.n_blocks = int(n_blocks)
        self.trash = self.n_blocks
        self.max_blocks = blocks_needed(max_len, block_tokens)
        if self.max_blocks < 1:
            raise ValueError(f"max_len {max_len} yields an empty "
                             f"block table")
        self.allocator = BlockAllocator(n_blocks, block_tokens)
        # -1 = unallocated (mapped to trash on the device mirror).
        self._table = np.full((self.slots, self.max_blocks), -1,
                              np.int32)
        self._dev = None                      # invalidated on change

    # -- allocation (owner = slot) ------------------------------------
    def alloc(self, slot: int, tokens: int) -> None:
        """Worst-case allocation for a request that may reach
        ``tokens`` KV entries.  Raises
        :class:`~..serving_fast.paging.BlocksExhausted` untaken."""
        ids = self.allocator.alloc(str(slot),
                                   blocks_needed(tokens,
                                                 self.block_tokens))
        self._table[slot, :] = -1
        self._table[slot, :len(ids)] = ids
        self._dev = None

    def free(self, slot: int) -> int:
        n = self.allocator.free(str(slot))
        self._table[slot, :] = -1
        self._dev = None
        return n

    def defrag(self) -> dict[int, int]:
        """Compact the allocator and refresh the host tables; the
        caller applies the returned moves to the pool with
        :func:`apply_moves` (host table and device storage move in
        lock-step or not at all)."""
        moves = self.allocator.defrag()
        if moves:
            for slot in range(self.slots):
                ids = self.allocator._tables.get(str(slot))
                if ids is not None:
                    self._table[slot, :len(ids)] = ids
            self._dev = None
        return moves

    # -- device mirrors ------------------------------------------------
    def device_table(self):
        """(S, MB) int32 physical-id table, -1 entries mapped to the
        trash block.  Rebuilt only when the tables changed — the
        common decode tick reuses the cached device array."""
        if self._dev is None:
            t = np.where(self._table < 0, self.trash, self._table)
            self._dev = jnp.asarray(t, jnp.int32)
        return self._dev

    def device_row(self, slot: int):
        """(MB,) int32 physical ids for one slot (prefill's view)."""
        return self.device_table()[slot]

    # -- accounting ----------------------------------------------------
    @property
    def used_blocks(self) -> int:
        return self.allocator.used_blocks

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    def largest_free_run(self) -> int:
        """Longest contiguous free-block run (fragmentation telemetry
        for the serving observatory / %dist_top frag column)."""
        return self.allocator.largest_free_run()

    def snapshot(self) -> dict:
        return self.allocator.snapshot()
