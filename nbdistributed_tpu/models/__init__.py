"""Model families: TPU-first Llama-style transformers (configs from the
tiny demo scale up to Llama-2-7B, matching BASELINE.json's acceptance
configs)."""

from .generate import (forward_with_cache, generate, init_kv_cache,
                       kv_cache_shardings, make_generate_fn,
                       prefill_chunked)
from .hf import (config_from_hf, load_hf_pretrained,
                 moe_config_from_hf, moe_params_from_hf,
                 params_from_hf)
from .lora import (ALL_TARGETS, ATTN_TARGETS, lora_init, lora_merge,
                   lora_num_params, lora_shardings,
                   make_lora_train_step)
from .pp import (make_pp_1f1b_train_step, make_pp_train_step,
                 pp_apply_shardings, pp_loss_fn,
                 pp_stage_params, pp_unstage_params)
from .serving import DecodeServer
from .speculative import speculative_generate
from .quant import (dequantize_weight, dequantize_weight4,
                    is_quantized, is_quantized4, quantization_error,
                    quantize_moe_params, quantize_params,
                    quantize_params4, quantize_weight4,
                    quantize_weight, quantized_moe_shardings,
                    quantized_shardings4,
                    quantized_shardings)
from .moe import (MoEConfig, init_moe_model, mixtral_8x7b_config,
                  moe_forward_hidden,
                  moe_forward, moe_loss_fn, moe_model_shardings,
                  tiny_moe_config)
from .transformer import (SeqParallel, TransformerConfig,
                          fsdp_param_shardings, forward,
                          forward_hidden,
                          init_params, llama2_7b_config, loss_fn,
                          make_train_step, mistral_7b_config,
                          packed_positions, param_shardings,
                          smol_135m_config, tinyllama_1b_config,
                          tiny_config)

__all__ = ["SeqParallel", "TransformerConfig", "forward",
           "forward_hidden",
           "fsdp_param_shardings", "init_params",
           "llama2_7b_config", "loss_fn", "make_train_step",
           "mistral_7b_config", "packed_positions",
           "param_shardings", "smol_135m_config", "tiny_config",
           "tinyllama_1b_config",
           "MoEConfig", "init_moe_model", "mixtral_8x7b_config",
           "moe_forward", "moe_forward_hidden", "moe_loss_fn", "moe_model_shardings",
           "tiny_moe_config",
           "forward_with_cache", "generate", "init_kv_cache",
           "kv_cache_shardings", "make_generate_fn", "prefill_chunked",
           "config_from_hf", "load_hf_pretrained", "params_from_hf",
           "moe_config_from_hf", "moe_params_from_hf",
           "ALL_TARGETS", "ATTN_TARGETS", "lora_init", "lora_merge",
           "lora_num_params", "lora_shardings", "make_lora_train_step",
           "dequantize_weight", "is_quantized", "quantization_error",
           "quantize_moe_params", "quantize_params", "quantize_weight",
           "quantized_moe_shardings", "quantized_shardings",
           "speculative_generate", "DecodeServer",
           "make_pp_1f1b_train_step", "make_pp_train_step",
           "pp_apply_shardings", "pp_loss_fn",
           "pp_stage_params", "pp_unstage_params"]
