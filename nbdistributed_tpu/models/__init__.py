"""Model families: TPU-first Llama-style transformers (configs from the
tiny demo scale up to Llama-2-7B, matching BASELINE.json's acceptance
configs)."""

from .transformer import (TransformerConfig, forward, init_params,
                          llama2_7b_config, loss_fn, make_train_step,
                          param_shardings, smol_135m_config, tiny_config)

__all__ = ["TransformerConfig", "forward", "init_params",
           "llama2_7b_config", "loss_fn", "make_train_step",
           "param_shardings", "smol_135m_config", "tiny_config"]
