"""Llama-family decoder-only transformer, TPU-first.

The reference framework ships no models (users bring HF torch models in
cells — its demo runs SmolLM2-135M: 00_accelerate.ipynb cell 10); a
TPU-native framework needs a first-party model family for its
benchmarks and acceptance configs (BASELINE.json: tiny transformer DDP,
Llama-2-7B tensor-parallel forward).  Design:

* pure-JAX pytree params (no framework dependency on flax), bfloat16
  activations, fp32 RMSNorm accumulation — MXU-friendly;
* rotary embeddings, grouped-query attention (flash kernel from
  :mod:`nbdistributed_tpu.ops`), SwiGLU MLP — the Llama recipe;
* explicit ``PartitionSpec`` rules per parameter for dp/tp meshes
  (Megatron-style column/row splits expressed as shardings — XLA
  inserts the all-reduces the reference's users typed by hand,
  README.md:115-125);
* ``lax.scan`` over layers for O(1) compile scaling.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops import flash_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    use_flash: bool = True
    # Mistral-style sliding-window attention: each position attends at
    # most the previous `sliding_window` tokens.  None = full causal.
    sliding_window: int | None = None
    # Rematerialize each layer in the backward pass (jax.checkpoint):
    # activation memory drops from O(L·S·D) to O(S·D) + one extra
    # forward of compute — the standard long-context training trade on
    # HBM-bound TPUs.  Composes with sequence parallelism (ring/Ulysses
    # shard S; remat shrinks the per-layer residual footprint).
    remat: bool = False
    # Remat *policy*: what the checkpointed layer may keep.
    #   None      — save nothing (full recompute, minimum memory);
    #   "dots"    — jax.checkpoint_policies.checkpoint_dots: matmul
    #               outputs are saved, only cheap elementwise/norm ops
    #               recompute.  The backward skips re-running the MXU
    #               work, trading ~L·S·(3·d_ff + H·Dh + 2·Hkv·Dh + D)
    #               bytes of saved dots for most of remat's recompute
    #               FLOPs — the right default when the model fits.
    remat_policy: str | None = None
    # Chunked-vocab cross-entropy (ops/xent.py): the training loss
    # streams the lm_head in blocks of this many vocab columns and
    # never materializes the (B, S, V) logits — the buffer that caps
    # the train batch at LM scale (two+ fp32 copies of it live in the
    # naive loss).  None = standard full-logits path.  Engages on the
    # single-device / dp / sp paths (the scan body is row-wise math
    # GSPMD partitions over sharded tokens); under tp the head is
    # already vocab-sharded and the loss falls back to the standard
    # tail (loss_fn checks the sp mesh's tp axis; plain-tp callers
    # keep ce_chunk=None).  An int8-quantized lm_head also falls back
    # (quantized heads are the inference configuration; training
    # wants the dense head).
    ce_chunk: int | None = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        emb = self.vocab_size * self.d_model
        attn = (self.d_model * self.n_heads * self.head_dim
                + 2 * self.d_model * self.n_kv_heads * self.head_dim
                + self.n_heads * self.head_dim * self.d_model)
        mlp = 3 * self.d_model * self.d_ff
        norms = 2 * self.d_model
        return emb * 2 + self.n_layers * (attn + mlp + norms) + self.d_model


# Preset configs.  llama2_7b matches the acceptance config in
# BASELINE.json ("8-rank Llama-2-7B forward"); tiny is the test/demo
# scale (SmolLM2-135M-like role in the reference's notebook).
# Caller kwargs OVERRIDE the preset's defaults (so e.g.
# smol_135m_config(max_seq_len=8192) works — the bench's long-context
# row does exactly that).
def _preset(kw: dict, cls=None, **defaults):
    """Build a preset config with caller kwargs overriding the
    defaults.  ``cls`` lets subclass factories (MoEConfig) share the
    same override contract."""
    return (cls or TransformerConfig)(**{**defaults, **kw})


def tiny_config(**kw) -> TransformerConfig:
    return _preset(kw, vocab_size=512, d_model=128, n_layers=2,
                   n_heads=4, n_kv_heads=2, d_ff=384, max_seq_len=256)


def smol_135m_config(**kw) -> TransformerConfig:
    return _preset(kw, vocab_size=49152, d_model=576, n_layers=30,
                   n_heads=9, n_kv_heads=3, d_ff=1536,
                   max_seq_len=2048)


def tinyllama_1b_config(**kw) -> TransformerConfig:
    """TinyLlama-1.1B dims (Zhang et al. 2024): the ~1B scale where
    d_model=2048 matmuls feed the MXU properly — the bench's
    MFU-at-meaningful-scale config (a 135M model's d=576 GEMMs cannot
    reach competitive MFU on a v5e)."""
    return _preset(kw, vocab_size=32000, d_model=2048, n_layers=22,
                   n_heads=32, n_kv_heads=4, d_ff=5632,
                   max_seq_len=2048)


def mistral_7b_config(**kw) -> TransformerConfig:
    """Mistral-7B-v0.1: the sliding-window release (4096-token window,
    rope theta 1e4, 32k positions).  v0.2/v0.3 dropped the window and
    raised theta to 1e6 — convert those via config_from_hf instead of
    this preset."""
    return _preset(kw, vocab_size=32000, d_model=4096, n_layers=32,
                   n_heads=32, n_kv_heads=8, d_ff=14336,
                   max_seq_len=32768, sliding_window=4096,
                   rope_theta=10000.0)


def llama2_7b_config(**kw) -> TransformerConfig:
    return _preset(kw, vocab_size=32000, d_model=4096, n_layers=32,
                   n_heads=32, n_kv_heads=32, d_ff=11008,
                   max_seq_len=4096)


# ----------------------------------------------------------------------
# parameters

def layer_weight_dims(cfg: TransformerConfig) -> dict:
    """(d_in, d_out) of every per-layer weight matrix — the single
    source of truth shared by :func:`init_params` and the LoRA adapter
    factory (lora.lora_init)."""
    D, H, Hkv, Dh, F = (cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
                        cfg.head_dim, cfg.d_ff)
    return {"wq": (D, H * Dh), "wk": (D, Hkv * Dh), "wv": (D, Hkv * Dh),
            "wo": (H * Dh, D), "w_gate": (D, F), "w_up": (D, F),
            "w_down": (F, D)}


def init_params(key, cfg: TransformerConfig) -> dict:
    """Layer-stacked parameter pytree: per-layer arrays carry a leading
    (n_layers,) axis so the forward can ``lax.scan`` over them."""
    k_emb, k_layers, k_out = jax.random.split(key, 3)
    D, L = cfg.d_model, cfg.n_layers
    dims = layer_weight_dims(cfg)

    def normal(key, shape, fan_in):
        from ..utils import fan_in_normal
        return fan_in_normal(key, shape, fan_in, cfg.dtype)

    names = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
    ks = dict(zip(names, jax.random.split(k_layers, len(names))))
    layers = {name: normal(ks[name], (L,) + dims[name], dims[name][0])
              for name in names}
    layers["attn_norm"] = jnp.ones((L, D), jnp.float32)
    layers["mlp_norm"] = jnp.ones((L, D), jnp.float32)
    return {
        "embed": normal(k_emb, (cfg.vocab_size, D), 1.0),
        "layers": layers,
        "final_norm": jnp.ones((D,), jnp.float32),
        "lm_head": normal(k_out, (D, cfg.vocab_size), D),
    }


def param_shardings(cfg: TransformerConfig) -> dict:
    """Megatron-style tensor-parallel sharding rules over mesh axis
    ``tp`` (columns of qkv/gate/up; rows of o/down — so each layer needs
    exactly one all-reduce per block, inserted by XLA)."""
    return {
        "embed": P(None, "tp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        },
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }


def fsdp_param_shardings(cfg: TransformerConfig,
                         dp_axis: str = "dp",
                         tp_axis: str | None = None) -> dict:
    """FSDP / ZeRO-3-style weight sharding expressed as GSPMD rules:
    every large weight is sharded over ``dp_axis`` (column-split
    weights on their contraction dim, row-split wo/w_down on their
    output dim — the opposite axis from Megatron's split, so the two
    never collide), and per-device parameter (and gradient, and — via
    the same rules on the optimizer init — optimizer-state) memory
    drops by the dp size.  XLA compiles the per-use all-gather /
    reduce-scatter schedule from the sharding lattice, exactly as
    torch FSDP does by hand; numerics are identical to replicated
    training (tested).

    With ``tp_axis`` the Megatron split applies on the other dim
    simultaneously (2-D weight sharding — the HSDP layout).  Norms
    stay replicated (tiny)."""
    row, col = dp_axis, tp_axis
    return {
        "embed": P(dp_axis, col),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, row, col),
            "wk": P(None, row, col),
            "wv": P(None, row, col),
            "wo": P(None, col, row),
            "mlp_norm": P(None, None),
            "w_gate": P(None, row, col),
            "w_up": P(None, row, col),
            "w_down": P(None, col, row),
        },
        "final_norm": P(None),
        "lm_head": P(dp_axis, col),
    }


# ----------------------------------------------------------------------
# forward

def is_quantized(leaf) -> bool:
    """True for an int8 weight-only quantized leaf ``{"q8", "s"}``
    (produced by models/quant.py; defined here so qlinear and quant.py
    share one predicate without an import cycle)."""
    return isinstance(leaf, dict) and "q8" in leaf and "s" in leaf


def is_quantized4(leaf) -> bool:
    """True for a nibble-packed int4 leaf ``{"q4", "s"}``
    (models/quant.py quantize_weight4)."""
    return isinstance(leaf, dict) and "q4" in leaf and "s" in leaf


# Nibble pack/unpack live HERE (beside the qlinear consumer) so the
# packing layout has exactly one definition; quant.py re-exports them
# — the same no-import-cycle arrangement as is_quantized above.

def _pack_nibbles(q):
    """(..., d_in, d_out) int values in [-7, 7] -> (..., d_in/2, d_out)
    uint8; row 2k rides the low nibble, row 2k+1 the high."""
    lo = (q[..., 0::2, :] & 0xF)
    hi = (q[..., 1::2, :] & 0xF)
    return (lo | (hi << 4)).astype(jnp.uint8)


def _unpack_nibbles(packed, dtype):
    """Inverse of :func:`_pack_nibbles` (sign-extended)."""
    p = packed.astype(jnp.int32)
    lo = (((p & 0xF) ^ 8) - 8)
    hi = ((((p >> 4) & 0xF) ^ 8) - 8)
    q = jnp.stack([lo, hi], axis=-2)          # (..., d_in/2, 2, d_out)
    return q.reshape(*packed.shape[:-2], packed.shape[-2] * 2,
                     packed.shape[-1]).astype(dtype)


def _qlinear4(x, w):
    """``x @ W`` for a nibble-packed int4 leaf with grouped scales.

    The packed uint8 array (d_in/2, d_out) is HALF the int8 bytes —
    what decode streams; the unpack (shift/mask/sign-extend) is
    elementwise arithmetic XLA fuses into the consumer.  Grouped
    scales don't commute with the whole matmul, so the contraction
    runs as G batched (group x d_out) einsums whose partials combine
    with the (G, d_out) scales — one extra small reduction on the
    activation side, nothing extra on the weight side."""
    q4, s = w["q4"], w["s"]
    if q4.ndim != 2:
        # quantize_weight4 supports stacked leaves (e.g. the
        # (n_layers, ...) scanned-layers tree), but this contraction
        # is written for one 2D weight — the reshape below would fold
        # the leading dims into G and fail with an opaque size
        # mismatch (or worse, silently contract wrong axes).
        raise ValueError(
            f"qlinear on a stacked int4 leaf (q4 shape "
            f"{tuple(q4.shape)}): expected a 2D (d_in/2, d_out) "
            f"weight — index or scan over the leading "
            f"{q4.ndim - 2} dim(s) and apply qlinear per slice")
    d_in, d_out = q4.shape[-2] * 2, q4.shape[-1]
    G = s.shape[-3]
    group = d_in // G
    qu = _unpack_nibbles(q4, x.dtype)
    qg = qu.reshape(G, group, d_out)
    xg = x.reshape(*x.shape[:-1], G, group)
    y = jnp.einsum("...gk,gko->...go", xg, qg).astype(jnp.float32)
    y = jnp.einsum("...go,go->...o", y,
                   s.reshape(G, d_out).astype(jnp.float32))
    return y.astype(x.dtype)


def qlinear(x, w):
    """``x @ w`` where ``w`` is a plain array or an int8 weight-only
    quantized leaf ``{"q8", "s"}`` (see models/quant.py).  Per-output-
    channel scales commute with the matmul, so the dot consumes the raw
    int8 array (half the HBM traffic — the convert to x.dtype fuses
    into the operand read; int8 magnitudes are exact in bf16) and the
    rescale is one fused per-column multiply in fp32."""
    if is_quantized(w):
        y = x @ w["q8"].astype(x.dtype)
        return (y.astype(jnp.float32) * w["s"]).astype(x.dtype)
    if is_quantized4(w):
        return _qlinear4(x, w)
    return x @ w


def _rms_norm(x, weight, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * weight).astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding.  x: (B, S, H, D); positions: (B, S)."""
    B, S, H, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # (B,S,half)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


@dataclasses.dataclass(frozen=True)
class SeqParallel:
    """Route the model's attention through sequence parallelism.

    The rest of the network (embeddings, norms, MLP, lm_head) is
    position-wise, so GSPMD keeps it sequence-sharded for free once the
    batch's S axis is sharded over ``mesh[axis]``; attention is the one
    op that mixes positions, and this spec swaps it for the ring
    (``method="ring"``, any head count, K/V circulate at Hkv heads) or
    Ulysses (``method="ulysses"``, needs per-tp-shard head counts
    divisible by the axis size) implementation from the parallel
    library.  Zigzag-order ring training stays a library-level tool
    (it permutes the sequence axis, which would also permute the
    loss's next-token shift).

    ``dp_axis``/``tp_axis`` name the mesh axes the batch and head dims
    ride (they extend the attention shard_map specs, so dp/tp
    composition keeps attention local instead of all-gathering); each
    is used only if present in ``mesh`` — the defaults compose with
    the standard dp×sp×tp mesh with no ceremony.  ``use_flash=None``
    (default) follows ``cfg.use_flash``, so a CPU-oriented config
    doesn't silently pick the Pallas path.
    """
    mesh: Any
    axis: str = "sp"
    method: str = "ring"
    use_flash: bool | None = None
    dp_axis: str | None = "dp"
    tp_axis: str | None = "tp"

    def __post_init__(self):
        if self.method not in ("ring", "ulysses"):
            raise ValueError(f"unknown SeqParallel method "
                             f"{self.method!r}; use 'ring' or 'ulysses'")

    def _resolved_axes(self):
        """(batch_axis, head_axis), dropping names absent from mesh."""
        names = set(self.mesh.shape)
        return (self.dp_axis if self.dp_axis in names else None,
                self.tp_axis if self.tp_axis in names else None)


def _attention_block(x, layer, cfg: TransformerConfig, positions,
                     sp: SeqParallel | None = None, segment_ids=None):
    B, S, D = x.shape
    H, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = qlinear(h, layer["wq"]).reshape(B, S, H, Dh)
    k = qlinear(h, layer["wk"]).reshape(B, S, Hkv, Dh)
    v = qlinear(h, layer["wv"]).reshape(B, S, Hkv, Dh)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    if sp is not None:
        flash = cfg.use_flash if sp.use_flash is None else sp.use_flash
        batch_axis, head_axis = sp._resolved_axes()
        if sp.method == "ulysses":
            from ..parallel.ulysses import ulysses_attention
            o = ulysses_attention(q, k, v, sp.mesh, axis=sp.axis,
                                  causal=True, use_flash=flash,
                                  batch_axis=batch_axis,
                                  head_axis=head_axis,
                                  window=cfg.sliding_window,
                                  segment_ids=segment_ids)
        else:
            from ..parallel.ring import ring_attention
            o = ring_attention(q, k, v, sp.mesh, axis=sp.axis,
                               causal=True, use_flash=flash,
                               batch_axis=batch_axis,
                               head_axis=head_axis,
                               window=cfg.sliding_window,
                               segment_ids=segment_ids)
    elif cfg.use_flash:
        # block sizes None -> TUNED_BLOCKS table (tune_flash.py) with
        # the 128x128 fallback.
        o = flash_attention(q, k, v, True, None, None, None,
                            cfg.sliding_window, segment_ids)
    else:
        from ..ops import attention_reference
        o = attention_reference(q, k, v, causal=True,
                                window=cfg.sliding_window,
                                segment_ids=segment_ids)
    return x + qlinear(o.reshape(B, S, H * Dh), layer["wo"])


def _mlp_block(x, layer, cfg: TransformerConfig):
    h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gated = (jax.nn.silu(qlinear(h, layer["w_gate"]))
             * qlinear(h, layer["w_up"]))
    return x + qlinear(gated, layer["w_down"])


def make_layer_fn(cfg: TransformerConfig, positions,
                  sp: SeqParallel | None = None, segment_ids=None):
    """The per-layer recipe (attention block + MLP block, optionally
    rematerialized) — one definition shared by the plain forward and
    the pipelined stages (models/pp.py), so a change to the layer
    structure cannot silently diverge between them."""

    def one_layer(x, layer):
        x = _attention_block(x, layer, cfg, positions, sp, segment_ids)
        return _mlp_block(x, layer, cfg)

    # Validate the policy BEFORE the remat gate: a config carrying a
    # policy but remat=False (or an unknown policy string) must fail
    # loudly, not silently train with full activation memory.
    policy = getattr(cfg, "remat_policy", None)
    if policy not in (None, "dots", "attn_only", "mlp_only"):
        raise ValueError(f"unknown remat_policy {policy!r} "
                         f"(None, 'dots', 'attn_only' or 'mlp_only')")
    if policy is not None and not cfg.remat:
        raise ValueError("remat_policy is set but remat=False — the "
                         "policy would be silently ignored; set "
                         "remat=True (or drop the policy)")
    if not cfg.remat:
        return one_layer
    if policy == "dots":
        return jax.checkpoint(
            one_layer,
            policy=jax.checkpoint_policies.checkpoint_dots)
    if policy == "attn_only":
        # Recompute only the attention block (the O(S·D) internals the
        # flash kernel re-runs cheaply off its saved logsumexp); the
        # MLP's d_ff-wide activations — the per-layer memory bulk —
        # stay saved, so the backward skips 2/3 of the layer FLOPs a
        # full remat would re-run.
        attn = jax.checkpoint(lambda x, layer: _attention_block(
            x, layer, cfg, positions, sp, segment_ids))

        def one_layer_attn(x, layer):
            return _mlp_block(attn(x, layer), layer, cfg)

        return one_layer_attn
    if policy == "mlp_only":
        # Mirror image: recompute the MLP (plain GEMMs), keep the
        # attention internals saved — maximal memory saving among the
        # partial policies (the d_ff buffers dominate) at ~2/3-layer
        # recompute.
        mlp = jax.checkpoint(lambda x, layer: _mlp_block(
            x, layer, cfg))

        def one_layer_mlp(x, layer):
            return mlp(_attention_block(x, layer, cfg, positions, sp,
                                        segment_ids), layer)

        return one_layer_mlp
    return jax.checkpoint(one_layer)


def forward_hidden(params: dict, tokens, cfg: TransformerConfig,
                   positions=None, *, sp: SeqParallel | None = None,
                   segment_ids=None):
    """tokens: (B, S) int32 -> final-norm hidden states (B, S, D) in
    ``cfg.dtype`` — everything before the lm_head.  The chunked-vocab
    loss (ops/xent.py) consumes this directly so the (B, S, V) logits
    never exist."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    x = params["embed"][tokens].astype(cfg.dtype)
    one_layer = make_layer_fn(cfg, positions, sp,
                              segment_ids=segment_ids)

    def layer_step(x, layer):
        return one_layer(x, layer), None

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    return _rms_norm(x, params["final_norm"], cfg.norm_eps)


def forward(params: dict, tokens, cfg: TransformerConfig,
            positions=None, *, sp: SeqParallel | None = None,
            segment_ids=None):
    """tokens: (B, S) int32 -> logits (B, S, vocab) in fp32.

    With ``sp``, attention runs sequence-parallel (see
    :class:`SeqParallel`); shard the batch's S axis over
    ``sp.mesh[sp.axis]`` and jit as usual.  ``segment_ids`` (B, S):
    packed-document attention masking (see
    :func:`~nbdistributed_tpu.ops.attention.flash_attention`) —
    positions attend only within their own document."""
    x = forward_hidden(params, tokens, cfg, positions, sp=sp,
                       segment_ids=segment_ids)
    return qlinear(x, params["lm_head"]).astype(jnp.float32)


def shifted_xent(logits, tokens, segment_ids=None):
    """The logits-shift next-token cross-entropy tail: logits (B, S, V)
    from a full-S forward predict tokens[:, 1:] from positions 0..S-2.
    The single definition shared by the plain, SP, and pipelined
    losses — change it here and every path follows.

    ``segment_ids`` (B, S): packed-document batches exclude the
    boundary targets — position i must not be trained to predict the
    first token of the NEXT document (seg[i] != seg[i+1]); the mean
    runs over the surviving targets."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logp, tokens[:, 1:, None], axis=-1)
    if segment_ids is None:
        return jnp.mean(nll)
    keep = (segment_ids[:, :-1] == segment_ids[:, 1:])[..., None]
    return (jnp.sum(jnp.where(keep, nll, 0.0))
            / jnp.maximum(jnp.sum(keep), 1))


def packed_positions(segment_ids):
    """Within-document positions for a packed batch: position restarts
    at 0 at every document boundary, so RoPE sees each document as if
    it started the sequence — matching what the model will see at
    inference on unpacked prompts.  segment_ids (B, S) non-decreasing
    per row -> (B, S) int32."""
    seg = jnp.asarray(segment_ids)
    pos = jnp.arange(seg.shape[1], dtype=jnp.int32)[None]
    is_start = jnp.concatenate(
        [jnp.ones_like(seg[:, :1], bool),
         seg[:, 1:] != seg[:, :-1]], axis=1)
    seg_start = jax.lax.cummax(jnp.where(is_start, pos, 0), axis=1)
    return pos - seg_start


def _head_vocab_sharded(head) -> bool:
    """Best-effort: is this lm_head leaf sharded on its vocab (last)
    axis by a >1-way mesh axis?  Catches the plain-TP layout
    (``device_put`` with ``P(None, "tp")``, no SeqParallel object)
    whose sharding the ``sp``-based check below cannot see.  Only
    concrete arrays expose a committed ``NamedSharding``; under jit
    tracing or for quantized dict leaves detection is impossible and
    this returns False (the documented contract — don't set
    ``ce_chunk`` under plain tp — still applies there)."""
    try:
        spec = head.sharding.spec
        mesh_shape = dict(head.sharding.mesh.shape)
        ndim = head.ndim
    except Exception:
        return False
    if len(spec) < ndim:
        return False  # trailing (vocab) axis unmentioned = replicated
    entry = spec[ndim - 1]
    if entry is None:
        return False
    axes = entry if isinstance(entry, tuple) else (entry,)
    size = 1
    for a in axes:
        size *= mesh_shape.get(a, 1)
    return size > 1


def loss_fn(params, batch, cfg: TransformerConfig,
            sp: SeqParallel | None = None):
    """Next-token cross-entropy.  batch: {tokens (B,S)}; predicts
    tokens[:, 1:] from the logits at positions 0..S-2.

    The forward runs on the full S tokens and the *logits* are
    shifted, not the inputs: under causal attention position i's
    logits depend only on tokens <= i, so this is mathematically
    identical to forwarding tokens[:, :-1] — but it keeps the model's
    sequence length equal to the batch's (typically a power of two, so
    no kernel padding, and divisible by a sequence-parallel axis,
    which S-1 never is).

    ``batch["segments"]`` (optional, (B, S)): packed-document
    training — attention masks across documents, RoPE positions
    restart per document, and boundary targets drop from the loss."""
    tokens = batch["tokens"]
    seg = batch.get("segments")
    positions = packed_positions(seg) if seg is not None else None
    # A tp axis in the sp mesh means the lm_head is vocab-sharded
    # (param_shardings: P(None, "tp")) — slicing it chunk-wise would
    # make GSPMD re-gather the head every scan step, destroying the
    # memory win; fall back to the standard (already tp-sharded) tail.
    tp_sharded_head = (
        sp is not None and sp.tp_axis is not None
        and dict(getattr(sp.mesh, "shape", {})).get(sp.tp_axis, 1) > 1)
    if (not tp_sharded_head and cfg.ce_chunk is not None
            and _head_vocab_sharded(params["lm_head"])):
        # Plain-TP trap (ADVICE r5): a vocab-sharded head reached the
        # chunked path without an sp object — slicing it chunk-wise
        # would make GSPMD re-gather the whole head every scan step,
        # silently destroying the memory win.  Fall back loudly.
        import warnings
        warnings.warn(
            "ce_chunk ignored: lm_head is vocab-sharded (plain tensor "
            "parallelism) — the chunked tail would re-gather the head "
            "every scan step; using the standard tp-sharded tail "
            "instead", stacklevel=2)
        tp_sharded_head = True
    if (cfg.ce_chunk is not None and not tp_sharded_head
            and not is_quantized(params["lm_head"])
            and not is_quantized4(params["lm_head"])):
        # Chunked-vocab tail (ops/xent.py): the (B, S, V) logits never
        # materialize.  Same shift/boundary-mask contract as
        # shifted_xent — tests pin the two paths equal to fp32
        # reassociation.  Composes with sp: the scan body is plain
        # row-wise math over S-sharded hidden states and a replicated
        # head chunk, so GSPMD partitions it like the standard tail
        # (equality tested on the virtual sp mesh).
        from ..ops.xent import shifted_chunked_xent
        hidden = forward_hidden(params, tokens, cfg, positions, sp=sp,
                                segment_ids=seg)
        return shifted_chunked_xent(hidden, params["lm_head"], tokens,
                                    segment_ids=seg,
                                    chunk=cfg.ce_chunk)
    logits = forward(params, tokens, cfg, positions, sp=sp,
                     segment_ids=seg)
    return shifted_xent(logits, tokens, segment_ids=seg)


# ----------------------------------------------------------------------
# training step

def apply_optimizer_updates(params, updates):
    """Apply optax updates with fp32 accumulation, casting back to each
    leaf's storage dtype — the one mixed-precision update convention,
    shared by the full and LoRA train steps."""
    return jax.tree_util.tree_map(
        lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype),
        params, updates)


def make_train_step(cfg: TransformerConfig, optimizer,
                    sp: SeqParallel | None = None):
    """Returns ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` — shard params/batch and jit with shardings to scale it over
    any dp/tp mesh (XLA inserts gradient all-reduces for dp and
    activation collectives for tp).  ``sp`` additionally runs attention
    sequence-parallel for long-context batches (shard the batch's S
    axis over ``sp.mesh[sp.axis]``)."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg,
                                                  sp)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = apply_optimizer_updates(params, updates)
        return params, opt_state, loss

    return step


def num_tokens_per_step(batch_shape) -> int:
    return int(np.prod(batch_shape))
