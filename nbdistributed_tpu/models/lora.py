"""LoRA fine-tuning for the transformer family (Hu et al. 2021,
arXiv:2106.09685).

The reference framework has no fine-tuning subsystem (its notebook
demonstrates full-parameter DDP training via HF Accelerate,
/root/reference/00_accelerate.ipynb cells 36-40); LoRA is the
beyond-parity equivalent for the common interactive workflow — adapt a
7B-class checkpoint on hardware whose HBM cannot hold its optimizer
state.  Design is TPU-first and reuses the whole existing stack:

* Adapters are a *separate* pytree mirroring the targeted weights:
  ``{"layers": {name: {"a": (L, d_in, r), "b": (L, r, d_out)}}}`` with
  ``a ~ N(0, 1/d_in)`` and ``b = 0`` — the adapted model starts exactly
  at the base model.
* :func:`lora_merge` adds ``(a @ b) * alpha/r`` onto the frozen base
  weights *inside* the differentiated function, so
  ``jax.value_and_grad`` over the adapter pytree gets its gradients by
  ordinary autodiff through the merge — no surgery on the forward, and
  every config knob (flash kernel, remat, sliding window) and every
  parallelism rule (dp/tp shardings, ring/Ulysses) applies unchanged.
  XLA fuses the rank-r matmul + add into the surrounding computation;
  the merged weights are scan-stacked like the base ones.
* Sharding: adapters follow the base weight's Megatron split —
  column-split weights shard ``b``'s output dim on ``tp``; row-split
  weights shard ``a``'s input dim.  The rank-r inner axis is always
  replicated (r is far below a single chip's tile, splitting it would
  only add collectives).
* Optimizer state (adamw m/v) exists only for adapter leaves: for
  llama2-7b at r=16 that is ~0.6% of the full-model optimizer state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .transformer import (TransformerConfig, apply_optimizer_updates,
                          layer_weight_dims, loss_fn)

# Classic LoRA targets the attention projections; "all-linear" adds the
# SwiGLU MLP weights (QLoRA-style).
ATTN_TARGETS = ("wq", "wk", "wv", "wo")
ALL_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

# Which structural split each layer weight uses under tensor
# parallelism (see transformer.param_shardings): "col" = output dim on
# tp, "row" = input dim on tp.
_SPLIT = {"wq": "col", "wk": "col", "wv": "col", "w_gate": "col",
          "w_up": "col", "wo": "row", "w_down": "row"}


def _check_targets(targets):
    bad = [t for t in targets if t not in _SPLIT]
    if bad:
        raise ValueError(f"unknown LoRA targets {bad}; valid: "
                         f"{sorted(_SPLIT)}")


def lora_init(key, cfg: TransformerConfig, rank: int,
              targets=ATTN_TARGETS, dtype=None) -> dict:
    """Adapter pytree for ``targets`` (subset of the per-layer weight
    names).  ``a`` is fan-in-scaled gaussian, ``b`` zeros — the merged
    model is exactly the base model at step 0.

    Works for both model families: the MoE transformer's attention
    projections share the dense family's names and shapes, so
    attention-target LoRA (the classic recipe) applies unchanged —
    only the expert SwiGLU weights are off-limits (they carry a
    leading ``n_experts`` axis; per-expert adapters are a different
    object)."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    _check_targets(targets)
    from .moe import MoEConfig
    if isinstance(cfg, MoEConfig):
        bad = [t for t in targets if t in ("w_gate", "w_up", "w_down")]
        if bad:
            raise ValueError(
                f"LoRA targets {bad} are expert weights on a MoE "
                f"config (leading n_experts axis); target the "
                f"attention projections {ATTN_TARGETS} instead")
    dtype = dtype if dtype is not None else cfg.dtype
    L = cfg.n_layers
    dims = layer_weight_dims(cfg)
    layers = {}
    for name, k in zip(targets, jax.random.split(key, len(targets))):
        d_in, d_out = dims[name]
        layers[name] = {
            "a": (jax.random.normal(k, (L, d_in, rank), jnp.float32)
                  / jnp.sqrt(d_in)).astype(dtype),
            "b": jnp.zeros((L, rank, d_out), dtype),
        }
    return {"layers": layers}


def lora_merge(params: dict, lora: dict, *, alpha: float = 16.0) -> dict:
    """Base params with ``(a @ b) * alpha/r`` added to each targeted
    weight.  Differentiable in ``lora``; the base stays frozen by
    construction when only ``lora`` is a differentiated argument."""
    merged_layers = dict(params["layers"])
    for name, ab in lora["layers"].items():
        rank = ab["a"].shape[-1]
        scale = alpha / rank
        base = params["layers"][name]
        delta = jnp.einsum("lir,lro->lio", ab["a"].astype(jnp.float32),
                           ab["b"].astype(jnp.float32)) * scale
        merged_layers[name] = (base.astype(jnp.float32)
                               + delta).astype(base.dtype)
    out = dict(params)
    out["layers"] = merged_layers
    return out


def lora_shardings(cfg: TransformerConfig, lora_or_targets) -> dict:
    """``PartitionSpec`` rules for the adapter pytree, derived from the
    base weight's Megatron split (column-split → shard ``b``'s output
    dim; row-split → shard ``a``'s input dim; rank axis replicated)."""
    targets = (tuple(lora_or_targets["layers"])
               if isinstance(lora_or_targets, dict) else
               tuple(lora_or_targets))
    _check_targets(targets)
    layers = {}
    for name in targets:
        if _SPLIT[name] == "col":
            layers[name] = {"a": P(None, None, None),
                            "b": P(None, None, "tp")}
        else:
            layers[name] = {"a": P(None, "tp", None),
                            "b": P(None, None, None)}
    return {"layers": layers}


def lora_num_params(lora: dict) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(lora))


def make_lora_train_step(cfg: TransformerConfig, optimizer, *,
                         alpha: float = 16.0, sp=None, mesh=None,
                         ep_axis: str = "ep"):
    """Returns ``step(base_params, lora, opt_state, batch) ->
    (lora, opt_state, loss)``.  Only the adapter pytree is
    differentiated and updated; optimizer state exists only for adapter
    leaves.  Shard ``base_params`` with ``param_shardings`` and ``lora``
    with :func:`lora_shardings`, then jit over any dp/tp mesh exactly
    like the full train step.  ``sp`` (a ``SeqParallel``) additionally
    runs attention sequence-parallel — long-context LoRA fine-tuning
    composes for free because the merge happens before the forward.

    A :class:`~.moe.MoEConfig` dispatches to the MoE loss (load
    balance included); ``mesh``/``ep_axis`` route its expert
    all-to-alls — adapter fine-tuning of a Mixtral-class model on a
    dp×ep mesh uses the identical step shape."""
    from .moe import MoEConfig, moe_loss_fn

    if isinstance(cfg, MoEConfig):
        def base_loss(p, batch):
            return moe_loss_fn(p, batch, cfg, mesh=mesh,
                               ep_axis=ep_axis, sp=sp)
    else:
        def base_loss(p, batch):
            return loss_fn(p, batch, cfg, sp)

    def step(base_params, lora, opt_state, batch):
        def adapted_loss(l):
            return base_loss(lora_merge(base_params, l, alpha=alpha),
                             batch)

        loss, grads = jax.value_and_grad(adapted_loss)(lora)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        return apply_optimizer_updates(lora, updates), opt_state, loss

    return step
