"""Speculative decoding (Leviathan et al. 2022, arXiv:2211.17192).

A small draft model proposes ``gamma`` tokens autoregressively; the
target model scores all of them in ONE batched forward (prefill-shaped
work, MXU-friendly), and the longest valid prefix is accepted.  Decode
latency is bounded by target-model *forwards per accepted token*, which
drops from 1 to ~1/(mean accepted + 1) — and TPU-native here because
both the proposal loop and the verify pass reuse the static-shape
KV-cache machinery (models/generate.py: fixed-length caches,
position-masked attention).

**Batched streams share every forward.**  All B streams ride one
(B, gamma+1) verify call and one (B, 1) draft call per proposal step —
the verify matmuls grow along the batch axis, which is exactly how the
MXU wants them (a B=8 verify is ~the cost of a B=1 verify at these
sizes, so speculation's win multiplies across streams).  Streams accept
different prefix lengths per round, so each row keeps its own logical
cache pointer: ``forward_with_cache`` takes a per-row ``(B,)``
``cache_len``, positions are masked per row (``t <= pos_b``), and cache
writes land at per-row offsets.  Rollback is free by construction:
rejecting tokens just moves a row's pointer back — stale slots are
position-masked until overwritten.

Finished streams freeze: their advance is masked to zero and their
(recomputed, identical) writes land in slots beyond the output slice,
so the while-loop runs until the *slowest* stream reaches
``max_new_tokens`` without any stream overshooting its committed
output.

Stream independence holds exactly for the dense family (asserted
bit-identical to solo runs in the tests).  For MoE configs, frozen
streams are *masked out of expert dispatch* (``row_mask`` →
``moe_ffn(token_mask=...)``): their discarded recomputation takes no
capacity slot, so finishing early never perturbs a live stream.  The
remaining (inherent) qualification: capacity-based expert dispatch
pools all *live* rows' tokens into one capacity buffer, so under
tight capacity batched MoE decode can drop tokens a solo run would
keep — batched speculative MoE matches batched MoE decode semantics.

Greedy mode reproduces the target model's own greedy decode (verified
bit-identical against :func:`~.generate.generate` in the fp32 tests) —
with the usual batched-vs-stepwise numerics caveat: the verify pass
scores gamma+1 tokens in one forward while ``generate`` decodes S=1 at
a time, so in bf16 a near-tied top-2 logit can round differently and
flip an argmax.  Sampled mode implements the modified rejection scheme
per stream: accept draft token d_i with probability
``min(1, p_t(d_i)/p_d(d_i))``; on the first rejection resample from
``normalize(max(0, p_t - p_d))``; if all gamma survive, sample the
bonus token from the target's next-position distribution.  The output
distribution equals sampling from the target alone, independently per
stream.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .generate import forward_with_cache, init_kv_cache, truncate_logits
from .transformer import TransformerConfig


def _greedy_tok(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def spec_round(params, draft_params, cfg, draft_cfg, *, gamma: int,
               temperature: float, cache_t, len_t, cache_d, len_d,
               last_tok, key, active, mesh=None, ep_axis: str = "ep",
               top_k: int | None = None, top_p: float | None = None):
    """ONE draft-propose / target-verify round for B streams — the
    engine shared by :func:`speculative_generate`'s closed loop and
    the continuous-batching server's speculative mode.

    State contract (the lag-one cache discipline): both caches hold
    exactly the committed tokens' K/V below their pointers, and
    ``last_tok`` is the newest committed token, NOT yet written to
    either cache — each model re-feeds it first, which is why both
    pointers advance by ``n_acc + 1``.

    Returns ``(cache_t, len_t, cache_d, len_d, key, cand, n_acc,
    new_last)``: ``cand`` (B, gamma+1) holds each row's candidate
    tokens (accepted prefix + correction/bonus at index ``n_acc``;
    later entries stale), ``n_acc`` (B,) the accepted draft counts,
    ``new_last`` the per-row newest committed token.  Rows with
    ``active=False`` freeze: pointers do not advance (callers mask),
    and ``row_mask`` keeps them out of MoE expert capacity.
    """
    B = last_tok.shape[0]

    def draft_step(carry, i):
        cache_d, len_d, tok, key = carry
        lg, cache_d = forward_with_cache(
            draft_params, tok[:, None], cache_d, len_d, draft_cfg,
            row_mask=active, mesh=mesh, ep_axis=ep_axis)
        key, ks = jax.random.split(key)
        nxt = _sample_1(lg[:, -1], temperature, ks, top_k, top_p)  # (B,)
        return (cache_d, len_d + 1, nxt, key), (nxt, lg[:, -1])

    (cache_d, _, _, key), (drafts, draft_logits) = \
        jax.lax.scan(draft_step, (cache_d, len_d, last_tok, key),
                     jnp.arange(gamma))
    # drafts: (gamma, B) int32; draft_logits: (gamma, B, V)
    # The scan wrote K/V for [newest, d_1..d_{gamma-1}] — d_gamma's
    # K/V is still missing, and the n_acc == gamma round needs it
    # (the pointer then advances past its slot).  One more write
    # (logits discarded) keeps the lag-one invariant for every
    # n_acc; the slot is stale-and-masked when d_gamma is rejected.
    _, cache_d = forward_with_cache(
        draft_params, drafts[-1][:, None], cache_d,
        len_d + gamma, draft_cfg, row_mask=active, mesh=mesh,
        ep_axis=ep_axis)

    # --- target verifies the newest token + all proposals ------
    # ONE forward shared by every stream: (B, gamma+1) — this
    # batched verify is the speedup's engine room.
    verify_in = jnp.concatenate([last_tok[:, None], drafts.T],
                                axis=1)              # (B, g+1)
    logits_v, cache_t = forward_with_cache(
        params, verify_in, cache_t, len_t, cfg,
        row_mask=active, mesh=mesh, ep_axis=ep_axis)  # (B, g+1, V)

    key, kacc, kfix = jax.random.split(key, 3)
    # top_k/top_p bind via partial (static ints for lax.top_k — they
    # must not pass through vmap as mapped operands).
    n_acc, next_tok = jax.vmap(
        functools.partial(_accept, top_k=top_k, top_p=top_p),
        in_axes=(1, 1, 0, None, 0, 0))(
        drafts, draft_logits, logits_v, temperature,
        jax.random.split(kacc, B), jax.random.split(kfix, B))

    cand = jnp.concatenate(
        [drafts.T, jnp.zeros((B, 1), jnp.int32)], axis=1)
    cand = cand.at[jnp.arange(B), n_acc].set(next_tok)
    adv = jnp.where(active, n_acc + 1, 0)
    new_last = jnp.where(active, next_tok, last_tok)
    return (cache_t, len_t + adv, cache_d, len_d + adv, key, cand,
            n_acc, new_last)


def speculative_generate(params: dict, draft_params: dict,
                         prompt, cfg: TransformerConfig,
                         draft_cfg: TransformerConfig,
                         max_new_tokens: int, *, gamma: int = 4,
                         temperature: float = 0.0, key=None,
                         top_k: int | None = None,
                         top_p: float | None = None,
                         max_len: int | None = None,
                         kv_quantized: bool = False,
                         mesh=None, ep_axis: str = "ep"):
    """Generate ``max_new_tokens`` continuations of ``prompt`` (B, S0)
    with draft-proposed, target-verified decoding.

    Both models must share the vocabulary.  Greedy when
    ``temperature == 0`` — each stream's output reproduces the target's
    own greedy decode (see the module docstring for the
    batched-vs-stepwise numerics caveat); otherwise the
    rejection-sampling scheme preserves the target's sampling
    distribution per stream (``key`` required).  ``top_k``/``top_p``
    compose with sampling via truncation-aware acceptance (draft
    proposals and the rejection test both use the truncated
    distributions — see :func:`_accept`): the output distribution
    equals ``generate(..., top_k=, top_p=)``'s.

    Returns (tokens (B, S0 + max_new_tokens), mean_accepted) — the
    second value is the average number of draft tokens accepted per
    verify round per active stream (max ``gamma``), the quantity that
    sets the speedup.

    With ``mesh``, both KV caches are created sharded (batch over
    ``dp``, KV heads over ``tp``) and every forward routes through the
    mesh-aware decode path (``_flash_decode_on_mesh`` for the S=1
    draft steps; MoE expert all-to-alls over ``ep_axis``) — pass
    target/draft params sharded by ``param_shardings``.
    """
    B = prompt.shape[0]
    if B < 1:
        raise ValueError(f"need at least one stream, got batch {B}")
    if prompt.shape[1] == 0:
        raise ValueError("cannot generate from an empty prompt "
                         "(S == 0)")
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("target and draft must share a vocabulary")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got "
                         f"{max_new_tokens}")
    if temperature != 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    if top_k is not None and not 1 <= top_k <= cfg.vocab_size:
        raise ValueError(f"top_k must be in [1, vocab_size="
                         f"{cfg.vocab_size}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if key is None:
        key = jax.random.PRNGKey(0)

    S0 = prompt.shape[1]
    # The token buffer over-allocates one whole round (gamma + 1) so a
    # final round can write past the target count; the result is
    # sliced to exactly max_new_tokens.
    buf_len = S0 + max_new_tokens + gamma + 1
    T = max_len if max_len is not None else buf_len
    if T < buf_len:
        raise ValueError(f"max_len {T} < required {buf_len} "
                         f"(prompt + max_new_tokens + gamma + 1)")
    # int8 caches compose transparently: forward_with_cache dispatches
    # on the cache keys, and rollback-by-pointer works identically.
    cache_t = init_kv_cache(cfg, B, T, mesh=mesh,
                            quantized=kv_quantized)
    cache_d = init_kv_cache(draft_cfg, B, T, mesh=mesh,
                            quantized=kv_quantized)

    # Prefill both models on the prompt (streams still aligned, so the
    # pointer is a shared scalar 0 here); the target's last-position
    # logits seed the first accepted token of every stream.
    logits_t, cache_t = forward_with_cache(params, prompt, cache_t, 0,
                                           cfg, last_only=True,
                                           mesh=mesh, ep_axis=ep_axis)
    _, cache_d = forward_with_cache(draft_params, prompt, cache_d, 0,
                                    draft_cfg, last_only=True,
                                    mesh=mesh, ep_axis=ep_axis)

    key, k0 = jax.random.split(key)
    first = _sample_1(logits_t[:, -1], temperature, k0,
                      top_k, top_p)                          # (B,)

    toks = jnp.zeros((B, buf_len), jnp.int32)
    toks = jax.lax.dynamic_update_slice(toks, prompt, (0, 0))
    toks = toks.at[:, S0].set(first)

    # Carried state: token buffer, per-stream #generated (>=1 after the
    # seed), both caches with their per-stream logical lengths (prompt
    # is in both), rng, and the accept-count accumulators.  The caches
    # MUST ride the loop carry — accepted tokens' K/V written in round
    # r are read in every later round.
    ones = jnp.ones((B,), jnp.int32)
    state = (toks, ones, cache_t, S0 * ones, cache_d, S0 * ones, key,
             jnp.float32(0.0), jnp.float32(0.0))

    def cond(state):
        return jnp.any(state[1] < max_new_tokens)

    def body(state):
        (toks, n, cache_t, len_t, cache_d, len_d, key, acc_sum,
         active_rounds) = state
        done = n >= max_new_tokens                       # (B,)
        pos_last = S0 + n - 1          # buffer index of newest token
        last_tok = jnp.take_along_axis(
            toks, pos_last[:, None], axis=1)[:, 0]       # (B,)
        active = ~done  # frozen rows: no expert-capacity footprint

        (cache_t, len_t, cache_d, len_d, key, upd, n_acc, _) = \
            spec_round(params, draft_params, cfg, draft_cfg,
                       gamma=gamma, temperature=temperature,
                       cache_t=cache_t, len_t=len_t, cache_d=cache_d,
                       len_d=len_d, last_tok=last_tok, key=key,
                       active=active, mesh=mesh, ep_axis=ep_axis,
                       top_k=top_k, top_p=top_p)

        # --- commit ------------------------------------------------
        # Write all gamma+1 candidate slots per row; only the first
        # n_acc + 1 are real — the counter never reaches the stale
        # tail before a later round overwrites it.  Finished rows
        # advance by 0; their (frozen-pointer) writes land at or past
        # S0 + max_new_tokens, outside the output slice — dynamic
        # slice clamping keeps even the overshoot case in that region.
        toks = jax.vmap(
            lambda row, u, s: jax.lax.dynamic_update_slice(row, u,
                                                           (s,)))(
            toks, upd, pos_last + 1)
        n = n + jnp.where(done, 0, n_acc + 1)
        acc_sum = acc_sum + jnp.sum(
            jnp.where(done, 0.0, n_acc.astype(jnp.float32)))
        active_rounds = active_rounds + jnp.sum(
            (~done).astype(jnp.float32))
        return (toks, n, cache_t, len_t, cache_d, len_d, key,
                acc_sum, active_rounds)

    toks, n, _, _, _, _, _, acc_sum, active_rounds = jax.lax.while_loop(
        cond, body, state)
    out = jax.lax.dynamic_slice(
        toks, (0, 0), (B, S0 + max_new_tokens))
    mean_acc = acc_sum / jnp.maximum(active_rounds, 1.0)
    return out, mean_acc


def _sample_1(logits, temperature: float, key,
              top_k: int | None = None, top_p: float | None = None):
    """(B, V) or (V,) logits -> (B,) int32 tokens (independent rows).
    ``top_k``/``top_p`` truncate the distribution before sampling
    (see :func:`~.generate.truncate_logits`)."""
    if temperature == 0.0:
        return _greedy_tok(jnp.atleast_2d(logits))
    logits = truncate_logits(jnp.atleast_2d(logits) / temperature,
                             top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _accept(drafts, draft_logits, verify_logits, temperature: float,
            kacc, kfix, *, top_k: int | None = None,
            top_p: float | None = None):
    """Acceptance rule for one round of one stream (vmapped over B).

    drafts: (g,) proposed tokens; draft_logits: (g, V) the draft's
    logits at each proposal; verify_logits: (g+1, V) the target's
    logits at [newest, d_1..d_g] — position i scores d_{i+1}.
    Returns (n_acc in [0, g], next token after the accepted prefix).

    ``top_k``/``top_p`` implement truncation-aware speculative
    sampling: BOTH distributions are filtered with the same knobs
    before the rejection test.  The accept/resample lemma holds for
    any (p, q) pair, so the emitted distribution equals sampling from
    the *truncated target* — exactly what ``generate(top_k=, top_p=)``
    samples.  The draft proposals must be drawn from the same
    truncated draft distribution (:func:`_sample_1` with the same
    knobs), which also keeps ``q(d_i) > 0`` for every proposal.
    """
    g = drafts.shape[0]
    if temperature == 0.0:
        # Greedy: accept while the target's argmax equals the draft
        # (truncation never changes an argmax: top-k keeps the k
        # largest, nucleus always keeps the top-1 token).
        tgt = _greedy_tok(verify_logits)             # (g+1,)
        match = tgt[:g] == drafts
        n_acc = jnp.argmin(jnp.concatenate(
            [match, jnp.zeros((1,), bool)])).astype(jnp.int32)
        # next token: target's argmax at the divergence position
        # (== bonus position when everything matched).
        return n_acc, tgt[n_acc]

    pt = jax.nn.softmax(truncate_logits(
        verify_logits / temperature, top_k, top_p), axis=-1)  # (g+1,V)
    pd = jax.nn.softmax(truncate_logits(
        draft_logits / temperature, top_k, top_p), axis=-1)   # (g,V)
    pt_i = jnp.take_along_axis(pt[:g], drafts[:, None], axis=-1)[:, 0]
    pd_i = jnp.take_along_axis(pd, drafts[:, None], axis=-1)[:, 0]
    u = jax.random.uniform(kacc, (g,))
    ok = u < jnp.minimum(1.0, pt_i / jnp.maximum(pd_i, 1e-20))
    n_acc = jnp.argmin(jnp.concatenate(
        [ok, jnp.zeros((1,), bool)])).astype(jnp.int32)

    # Residual distribution at the rejection position; at the bonus
    # position (all accepted) the residual is just p_t itself.
    pt_at = pt[n_acc]
    pd_at = jnp.where(n_acc < g, pd[jnp.minimum(n_acc, g - 1)], 0.0)
    resid = jnp.maximum(pt_at - pd_at, 0.0)
    resid = resid / jnp.maximum(jnp.sum(resid), 1e-20)
    nxt = jax.random.choice(kfix, resid.shape[-1], p=resid)
    return n_acc, nxt.astype(jnp.int32)
