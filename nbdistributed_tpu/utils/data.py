"""Per-rank data sharding for interactive DDP training.

The reference's demo delegates data distribution to HF Accelerate
(``accelerator.prepare(dataloader)`` shards batches across ranks —
reference: 00_accelerate.ipynb cells 28-36); this module is the
framework-native equivalent for cell-driven training: deterministic,
rank-local views of a host-resident dataset, shaped for jit (static
batch shapes, drop-remainder) and for dp meshes (``shard_batch``
composes on top for in-process meshes).

Everything here is plain host-side slicing — no torch, no dataloader
processes.  On TPU the input pipeline's job is simply to hand XLA a
static-shape array per step; :func:`prefetch_to_device` adds the one
piece of that worth owning — issuing the (async) host→device transfer
``size`` batches ahead so H2D DMA overlaps the current step's compute
— without any threads, because ``jax.device_put`` already is async.
Tokenization and fancier loading belong in user code or upstream
libraries.
"""

from __future__ import annotations

from typing import Any, Iterator, Sequence

import numpy as np


def rank_slice(n: int, rank: int, world_size: int) -> slice:
    """Contiguous near-equal split of ``n`` items: the first ``n %
    world_size`` ranks get one extra item.  Deterministic and
    partition-exact (the slices tile [0, n))."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside world of {world_size}")
    base, extra = divmod(n, world_size)
    start = rank * base + min(rank, extra)
    return slice(start, start + base + (1 if rank < extra else 0))


def _check_aligned(arrays: dict[str, np.ndarray]) -> int:
    keys = list(arrays)
    n = len(arrays[keys[0]])
    for k in keys:
        if len(arrays[k]) != n:
            raise ValueError(
                f"leading-axis mismatch: {keys[0]}={n}, "
                f"{k}={len(arrays[k])}")
    return n


def shard_arrays(batch: dict[str, Any], rank: int,
                 world_size: int) -> dict[str, Any]:
    """Slice every leading axis of a dict-of-arrays by rank."""
    arrays = {k: np.asarray(v) for k, v in batch.items()}
    sl = rank_slice(_check_aligned(arrays), rank, world_size)
    return {k: v[sl] for k, v in arrays.items()}


def batch_iterator(data: dict[str, Any], *, batch_size: int, rank: int,
                   world_size: int, seed: int | None = 0,
                   drop_remainder: bool = True,
                   epochs: int | None = 1) -> Iterator[dict[str, Any]]:
    """Deterministic per-rank minibatch stream over a dict-of-arrays.

    Every rank must construct this with the SAME ``seed`` — the
    permutation is generated identically everywhere and each rank takes
    its own stride through it (global batch = world_size ×
    ``batch_size``, rank r takes rows [r·bs, (r+1)·bs) of each global
    batch).  ``drop_remainder=True`` keeps shapes static for jit: a
    trailing global batch smaller than world_size × batch_size is
    dropped.  With ``drop_remainder=False`` the trailing batch is split
    near-equally across ranks (ragged shapes → one extra jit trace) —
    and is dropped entirely when it has fewer rows than ranks, so every
    rank always yields the SAME number of batches: a rank-dependent
    count would deadlock the first collective of the step some ranks
    never run.  ``epochs=None`` streams forever (reshuffling each
    epoch).  All validation happens at call time, not first ``next()``.
    """
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside world of {world_size}")
    keys = list(data)
    arrays = {k: np.asarray(v) for k, v in data.items()}
    n = _check_aligned(arrays)
    global_bs = batch_size * world_size
    if n < global_bs and (drop_remainder or n < world_size):
        raise ValueError(
            f"{n} examples < one global batch ({global_bs}); lower "
            f"batch_size or world size")

    def gen():
        epoch = 0
        while epochs is None or epoch < epochs:
            if seed is None:
                perm = np.arange(n)
            else:
                perm = np.random.default_rng(seed + epoch).permutation(n)
            for start in range(0, n - n % global_bs, global_bs):
                gidx = perm[start:start + global_bs]
                ridx = gidx[rank * batch_size:(rank + 1) * batch_size]
                yield {k: arrays[k][ridx] for k in keys}
            tail = n % global_bs
            if not drop_remainder and tail >= world_size:
                gidx = perm[n - tail:]
                ridx = gidx[rank_slice(tail, rank, world_size)]
                yield {k: arrays[k][ridx] for k in keys}
            epoch += 1

    return gen()


def prefetch_to_device(batches, *, size: int = 2,
                       sharding=None) -> Iterator[Any]:
    """Run ``jax.device_put`` ``size`` batches ahead of consumption.

    ``jax.device_put`` is asynchronous: issuing the transfer early is
    all it takes to overlap the H2D DMA with the current step's
    compute — no prefetch thread, no staging buffers to manage.  A
    depth of 2 (current + next in flight) captures the whole win; the
    queue costs ``size`` device copies of one batch.

    ``sharding`` (e.g. ``NamedSharding(mesh, P("dp"))``) places each
    pytree leaf directly in its dp-sharded layout, so the per-step
    path is transfer-only — no device-side resharding.  Yields batches
    in order; safe on any iterator length (including empty).
    """
    import collections

    import jax

    # Validate at call time, not first next() (same convention as
    # batch_iterator): misconfiguration should point here.
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    it = iter(batches)

    def put(b):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), b)

    def gen():
        q: collections.deque = collections.deque()
        try:
            while len(q) < size:
                q.append(put(next(it)))
        except StopIteration:
            pass
        while q:
            out = q.popleft()
            try:
                q.append(put(next(it)))
            except StopIteration:
                pass
            yield out

    return gen()


def interleave_shards(shards: Sequence[dict[str, Any]]) -> dict[str, Any]:
    """Reassemble per-rank batches into the global batch (test/eval
    helper; inverse of one step of :func:`batch_iterator`)."""
    keys = list(shards[0])
    return {k: np.concatenate([np.asarray(s[k]) for s in shards])
            for k in keys}


def pack_tokens(docs: Sequence[Sequence[int]], seq_len: int, *,
                eos_id: int | None = None,
                drop_remainder: bool = True,
                return_segments: bool = False):
    """Pack variable-length token documents into fixed (N, seq_len)
    windows — the standard LM-pretraining prep: concatenate all docs
    (optionally ``eos_id``-separated) and chunk the stream.

    Static output shapes are the TPU contract: every window is exactly
    ``seq_len`` tokens; a trailing partial window is dropped (default)
    or right-padded with ``eos_id`` (requires one).  Feed windows of
    ``seq_len = model_S`` straight into the logits-shift loss
    (``models.transformer.loss_fn`` predicts positions 1..S-1 from
    0..S-2 — no +1 fencepost to manage).

    ``return_segments=True`` additionally returns per-window document
    ids (N, seq_len) int32 (global doc index; eos separators belong to
    the document they end, trailing padding to the final one) — feed
    them as ``batch["segments"]`` so attention masks across documents,
    RoPE restarts per document, and boundary targets drop from the
    loss; without them packed windows silently leak attention across
    documents.
    """
    if seq_len < 2:
        raise ValueError(f"seq_len must be >= 2, got {seq_len}")
    parts: list[np.ndarray] = []
    seg_parts: list[np.ndarray] = []
    for i, d in enumerate(docs):
        arr = np.asarray(d, np.int32).ravel()
        n = len(arr) + (1 if eos_id is not None else 0)
        parts.append(arr)
        if eos_id is not None:
            parts.append(np.asarray([eos_id], np.int32))
        seg_parts.append(np.full((n,), i, np.int32))
    stream = (np.concatenate(parts) if parts
              else np.zeros((0,), np.int32))
    segs = (np.concatenate(seg_parts) if seg_parts
            else np.zeros((0,), np.int32))
    n_full, tail = divmod(len(stream), seq_len)
    if tail and not drop_remainder:
        if eos_id is None:
            raise ValueError(
                "drop_remainder=False needs eos_id to pad the "
                "trailing window")
        pad = np.full((seq_len - tail,), eos_id, np.int32)
        stream = np.concatenate([stream, pad])
        segs = np.concatenate(
            [segs, np.full((seq_len - tail,), segs[-1], np.int32)])
        n_full += 1
    windows = stream[: n_full * seq_len].reshape(n_full, seq_len)
    if not return_segments:
        return windows
    return windows, segs[: n_full * seq_len].reshape(n_full, seq_len)
