"""JAX version-compatibility shims.

The repo targets the current ``jax.shard_map`` API (``check_vma=``),
but container images pin a range of JAX releases: on 0.4.x the
function only exists as ``jax.experimental.shard_map.shard_map`` and
the replication check is spelled ``check_rep=``.  Every internal call
site goes through :func:`shard_map` so the version split lives in
exactly one place.

No module-level ``jax`` import: several callers (parallel/collectives)
deliberately defer JAX import until first use so platform-selection
config updates still win.
"""

from __future__ import annotations


def shard_map(f, **kw):
    """``jax.shard_map(f, **kw)`` on any supported JAX version.

    Accepts the modern keyword set; on legacy JAX (no ``jax.shard_map``)
    ``check_vma`` is translated to its old name ``check_rep``.
    """
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        return native(f, **kw)
    from jax.experimental.shard_map import shard_map as legacy

    if "check_vma" in kw:
        kw["check_rep"] = kw.pop("check_vma")
    return legacy(f, **kw)


def axis_size(axis_name):
    """``jax.lax.axis_size`` on any supported JAX version (legacy
    releases spell it ``psum(1, axis)``, which XLA folds to a
    constant)."""
    from jax import lax

    native = getattr(lax, "axis_size", None)
    if native is not None:
        return native(axis_name)
    return lax.psum(1, axis_name)
