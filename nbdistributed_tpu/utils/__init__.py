"""Shared small utilities used across the model/parallel stack.

Deliberately lazy: ``utils.knobs`` (the env-knob registry) is imported
by stdlib-only modules (resilience/, observability/) that must not pay
a JAX import, so nothing heavy may execute at package-import time —
``fan_in_normal`` resolves jax inside the call, and the ``data``
re-exports resolve through module ``__getattr__`` (PEP 562).
"""

from __future__ import annotations

_DATA_EXPORTS = ("batch_iterator", "interleave_shards",
                 "prefetch_to_device", "rank_slice", "shard_arrays")

__all__ = ["fan_in_normal", *_DATA_EXPORTS]


def fan_in_normal(key, shape, fan_in, dtype):
    """Gaussian init scaled by 1/sqrt(fan_in), cast to ``dtype`` —
    the one initializer every model family uses."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    return (jax.random.normal(key, shape, jnp.float32)
            / np.sqrt(fan_in)).astype(dtype)


def __getattr__(name: str):
    if name in _DATA_EXPORTS:
        from . import data
        return getattr(data, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
