"""Shared small utilities used across the model/parallel stack."""

from __future__ import annotations

import jax
import numpy as np


def fan_in_normal(key, shape, fan_in, dtype):
    """Gaussian init scaled by 1/sqrt(fan_in), cast to ``dtype`` —
    the one initializer every model family uses."""
    import jax.numpy as jnp

    return (jax.random.normal(key, shape, jnp.float32)
            / np.sqrt(fan_in)).astype(dtype)


from .data import (batch_iterator, interleave_shards,
                   prefetch_to_device, rank_slice, shard_arrays)

__all__ = ["fan_in_normal", "batch_iterator", "interleave_shards",
           "prefetch_to_device", "rank_slice", "shard_arrays"]
