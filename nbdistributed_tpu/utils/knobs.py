"""The NBD_* environment-knob registry — every env knob in one table.

Every ``NBD_*`` variable the framework (or its tools/bench harness)
reads MUST be declared here.  The declaration is load-bearing three
ways:

- the accessors below are the one choke point for env reads, so a
  typo'd knob name fails fast instead of silently reading nothing;
- ``tools/nbd_lint.py --self`` (analysis/selfcheck.py) walks the tree
  and fails CI on any ``NBD_*`` string that is not declared here, and
  on any declared knob missing from README's configuration reference;
- :func:`knob_table_markdown` renders the README "Configuration
  reference" table from this registry, so docs cannot drift from code.

Stdlib-only and import-light on purpose: resilience/ and
observability/ modules import this at startup.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class Knob:
    name: str
    default: str | None   # shown in docs; None = unset/required-by-context
    kind: str             # str | int | float | bool | json | path
    doc: str
    scope: str = "core"   # grouping for the README table


def _k(name, default, kind, doc, scope="core"):
    return Knob(name, default, kind, doc, scope)


_ALL = (
    # --- core / topology ------------------------------------------------
    _k("NBD_RUN_DIR", None, "path",
       "Shared per-session run directory (flight rings, stack dumps, "
       "session manifest, postmortem bundles). Minted and exported by "
       "the first coordinator when unset."),
    _k("NBD_HOST", "local", "str",
       "This process's host label in a multi-host world (set by the "
       "launch plan; feeds link-fault shaping and per-host status)."),
    _k("NBD_COORD_HOST", "local", "str",
       "The coordinator's host label as seen by a worker (set by the "
       "launch plan; the worker side of each link pair)."),
    _k("NBD_NATIVE", None, "str",
       "Control-plane transport override: 1 = require the native C++ "
       "listener, 0 = force the pure-Python one, unset = auto."),
    _k("NBD_AUTH_TOKEN", None, "str",
       "Shared secret for non-loopback control-plane binds (multi-host "
       "worlds); shipped to workers via their environment."),
    _k("NBD_AGENT_TOKEN", None, "str",
       "Admission secret for dialing nbd_agent host daemons "
       "(%dist_init --agents); distinct from the per-session token."),
    _k("NBD_AGENT_READY", None, "str",
       "Set by tools/nbd_agent.py in its readiness line (internal "
       "handshake marker for launchers that scrape agent stdout)."),
    # --- durable sessions ----------------------------------------------
    _k("NBD_SESSION_TOKEN", None, "str",
       "Durable-session identity a worker was spawned under (set by "
       "%dist_init; proves a reattaching coordinator resumes THIS "
       "session).", "session"),
    _k("NBD_SESSION_EPOCH", "0", "int",
       "Session epoch a worker was spawned under; only a hello "
       "exchange may raise it (stale-coordinator fencing).", "session"),
    _k("NBD_ORPHAN_TTL_S", "600", "float",
       "Seconds an orphaned worker (coordinator gone) keeps running "
       "and reattachable before self-terminating; 0 = legacy exit-on-"
       "disconnect.", "session"),
    _k("NBD_GC_TTL_S", "21600", "float",
       "Stale-run age for %dist_gc / nbd-gc sweeps of abandoned "
       "session run dirs.", "session"),
    # --- retry / redelivery ---------------------------------------------
    _k("NBD_RETRY_TIMEOUT_S", None, "float",
       "Per-attempt response wait; PRESENCE enables request "
       "redelivery.", "retry"),
    _k("NBD_RETRY_ATTEMPTS", "4", "int",
       "Total deliveries per request (1 initial + N-1 redeliveries).",
       "retry"),
    _k("NBD_RETRY_CLASS_BULK_TIMEOUT_S", None, "float",
       "Bulk-class (push/pull/checkpoint) per-attempt budget override.",
       "retry"),
    _k("NBD_RETRY_CLASS_BULK_ATTEMPTS", None, "int",
       "Bulk-class delivery-count override.", "retry"),
    _k("NBD_RETRY_CLASS_CONTROL_TIMEOUT_S", None, "float",
       "Control-class per-attempt budget override.", "retry"),
    _k("NBD_RETRY_CLASS_CONTROL_ATTEMPTS", None, "int",
       "Control-class delivery-count override.", "retry"),
    # --- chaos / fault injection ----------------------------------------
    _k("NBD_FAULT_PLAN", None, "json",
       "Spawn-time deterministic fault-plan spec (the %dist_chaos "
       "knobs as JSON) — CI's chaos entry point.", "chaos"),
    # --- hang watchdog ---------------------------------------------------
    _k("NBD_HANG", "1", "bool",
       "Master switch for hang detection; 0 also drops the heartbeat "
       "collective-position piggyback at worker spawn.", "hang"),
    _k("NBD_HANG_POLL_S", "1.0", "float",
       "Watchdog poll cadence.", "hang"),
    _k("NBD_HANG_SKEW_S", "20", "float",
       "Cross-rank lag persistence before a skew verdict.", "hang"),
    _k("NBD_HANG_STALL_S", "120", "float",
       "Busy-with-zero-collective-progress window before a stall "
       "verdict.", "hang"),
    _k("NBD_HANG_GRACE_S", "15", "float",
       "Pause between escalation-ladder steps.", "hang"),
    _k("NBD_HANG_ESCALATE", "warn,dump", "str",
       "Escalation ladder, comma-separated from: warn, dump, "
       "interrupt, heal.", "hang"),
    _k("NBD_PARTITION_GRACE_S", "30", "float",
       "Whole-host silence grace before a suspected partition is "
       "declared lost and healing proceeds.", "hang"),
    # --- async pipelined executor (ISSUE 14) ------------------------------
    _k("NBD_ASYNC_WINDOW", "0", "int",
       "Async in-flight dispatch window for %%distributed cells: N>0 "
       "streams up to N cells to the workers while earlier ones run "
       "(admission gated by the effects/deps DAG — no RAW/WAR/WAW "
       "hazard with any in-flight cell, at most one collective-"
       "bearing cell in flight; opaque cells drain the window and "
       "serialize).  0 (default) keeps every cell synchronous; "
       "%%distributed --async arms the window for one cell.",
       "pipeline"),
    # --- session gateway / multi-tenant pools -----------------------------
    _k("NBD_POOL_SCHED", "fair", "str",
       "Gateway pool scheduling mode: fair (priority, then least-"
       "served tenant) or fifo (arrival order).", "pool"),
    _k("NBD_POOL_MESH_SLOTS", "1", "int",
       "Concurrent cells the pooled mesh runs (0 = unlimited; the "
       "single-kernel path always runs unlimited).  >1 overlaps "
       "cells, which is only safe when at most one of them can run "
       "collectives — arm NBD_POOL_SCHED_EFFECTS so the effect "
       "analyzer PROVES it instead of you assuming it.", "pool"),
    _k("NBD_POOL_SCHED_EFFECTS", "0", "bool",
       "Effects-aware admission (analysis/effects.py): with more "
       "than one mesh slot, only cells proven collective-free may "
       "overlap a collective-bearing cell; unknown/opaque cells "
       "serialize with an explicit 'serialized: ...' verdict naming "
       "the reason.", "pool"),
    _k("NBD_POOL_QUEUE_DEPTH", "64", "int",
       "Queued-cell bound before the pool sheds the lowest-priority "
       "queued cell with a visible verdict (0 = unbounded).", "pool"),
    _k("NBD_TENANT_MAX_INFLIGHT", "8", "int",
       "Per-tenant queued+active cell cap; a tenant at the cap gets "
       "an explicit rejected verdict (0 = uncapped).", "pool"),
    _k("NBD_POOL_MAX_TENANTS", "8", "int",
       "Tenant headcount a gateway admits; later hellos are refused "
       "at admission.", "pool"),
    # --- elastic pools (ISSUE 16) -----------------------------------------
    _k("NBD_AUTOSCALE_MIN", "1", "int",
       "Autoscaler band floor: the pool never shrinks below this "
       "world size, and a world below it is grown back immediately.",
       "elastic"),
    _k("NBD_AUTOSCALE_MAX", "8", "int",
       "Autoscaler band ceiling: the pool never grows past this "
       "world size.", "elastic"),
    _k("NBD_AUTOSCALE_INTERVAL_S", "5.0", "float",
       "Autoscale observe cadence: how often the gateway feeds load "
       "snapshots (queue depth, serving backlog, queue-stage p95) to "
       "the PoolAutoscaler policy.", "elastic"),
    _k("NBD_AUTOSCALE_UP_QUEUE", "4", "int",
       "Scheduler queue depth above which the pool counts as under "
       "pressure (0 disables this signal).", "elastic"),
    _k("NBD_AUTOSCALE_UP_BACKLOG", "8", "int",
       "Serving-plane pending-request backlog above which the pool "
       "counts as under pressure (0 disables this signal).",
       "elastic"),
    _k("NBD_AUTOSCALE_UP_P95_S", "2.0", "float",
       "Latency-observatory queue-stage p95 (seconds) above which "
       "the pool counts as under pressure (0 disables this signal).",
       "elastic"),
    _k("NBD_AUTOSCALE_SUSTAIN_S", "15", "float",
       "Seconds pressure must persist before a grow fires — a single "
       "spike that clears resets the clock (no flapping).", "elastic"),
    _k("NBD_AUTOSCALE_IDLE_S", "120", "float",
       "Seconds of sustained idleness (nothing queued, active, or "
       "pending) before a shrink fires.", "elastic"),
    _k("NBD_AUTOSCALE_COOLDOWN_S", "60", "float",
       "Post-resize decision blackout: no new grow/shrink decision "
       "fires within this window of the last executed (or failed) "
       "resize.", "elastic"),
    _k("NBD_RESIZE_DRAIN_TIMEOUT_S", "120", "float",
       "Resize drain-barrier bound: seconds to wait for in-flight "
       "cells and decode ticks to finish before the resize is "
       "aborted and the pool resumed at its old size.", "elastic"),
    _k("NBD_COMPILE_CACHE_DIR", None, "path",
       "Persistent XLA compilation-cache directory workers enable at "
       "spawn (jax_compilation_cache_dir) so resized-in workers and "
       "new tenants skip the cold compile.  The gateway daemon "
       "defaults it to <run_dir>/xla-cache for its fleet; set 0/off "
       "to disable entirely.", "elastic"),
    # --- serving plane (%dist_serve) --------------------------------------
    _k("NBD_SERVE_MAX_BATCH", "8", "int",
       "Default KV-slot count (continuous-batching width) of the "
       "serving DecodeServer; one scheduler mesh-slot per KV slot.",
       "serve"),
    _k("NBD_SERVE_MAX_LEN", "512", "int",
       "Default KV-cache length of the serving DecodeServer; a "
       "request whose prompt + budget exceeds it is rejected with an "
       "explicit too-long verdict.", "serve"),
    _k("NBD_SERVE_STEPS", "8", "int",
       "Decode steps per serve_step tick — the interleaving "
       "granularity between decoding and notebook cells on the "
       "worker's serial loop.", "serve"),
    _k("NBD_SERVE_QUEUE_DEPTH", "64", "int",
       "Pending-request bound before the serving plane sheds the "
       "lowest-priority pending request with a visible verdict "
       "(0 = unbounded).", "serve"),
    _k("NBD_SERVE_INFLIGHT", "32", "int",
       "Per-submitting-tenant cap on pending + decoding requests; a "
       "tenant at the cap gets an explicit rejected verdict "
       "(0 = uncapped).", "serve"),
    _k("NBD_SERVE_STEP_TIMEOUT_S", "120", "float",
       "Per serve_step round-trip budget; a timed-out tick is "
       "redelivered under the same message id (replay-cache dedup), "
       "and an exhausted retry budget fails over to the next live "
       "rank.", "serve"),
    # --- serving fast path (paged KV + multi-rank decode, ISSUE 17) ------
    _k("NBD_KV_BLOCK_TOKENS", "64", "int",
       "Paged-KV block size in tokens: each serving request reserves "
       "ceil((prompt + max_new) / block) fixed-size cache blocks at "
       "admission, so capacity is measured in blocks rather than "
       "sequences.  0 keeps the dense per-slot cache.", "serve"),
    _k("NBD_KV_BLOCKS_PER_RANK", "0", "int",
       "Paged-KV pool size per decode rank.  0 derives the dense "
       "pool's exact capacity (max_batch x ceil(max_len / block)), so "
       "paging alone never refuses a request the dense server would "
       "have taken; set lower to bound HBM and surface explicit "
       "kv-exhausted verdicts.", "serve"),
    _k("NBD_PREFILL_CHUNK_TOKENS", "0", "int",
       "Chunked-prefill segment size for the serving plane: prompts "
       "longer than this stream in one chunk per decode tick, "
       "interleaved with active streams, so a long prompt can never "
       "starve TPOT.  0 keeps monolithic prefill-on-admit.", "serve"),
    _k("NBD_SERVE_DECODE_RANKS", "1", "int",
       "Decode ranks the serving driver shards requests across "
       "(highest live ranks first; rank 0 last — it hosts "
       "jax.distributed).  0 = every live rank.  Each rank runs its "
       "own DecodeServer; the journal-replay failover covers any "
       "subset dying.", "serve"),
    _k("NBD_LOADGEN_RPS", "4", "float",
       "nbd-loadgen: offered request rate (arrivals per second) of "
       "the closed-loop load run.", "serve"),
    _k("NBD_LOADGEN_DURATION_S", "15", "float",
       "nbd-loadgen: length of the offered-arrival schedule; the run "
       "then drains in-flight requests before reporting.", "serve"),
    _k("NBD_LOADGEN_ARRIVAL", "poisson", "str",
       "nbd-loadgen: arrival process — poisson (exponential gaps) or "
       "uniform (fixed 1/RPS gaps).", "serve"),
    _k("NBD_LOADGEN_SEED", "0", "int",
       "nbd-loadgen: seed of the deterministic arrival/length "
       "schedule (same seed + config = same offered load, "
       "byte-for-byte).", "serve"),
    # --- flight recorder / observability ---------------------------------
    _k("NBD_FLIGHT", "1", "bool",
       "Always-on mmap flight recorder; 0 disables.", "observability"),
    _k("NBD_FLIGHT_RING_BYTES", "262144", "int",
       "Flight-recorder ring-file capacity per process.",
       "observability"),
    _k("NBD_LAT", "1", "bool",
       "Latency observatory: per-cell stage attribution (vet/queue/"
       "wire/dispatch/compile/execute/reply/deliver) stamped through "
       "the optional `lt` wire header. 0 drops the stamps and the "
       "header entirely.", "observability"),
    _k("NBD_LAT_RING", "256", "int",
       "Recent per-cell stage records kept for %dist_lat and "
       "/latency.json.", "observability"),
    _k("NBD_LAT_SKEW_WARN_MS", "50", "float",
       "Clock-skew threshold: %dist_status warns when a rank's "
       "estimated |offset| exceeds it (skew degrades merged traces "
       "and stage attribution). 0 disables the warning.",
       "observability"),
    _k("NBD_SERVE_LAT", "1", "bool",
       "Serving observatory: per-request decode lifecycle "
       "attribution (admit/queue/kv_alloc/prefill/decode_wait/"
       "decode/emit/deliver) + per-tick KV/batching utilization "
       "gauges. 0 keeps the ring but drops metric/gauge exports.",
       "observability"),
    _k("NBD_SERVE_LAT_RING", "256", "int",
       "Recent per-request serving stage records (and utilization "
       "samples) kept for %dist_serve lat and /latency.json.",
       "observability"),
    _k("NBD_PERFWATCH_BASELINE", "BENCH_BASELINES.json", "str",
       "nbd-perfwatch: baseline file the perf-regression sentinel "
       "scores loadgen reports against (repo-root relative or "
       "absolute).", "observability"),
    _k("NBD_PERFWATCH_BAND_SCALE", "1", "float",
       "nbd-perfwatch: uniform multiplier on every baseline noise "
       "band (e.g. 2.0 on a noisy shared runner; bands themselves "
       "are pinned in the baseline file).", "observability"),
    _k("NBD_METRICS_PORT", "0", "int",
       "Live scrape endpoint port (GET /metrics Prometheus text, "
       "/healthz, /latency.json) served by the coordinator or "
       "gateway daemon; 0 = off. Also %dist_pool start "
       "--metrics-port (token-gated on pools).", "observability"),
    # --- training integrity guard (ISSUE 19) ------------------------------
    _k("NBD_GUARD", "1", "bool",
       "Master switch for the training-integrity guard's host-side "
       "machinery (verdict resolution, audits, snapshots, rollback, "
       "chaos injection).  The device-side non-finite skip is "
       "compiled into guard=True steps and is unaffected.", "guard"),
    _k("NBD_GUARD_SKIP_BUDGET", "3", "int",
       "Consecutive non-finite-gradient skips tolerated before the "
       "guard rolls back to the last good snapshot (0 = never roll "
       "back on skips).", "guard"),
    _k("NBD_GUARD_AUDIT_EVERY", "50", "int",
       "Steps between replica-consistency audits (param fingerprint "
       "all-gather + majority vote + repair); 0 disables audits.",
       "guard"),
    _k("NBD_GUARD_SNAPSHOT_EVERY", "50", "int",
       "Steps between in-memory rollback snapshots of params + "
       "optimizer state; 0 disables the snapshot ring.", "guard"),
    _k("NBD_GUARD_SNAPSHOT_KEEP", "2", "int",
       "In-memory snapshots retained in the rollback ring.", "guard"),
    _k("NBD_GUARD_CKPT_EVERY", "0", "int",
       "Steps between durable async checkpoints of the guarded state "
       "(coarser than the snapshot ring; also the no-majority audit "
       "fallback's restore source); 0 = no durable cadence.", "guard"),
    _k("NBD_GUARD_CKPT_PATH", None, "str",
       "Directory for the guard's durable checkpoints (required for "
       "NBD_GUARD_CKPT_EVERY and the no-majority restore fallback).",
       "guard"),
    _k("NBD_GUARD_SPIKE_WINDOW", "64", "int",
       "Rolling loss-history window for the median/MAD spike "
       "detector.", "guard"),
    _k("NBD_GUARD_SPIKE_NMAD", "8.0", "float",
       "MADs above the rolling median a finite loss must land to "
       "count as a spike suspect.", "guard"),
    _k("NBD_GUARD_SPIKE_CONFIRM", "2", "int",
       "Consecutive spike-suspect losses before the spike is "
       "confirmed and triggers a rollback.", "guard"),
    _k("NBD_GUARD_QUARANTINE_AFTER", "2", "int",
       "Audits a rank must land in the minority before it is "
       "escalated as a quarantine suspect (0 = never).", "guard"),
    _k("NBD_CORRUPT_SPEC", None, "json",
       "JSON list of bit-flip/scale corruption specs (rank, step, "
       "name, mode, bits, scale, count) merged into the spawn-time "
       "fault plan — %dist_chaos --corrupt's env twin.", "chaos"),
    # --- bulk-transfer plane (messaging/xfer.py) -------------------------
    _k("NBD_XFER_CHUNK_BYTES", str(4 << 20), "int",
       "Chunk size of the streaming bulk-transfer plane: large "
       "pushes/pulls move as pipelined chunks of this many bytes "
       "(floor 64 KiB).", "xfer"),
    _k("NBD_XFER_WINDOW", "8", "int",
       "Credit window: max chunk sub-messages in flight per "
       "transfer — peak extra memory on either side is window x "
       "chunk, never payload size.", "xfer"),
    _k("NBD_XFER_THRESHOLD_BYTES", str(8 << 20), "int",
       "Payloads at or above this ride the chunked transfer plane; "
       "smaller ones keep the legacy single-frame push/pull.",
       "xfer"),
    _k("NBD_XFER_CODEC", "none", "str",
       "Per-chunk compression: none (default), zlib, lz4, zstd, or "
       "auto (cheapest available); each chunk keeps a 'stored' "
       "escape when compression doesn't pay.", "xfer"),
    _k("NBD_XFER_MIN_BYTES_PER_S", str(1 << 20), "int",
       "Floor transfer rate used to scale per-transfer deadlines: "
       "timeout = max(NBD_XFER_MIN_TIMEOUT_S, bytes / this), so "
       "GB-scale moves don't spuriously time out.", "xfer"),
    _k("NBD_XFER_MIN_TIMEOUT_S", "60", "float",
       "Minimum per-transfer deadline (the old fixed push/pull "
       "timeout, now only a floor).", "xfer"),
    _k("NBD_XFER_INBOUND_MAX", "4", "int",
       "Max concurrent incomplete inbound/outbound transfers a "
       "worker holds before LRU-evicting the oldest.", "xfer"),
    # --- static analysis -------------------------------------------------
    _k("NBD_LINT", "warn", "str",
       "Default pre-dispatch cell-vetting mode: warn (annotate), "
       "strict (block cells with error findings), off.", "lint"),
    # --- selftest / bench / tools ---------------------------------------
    _k("NBD_SELFTEST_FAULTS", None, "bool",
       "nbd-selftest: also run the fault-injection smoke section.",
       "harness"),
    _k("NBD_SELFTEST_OBS", None, "bool",
       "nbd-selftest: also run the observability/postmortem sections.",
       "harness"),
    _k("NBD_SELFTEST_SERVE", None, "bool",
       "nbd-selftest: also run the serving smoke section (2-rank "
       "pool, 3 requests, one injected rank kill).", "harness"),
    _k("NBD_BENCH_ONLY", None, "str",
       "bench.py: comma-separated benchmark families to run.",
       "harness"),
    _k("NBD_BENCH_WORLD", None, "int",
       "bench.py: world size override for multi-process rows.",
       "harness"),
    _k("NBD_BENCH_FAMILY_BUDGET_S", None, "float",
       "bench.py: per-family wall-clock budget.", "harness"),
    _k("NBD_PROBE_CPU_SMOKE", None, "bool",
       "tools/probe_timing.py: run the CPU smoke variant.", "harness"),
)

KNOBS: dict[str, Knob] = {k.name: k for k in _ALL}

# Dynamically-composed knob-name prefixes (f-string builders like
# retry.py's NBD_RETRY_CLASS_<CLASS>_*).  The self-lint accepts a bare
# string constant ending in "_" only when it is declared here.
PREFIXES: frozenset[str] = frozenset({"NBD_RETRY_CLASS_"})

_FALSE = ("0", "false", "off")


def _declared(name: str) -> Knob:
    k = KNOBS.get(name)
    if k is None:
        raise KeyError(
            f"{name} is not a declared knob — add it to "
            f"nbdistributed_tpu/utils/knobs.py (and README's "
            f"configuration reference)")
    return k


def get_raw(name: str, default: str | None = None, *,
            env=None) -> str | None:
    """The raw env value of a DECLARED knob (None when unset and no
    default given).  ``env`` substitutes a mapping for testing —
    the same convention the from_env constructors already use."""
    _declared(name)
    return (os.environ if env is None else env).get(name, default)


def get_str(name: str, default: str | None = None, *,
            env=None) -> str | None:
    return get_raw(name, default, env=env)


def get_float(name: str, default: float, *, env=None) -> float:
    """Float knob; malformed values fall back to ``default`` (an env
    typo must degrade, not crash a worker at spawn)."""
    raw = get_raw(name, env=env)
    if raw is None:
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


def get_int(name: str, default: int, *, env=None) -> int:
    raw = get_raw(name, env=env)
    if raw is None:
        return default
    try:
        return int(raw)
    except (TypeError, ValueError):
        return default


def get_bool(name: str, default: bool = False, *, env=None) -> bool:
    """Bool knob: unset → default; "0"/"false"/"off" (any case) →
    False; anything else truthy."""
    raw = get_raw(name, env=env)
    if raw is None or raw == "":
        return default
    return str(raw).lower() not in _FALSE


def knob_table_markdown() -> str:
    """Render the registry as the README "Configuration reference"
    markdown table (regenerate with ``nbd-lint --knob-table``)."""
    lines = ["| Knob | Default | Type | What it does |",
             "|------|---------|------|--------------|"]
    for k in _ALL:
        default = "–" if k.default is None else f"`{k.default}`"
        lines.append(f"| `{k.name}` | {default} | {k.kind} | {k.doc} |")
    return "\n".join(lines)
