"""The gateway daemon: one pooled worker fleet, N tenant kernels.

``GatewayDaemon`` is a headless coordinator.  It owns the workers the
way ``%dist_init`` does — a :class:`CommunicationManager` (wired with
the pool's bounded :class:`~.scheduler.Scheduler` policy) plus a
:class:`ProcessManager` — and opens a SECOND listener, the *tenant
plane*, speaking the same authenticated codec the workers do.
Notebook kernels dial it as tenants (:class:`~.client.TenantClient`,
``%dist_attach --tenant``); their cells are admitted by the
:class:`~.tenancy.TenantRegistry`, scheduled by the shared
``Scheduler``, executed tenant-tagged on the mesh, and their replies
routed back — or, when the tenant kernel has crashed, parked in that
tenant's own mailbox partition for exactly-once redelivery on
reattach.

Robustness contract (what the chaos tests pin):

- a tenant connection death detaches the tenant but destroys nothing:
  queued and in-flight cells finish, results park, the tenant name +
  token + epoch survive for ``%dist_attach --tenant``;
- a reattach bumps the tenant epoch, so the dead kernel's old
  connection (were it to twitch again) is fenced with ``stale_epoch``
  — the PR 4 stale-coordinator fence, scoped to one tenant;
- admission control is explicit: a full pool refuses the hello, a
  tenant at its in-flight cap gets ``{"status": "rejected"}``, a busy
  mesh replies ``{"status": "queued", "position": n}`` instead of
  silently blocking, and overload sheds the lowest-priority queued
  cell with a visible ``{"status": "shed"}`` verdict — the mesh never
  wedges behind one tenant's flood.

The daemon also writes a **gateway manifest** (``gateway.json`` under
the run dir, next to the workers' ``session.json``): the tenant-plane
endpoint + pool token a kernel needs to attach, the per-tenant
token/epoch table a *crashed* kernel's successor reads to reattach by
name, and the daemon pid that ``gc_runs`` probes so a live pool's run
dir is never swept.

Run it as ``python -m nbdistributed_tpu.gateway.daemon -n 4`` or via
``tools/nbd_gateway.py`` / ``%dist_pool start``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

from ..observability import flightrec
from ..observability import metrics as obs_metrics
from ..resilience import session as session_mod
from ..utils import knobs
from .membership import PoolMembership
from .scheduler import CellRejected, CellShed, SchedPolicy, Scheduler
from .tenancy import TenantRegistry, TenantRejected

GATEWAY_MANIFEST_NAME = "gateway.json"

# Documented exemptions for the blocking-call-under-lock self-lint
# (analysis/concur.py).  The manifest lock EXISTS to serialize the
# manifest's file IO between the writer thread and close(): it guards
# nothing else, is never nested under the hot ``_lock``, and moving
# the IO outside it would reopen the torn-.tmp race it closes.
_LINT_BLOCKING_OK = {
    "GatewayDaemon._write_manifest_sync:open-write":
        "the manifest lock serializes exactly this write against "
        "close()'s removal; it is a cold-path IO lock, never taken "
        "on the park/claim/serve plane",
    "GatewayDaemon._write_manifest_sync:json.dump":
        "same manifest-IO serialization as open-write above",
    "GatewayDaemon._write_manifest_sync:os.replace":
        "the atomic-publish os.replace must happen inside the same "
        "critical section as the .tmp write, or two publishers can "
        "replace each other's torn file",
    # The resize lock EXISTS to serialize whole drain-barrier resizes
    # (minutes of teardown + respawn): overlapping resizes would race
    # two fleets onto one control port.  It is a cold-path admin lock,
    # never taken on the park/claim/serve plane, and never nested
    # under the hot _lock.
    "GatewayDaemon.resize:wait":
        "the drain barrier's bounded wait is the resize's phase 1; "
        "the resize lock must span it or a second resize could flip "
        "the fleet mid-drain",
    "GatewayDaemon.resize:join":
        "fleet teardown (pm.quiesce) is phase 2 of the serialized "
        "resize — same cold-path admin lock",
    "GatewayDaemon.resize:post":
        "the graceful shutdown broadcast to the draining fleet is "
        "part of the serialized flip",
    "GatewayDaemon.resize:time.sleep":
        "the settle sleeps (shutdown drain, stale-EOF drain) are "
        "part of the serialized flip",
    "GatewayDaemon.resize:request":
        "pm.shutdown's host-agent requests are part of the "
        "serialized flip",
    "GatewayDaemon.resize:send_to_ranks":
        "template replay warms the NEW fleet before the scheduler "
        "resumes — running it outside the resize lock would let a "
        "second resize tear the fleet down mid-warm",
}

# The world-reset abort path fails stale pendings (firing their
# on_done callbacks) while the resize lock is held: those callbacks
# are the latency observatory's stage stamps and the serve threads'
# wakeups — none re-enter the daemon's resize path.
_LINT_CALLBACK_OK = {
    "GatewayDaemon.resize:cb":
        "reset_world's pending-abort callbacks (latency stamps, "
        "ticket wakeups) never re-enter the resize plane; deferring "
        "them would leave serve threads parked until after the flip "
        "— exactly the hang the abort exists to prevent",
}

# Tenant-plane request types a connection may send BEFORE its
# tenant_hello: status probes and the admin plane need no tenant slot
# (the transport-level pool token already authenticated the peer; the
# mutating ones re-prove the pool token in their payload, like
# pool_shutdown always has).  pool_resize/pool_template are the
# elastic-pool controls; tenant_export/import/release are the router's
# migration plane (ISSUE 16).
_PRE_HELLO = frozenset({"tenant_hello", "pool_status", "pool_shutdown",
                        "pool_resize", "pool_template",
                        "tenant_export", "tenant_import",
                        "tenant_release"})

# Serving-plane request types (ISSUE 11), served off-listener like
# execute/mailbox: submit journals to disk, start dispatches a model
# spec, and none of that may stall other tenants' frames.
_SERVE_TYPES = frozenset({"serve_start", "serve_stop", "serve_status",
                          "serve_submit", "serve_result",
                          "serve_stream"})


def gateway_manifest_path(run_dir: str) -> str:
    return os.path.join(run_dir, GATEWAY_MANIFEST_NAME)


def read_gateway_manifest(run_dir: str) -> dict | None:
    """The run dir's gateway manifest, or None (missing/torn — same
    lenient contract as :func:`~..resilience.session.read_manifest`)."""
    try:
        with open(gateway_manifest_path(run_dir)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    return m if isinstance(m, dict) else None


def gateway_alive(manifest: dict | None) -> bool:
    """True when the manifest's daemon pid is a live process — the
    ``gc_runs`` liveness probe that keeps a pooled fleet's run dir."""
    if not manifest:
        return False
    try:
        pid = int(manifest.get("pid") or 0)
    except (TypeError, ValueError):
        return False
    return bool(pid) and session_mod.pid_alive(pid)


def discover_gateway(run_dir: str | None = None) -> str | None:
    """Best pool to attach to when the caller names none: the env run
    dir if it holds a live gateway manifest, else the newest live one
    under the runs root — the ``discover_run_dir`` analog."""
    if run_dir:
        return run_dir if read_gateway_manifest(run_dir) else None
    env = knobs.get_str("NBD_RUN_DIR")
    if env and gateway_alive(read_gateway_manifest(env)):
        return env
    root = session_mod.default_runs_root()
    best: tuple[float, str] | None = None
    try:
        names = os.listdir(root)
    except OSError:
        return None
    for name in names:
        d = os.path.join(root, name)
        m = read_gateway_manifest(d)
        if not gateway_alive(m):
            continue
        ts = m.get("updated_ts") or m.get("created_ts") or 0.0
        if best is None or ts > best[0]:
            best = (ts, d)
    return best[1] if best else None


class GatewayDaemon:
    """Owns the pooled fleet and serves the tenant plane.

    Constructing it spawns (and waits for) the workers; ``close()``
    tears everything down and removes the manifests.  All tenant-plane
    callbacks run on the listener's IO thread and must not block —
    ``execute`` is served on its own thread per request (bounded by
    the scheduler's admission control, which is the point).
    """

    def __init__(self, world_size: int, *, backend: str = "auto",
                 host: str = "127.0.0.1", tenant_port: int = 0,
                 policy: SchedPolicy | None = None,
                 max_tenants: int | None = None,
                 request_timeout: float | None = None,
                 attach_timeout: float = 180.0,
                 pool_token: str | None = None,
                 watchdog: bool = True,
                 metrics_port: int | None = None):
        from ..manager import ProcessManager, wait_until_ready
        from ..messaging import CommunicationManager

        self.policy = policy or SchedPolicy.pool_from_env()
        if max_tenants is None:
            max_tenants = knobs.get_int("NBD_POOL_MAX_TENANTS", 8)
        self.registry = TenantRegistry(max_tenants=max_tenants)
        # The pool token authenticates the tenant plane (transport
        # preamble digest) and authorizes pool_shutdown.  Kernels read
        # it from the gateway manifest — same-filesystem trust, like
        # the session manifest's auth_token.
        self.pool_token = pool_token or session_mod.mint_token()
        self.request_timeout = request_timeout
        self._lock = threading.Lock()   # mailbox park/claim + serving
        # Manifest publishing gets its OWN lock: it serializes two
        # writers sharing one .tmp path, and file IO under the hot
        # _lock would stall every park/claim/serve-count behind disk.
        self._manifest_lock = threading.Lock()
        self._manifest_dirty = threading.Event()
        self._closed = threading.Event()    # set AFTER teardown done
        # Per-tenant count of execute serve threads between spawn and
        # their post-_deliver exit.  Eviction consults it: the
        # scheduler marks a cell complete BEFORE _deliver parks its
        # reply, so "scheduler idle + mailbox empty" alone can evict
        # a tenant whose result is mid-park and lose it.
        self._serving: dict[str, int] = {}
        # The serving plane (ISSUE 11): one ServingManager per daemon,
        # created by serve_start.  Plain rebinds under _lock.
        self._serve_mgr = None
        self._close_lock = threading.Lock()
        self._close_started = False
        # One process, one black box: the CommunicationManager created
        # below re-inits the process-global recorder as "coordinator"
        # (the name postmortem bundles recover), CLOSING any recorder
        # opened before it.  A separate init("gateway") here used to be
        # silently dead after that — every daemon record dropped — so
        # the daemon binds to the comm's live recorder instead (below).
        self.flight = flightrec.init("gateway")
        self.run_dir = flightrec.run_dir()

        # Elastic pools (ISSUE 16): membership — who owns which ranks,
        # generation-stamped — is split from scheduling so both can
        # change at runtime.  A resize is an attach-like epoch bump:
        # session_epoch advances, the old epoch's frames fence on the
        # existing ``ep`` header, and membership records which rank
        # set belonged to which epoch for late-frame forensics.
        self.membership = PoolMembership(world_size, epoch=1,
                                         now=time.time())
        self.session_epoch = 1
        self._resize_lock = threading.Lock()   # one resize at a time
        self._backend = backend
        self._attach_timeout = attach_timeout
        # Warm starts: a persistent per-pool XLA compilation cache,
        # shipped to every worker (including resized-in ones), so the
        # first cell after a grow — or a migrated tenant's first cell —
        # doesn't pay the cold compile.  Default lives under the run
        # dir; NBD_COMPILE_CACHE_DIR overrides; "0"/"off" disables.
        cache = knobs.get_str("NBD_COMPILE_CACHE_DIR")
        if cache is None:
            cache = os.path.join(self.run_dir, "xla-cache")
        if cache.strip().lower() in ("", "0", "off", "none"):
            cache = ""
        self.compile_cache_dir = cache
        # Template namespaces: admin-registered cells re-run on every
        # epoch's fresh fleet so resized-in workers start warm.
        self._templates: dict[str, str] = {}
        self._autoscaler = None
        self._autoscale_stop = threading.Event()
        self._autoscale_thread = None

        session_token = session_mod.mint_token()
        self._session_token = session_token
        self.comm = CommunicationManager(
            num_workers=world_size, timeout=request_timeout,
            session_token=session_token, session_epoch=1,
            scheduler=Scheduler(self.policy))
        # See the note above: the comm's "coordinator" ring is the
        # live one now; record into it so resize/autoscale/tenant
        # events actually persist and reach postmortem bundles.
        self.flight = self.comm.flight
        self.pm = ProcessManager()
        self.pm.add_death_callback(
            lambda r, rc: self.comm.mark_worker_dead(r))
        try:
            self.pm.start_workers(
                world_size, self.comm.port, backend=backend,
                extra_env=self._worker_env(1))
            wait_until_ready(self.comm, self.pm, attach_timeout)
            self.comm.set_output_callback(self._on_stream)
            self.world_size = world_size

            # Workers' session manifest: the fleet outlives this
            # daemon exactly like a single-kernel fleet outlives its
            # kernel — a future coordinator (or replacement gateway)
            # can adopt it.
            try:
                session_mod.write_manifest(
                    self.run_dir, session_mod.make_manifest(
                        world_size=world_size,
                        control_host="127.0.0.1",
                        control_port=self.comm.port,
                        token=session_token, epoch=1,
                        pids={r: p.pid
                              for r, p in self.pm.processes.items()},
                        backend=self.pm.backend,
                        dist_port=self.pm.dist_port))
            except OSError:
                pass

            # Tenant plane: same listener class + codec as the worker
            # plane, authenticated with the pool token.  Inside the
            # same guard as the spawn: a bad --tenant-port must not
            # orphan the already-running fleet.
            from ..messaging.native import make_listener
            self._tenants_listener = make_listener(
                host=host, port=tenant_port,
                auth_token=self.pool_token)
            self._tenants_listener.on_message = self._on_tenant_message
            self._tenants_listener.on_disconnect = self._on_tenant_gone
            self._tenants_listener.start()
        except BaseException:
            # BaseException: a SIGTERM handler raising SystemExit
            # mid-spawn (the %dist_pool start timeout path) must
            # still reap the half-started fleet, same as any error.
            self.pm.shutdown()
            self.comm.shutdown()
            raise
        self.tenant_host = host
        self.tenant_port = self._tenants_listener.port

        # Live scrape endpoint (ISSUE 13): /metrics, /healthz,
        # /latency.json — token-gated with the pool token, like the
        # admin plane.  Off unless --metrics-port / NBD_METRICS_PORT
        # asks for it; a NEGATIVE port means "bind an ephemeral port"
        # (read it back from the manifest) — callers wanting an
        # OS-assigned port must not pre-claim one and re-bind it, the
        # classic TOCTOU a busy CI box loses.  A requested-but-
        # unbindable port fails the start loudly (a deployment that
        # asked to be scraped must not come up silently unscrapeable),
        # reaping the fleet like any other construction failure.
        self._metrics_httpd = None
        mp = (metrics_port if metrics_port is not None
              else knobs.get_int("NBD_METRICS_PORT", 0))
        if mp:
            from ..observability import httpd as obs_httpd
            try:
                self._metrics_httpd = obs_httpd.start_for_comm(
                    self.comm, port=max(0, mp), host=host,
                    token=self.pool_token,
                    extra_health=self._health_extra,
                    extra_latency=self._latency_extra)
            except BaseException:
                self._tenants_listener.close()
                self.pm.shutdown()
                self.comm.shutdown()
                raise

        # Hang watchdog over the pool: verdicts carry the tenant of
        # the hung cell (pending snapshots are tenant-tagged), so
        # blame lands on the right notebook.
        self._watchdog = None
        if watchdog and knobs.get_bool("NBD_HANG", True):
            try:
                from ..resilience.watchdog import (HangPolicy,
                                                   HangWatchdog)
                self._watchdog = HangWatchdog(
                    HangPolicy.from_env_lenient())
                self._watchdog.attach(self.comm, self.pm)
            except Exception:
                self._watchdog = None

        self.flight.record("gateway_start", world_size=world_size,
                           tenant_port=self.tenant_port,
                           policy=self.policy.describe())
        # First publish is synchronous — READY implies a readable
        # manifest; later republishes go through the writer thread.
        self._write_manifest_sync()
        threading.Thread(target=self._manifest_writer, daemon=True,
                         name="nbd-gw-manifest").start()

    # ------------------------------------------------------------------
    # manifest

    def _write_manifest(self) -> None:
        """Request a manifest publish.  The write itself happens on a
        dedicated writer thread — hello/detach call this from the
        tenant-plane listener IO thread, and json.dump + os.replace
        there stalled every other tenant's frames behind disk on a
        slow runs root."""
        self._manifest_dirty.set()

    def _manifest_writer(self) -> None:
        while True:
            self._manifest_dirty.wait()
            if self._close_started:
                return      # close() removes the manifest; stop here
            self._manifest_dirty.clear()
            self._write_manifest_sync()

    def _write_manifest_sync(self) -> None:
        m = {
            "kind": "gateway",
            "pid": os.getpid(),
            "world_size": self.world_size,
            # Elastic pools: the epoch fences stale frames after a
            # resize, the generation stamps the membership view, and
            # gc_runs keeps a recently-bumped manifest even when the
            # pid probe races a restart (the mid-resize keep-rule).
            "epoch": self.session_epoch,
            "generation": self.membership.generation,
            "membership": self.membership.describe(),
            "tenant_plane": {"host": self.tenant_host,
                             "port": self.tenant_port},
            "pool_token": self.pool_token,
            "policy": self.policy.describe(),
            "max_tenants": self.registry.max_tenants,
            "created_ts": getattr(self, "_created_ts", None)
            or time.time(),
            "updated_ts": time.time(),
            "tenants": self.registry.manifest_block(),
        }
        if self._metrics_httpd is not None:
            # Where to scrape this pool (token = the pool token the
            # manifest already carries).
            m["metrics"] = {"host": self.tenant_host,
                            "port": self._metrics_httpd.port}
        self._created_ts = m["created_ts"]
        path = gateway_manifest_path(self.run_dir)
        tmp = path + ".tmp"
        # Serialized: hello (listener thread) and eviction (its own
        # thread) both publish — two unserialized writers share the
        # one .tmp path and can os.replace torn JSON into place.
        with self._manifest_lock:
            if self._close_started:
                return      # don't resurrect a manifest close removes
            try:
                with open(tmp, "w") as f:
                    json.dump(m, f, indent=1)
                os.replace(tmp, path)
            except OSError:
                pass

    # ------------------------------------------------------------------
    # elastic pools (ISSUE 16): resize, templates, autoscale

    def _worker_env(self, epoch: int) -> dict:
        env = {"NBD_SESSION_TOKEN": self._session_token,
               "NBD_SESSION_EPOCH": str(epoch)}
        if self.compile_cache_dir:
            env["NBD_COMPILE_CACHE_DIR"] = self.compile_cache_dir
        return env

    def resize(self, target: int, *, reason: str = "manual") -> dict:
        """Change the pool's world size: a two-phase drain barrier
        followed by an attach-like epoch bump with a re-seeded fleet.

        Phase 1 (drain): the scheduler stops promoting (queued cells
        HOLD — they are not lost, their serve threads stay parked on
        their tickets), the serving driver parks between ticks, and
        we wait — bounded by ``NBD_RESIZE_DRAIN_TIMEOUT_S`` — for
        in-flight cells to finish.  Phase 2 (flip): the old fleet is
        torn down, the coordinator's world is reset under
        ``epoch+1``, and a fresh fleet spawns against the SAME
        control port with the persistent compile cache, so its first
        cells start warm.  Anything still in flight past the drain
        timeout is aborted with an explicit WorkerDied verdict (the
        tenant sees an error reply, never a hang), and any frame the
        old fleet emits afterwards is fenced by the ``ep`` header —
        the same stale-epoch fence a durable-session reattach uses.

        Stated limit: tenant worker namespaces do not survive the
        flip (the processes die).  Tenant identity, mailboxes, queued
        cells, and the serve journal all do; namespaces are lazily
        re-seeded by the next cell, which the warm compile cache and
        template replay make cheap instead of a cold compile."""
        from ..manager import wait_until_ready
        target = int(target)
        if target < 1:
            return {"status": "error",
                    "error": f"cannot resize to {target} workers"}
        reg = obs_metrics.registry()
        with self._resize_lock:
            if self._close_started:
                return {"status": "error",
                        "error": "gateway is shutting down"}
            if target == self.world_size:
                return {"status": "noop",
                        "world_size": self.world_size,
                        "epoch": self.session_epoch}
            new_epoch = self.session_epoch + 1
            t0 = time.monotonic()
            plan = self.membership.begin_resize(
                target, new_epoch, reason=reason, now=time.time())
            self.flight.record("resize_begin", **plan)
            self._write_manifest()   # publish the DRAINING view early
            # Phase 1: drain barrier.
            self.comm.scheduler.pause(f"resize:{reason}")
            mgr = self._serve_mgr
            if mgr is not None:
                mgr.pause(timeout=30.0)
            deadline = time.monotonic() + knobs.get_float(
                "NBD_RESIZE_DRAIN_TIMEOUT_S", 120.0)
            drained = False
            while time.monotonic() < deadline:
                if self.comm.scheduler.active_count() == 0:
                    drained = True
                    break
                if self._closed.wait(0.25):
                    break
            drain_s = time.monotonic() - t0
            self.flight.record("resize_drained", drained=drained,
                               drain_s=round(drain_s, 3))
            # Phase 2: flip the fleet under the new epoch.
            wd, self._watchdog = self._watchdog, None
            if wd is not None:
                try:
                    # A draining fleet must never be blamed as hung.
                    wd.stop()
                except Exception:
                    pass
            try:
                self.pm.quiesce()
                try:
                    self.comm.post(self.comm.connected_ranks(),
                                   "shutdown")
                    time.sleep(0.3)
                except Exception:
                    pass
                self.pm.shutdown()
                # Let the old sockets' disconnect events finish
                # draining before the world resets, so a stale EOF
                # can't mark a NEW rank dead.
                time.sleep(0.5)
                self.comm.reset_world(target, new_epoch)
                self.pm.start_workers(
                    target, self.comm.port, backend=self._backend,
                    extra_env=self._worker_env(new_epoch))
                wait_until_ready(self.comm, self.pm,
                                 self._attach_timeout)
            except Exception as e:
                # The old fleet is gone and the new one failed: this
                # pool is down, not half-up.  Leave membership in its
                # draining state (status shows the stuck transition),
                # resume the scheduler so queued work fails loudly
                # instead of waiting forever, and report.
                reg.counter("nbd_pool_resizes_total",
                            "pool resizes by outcome",
                            {"outcome": "failed"}).inc()
                self.flight.record("resize_failed", target=target,
                                   error=f"{type(e).__name__}: {e}")
                self.comm.scheduler.resume()
                return {"status": "error",
                        "error": f"resize to {target} failed mid-"
                                 f"flip: {type(e).__name__}: {e} — "
                                 f"the pool needs a restart"}
            self.session_epoch = new_epoch
            self.world_size = target
            gen = self.membership.complete_resize(target, new_epoch,
                                                  now=time.time())
            # Republish both manifests BEFORE resuming: a gc or a
            # reattach racing the flip must see the new epoch.
            try:
                session_mod.write_manifest(
                    self.run_dir, session_mod.make_manifest(
                        world_size=target, control_host="127.0.0.1",
                        control_port=self.comm.port,
                        token=self._session_token, epoch=new_epoch,
                        pids={r: p.pid
                              for r, p in self.pm.processes.items()},
                        backend=self.pm.backend,
                        dist_port=self.pm.dist_port))
            except OSError:
                pass
            self._write_manifest()
            if wd is not None and knobs.get_bool("NBD_HANG", True):
                try:
                    from ..resilience.watchdog import (HangPolicy,
                                                       HangWatchdog)
                    self._watchdog = HangWatchdog(
                        HangPolicy.from_env_lenient())
                    self._watchdog.attach(self.comm, self.pm)
                except Exception:
                    self._watchdog = None
            # Resume the scheduler BEFORE template replay and the
            # serving re-seed: both run ordinary ``execute`` cells,
            # which admission would otherwise queue against the still-
            # paused scheduler — a self-inflicted drain barrier that
            # stalls the resize for the cells' full timeout.  The
            # serving driver itself stays parked (its own pause flag)
            # until resume_after_resize below, so no decode tick can
            # race the re-seed.
            promoted = self.comm.scheduler.resume()
            self._replay_templates()
            if mgr is not None:
                mgr.resume_after_resize(target)
            wall_s = time.monotonic() - t0
            a = self._autoscaler
            if a is not None:
                a.note_resized(time.time())
            reg.counter("nbd_pool_resizes_total",
                        "pool resizes by outcome",
                        {"outcome": "grown" if target
                         > plan["from_world"] else "shrunk"}).inc()
            self.flight.record(
                "resize_done", world_size=target, epoch=new_epoch,
                generation=gen, drained=drained,
                drain_s=round(drain_s, 3), wall_s=round(wall_s, 3),
                promoted=promoted, reason=reason)
            return {"status": "resized", "world_size": target,
                    "epoch": new_epoch, "generation": gen,
                    "drained": drained, "drain_s": round(drain_s, 3),
                    "wall_s": round(wall_s, 3)}

    def _replay_templates(self) -> None:
        """Re-run every registered template cell on the fresh fleet so
        resized-in workers' first real cell finds a warm namespace (and
        the compile cache primed).  Failures are recorded, not raised —
        a broken template must not fail the resize."""
        with self._lock:
            templates = dict(self._templates)
        for name, code in templates.items():
            try:
                ranks = list(range(self.world_size))
                self.comm.send_to_ranks(
                    ranks, "execute",
                    {"code": code, "target_ranks": ranks},
                    tenant=f"_tpl_{name}", timeout=600.0)
                self.flight.record("template_replayed", template=name)
            except Exception as e:
                self.flight.record("template_replay_failed",
                                   template=name,
                                   error=f"{type(e).__name__}: {e}")

    def run_template(self, name: str, code: str) -> dict:
        """Register + run a template cell on all live ranks now."""
        with self._lock:
            self._templates[name] = code
        try:
            live = sorted(set(range(self.world_size))
                          - self.comm.dead_ranks())
            resps = self.comm.send_to_ranks(
                live, "execute", {"code": code, "target_ranks": live},
                tenant=f"_tpl_{name}", timeout=600.0)
            errs = {str(r): (m.data or {}).get("error")
                    for r, m in resps.items()
                    if (m.data or {}).get("error")}
            self.flight.record("template_stored", template=name,
                               errors=len(errs))
            if errs:
                return {"status": "error", "template": name,
                        "errors": errs}
            return {"status": "ok", "template": name, "ranks": live}
        except Exception as e:
            return {"status": "error", "template": name,
                    "error": f"{type(e).__name__}: {e}"}

    def start_autoscale(self, policy=None) -> None:
        """Arm the pressure-driven autoscaler (``--autoscale min:max``
        / ``%dist_pool start --autoscale``)."""
        from ..resilience.autoscaler import (AutoscalePolicy,
                                             PoolAutoscaler)
        self._autoscaler = PoolAutoscaler(policy
                                          or AutoscalePolicy.from_env())
        self.flight.record("autoscale_armed",
                           policy=self._autoscaler.policy.describe())
        self._autoscale_thread = threading.Thread(
            target=self._autoscale_loop, name="nbd-gw-autoscale",
            daemon=True)
        self._autoscale_thread.start()

    def _autoscale_loop(self) -> None:
        a = self._autoscaler
        while not self._autoscale_stop.wait(a.policy.interval_s):
            if self._close_started:
                return
            try:
                sched = self.comm.scheduler.snapshot()
                backlog = 0
                mgr = self._serve_mgr
                if mgr is not None:
                    d = mgr.describe()
                    backlog = (int(d.get("pending") or 0)
                               + int(d.get("decoding") or 0))
                summ = self.comm.lat.summary()
                p95_ms = ((summ.get("stages") or {}).get("queue")
                          or {}).get("p95", 0)
                decision = a.observe(
                    time.time(), world_size=self.world_size,
                    queued=int(sched.get("queued") or 0),
                    active=int(sched.get("active") or 0),
                    backlog=backlog,
                    queue_p95_s=float(p95_ms) / 1000.0)
                if decision is None:
                    continue
                # Full audit record on the flight ring (ISSUE 18):
                # the pressure inputs and sustain/cooldown state that
                # drove the verdict, not just the verdict — this is
                # what postmortem bundles carry.
                self.flight.record("autoscale_decision",
                                   action=decision.action,
                                   target=decision.target,
                                   reason=decision.reason,
                                   **({"audit": decision.record}
                                      if decision.record else {}))
                obs_metrics.registry().counter(
                    "nbd_autoscale_decisions_total",
                    "autoscaler grow/shrink decisions",
                    {"action": decision.action}).inc()
                self.resize(decision.target,
                            reason=f"autoscale: {decision.reason}")
                # resize() already ran note_resized on success; run it
                # here too so a FAILED resize still opens the cooldown
                # instead of retrying a wedged flip at poll frequency.
                a.note_resized(time.time())
            except Exception as e:
                self.flight.record("autoscale_error",
                                   error=f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------------
    # tenant plane (listener IO thread — keep fast, never block)

    def _send_to_client(self, client_id: int, msg) -> bool:
        from ..messaging.transport import TransportError
        try:
            self._tenants_listener.send_to_rank(client_id, msg)
            return True
        except TransportError:
            return False

    def _on_tenant_gone(self, client_id: int) -> None:
        t = self.registry.detach_client(client_id)
        if t is not None:
            self.flight.record("tenant_detached", tenant=t.name)
            obs_metrics.registry().counter(
                "nbd_tenant_detaches_total",
                "tenant detaches by kind (clean = explicit goodbye, "
                "lost = connection dropped: kernel crash or exit)",
                {"tenant": t.name, "kind": "lost"}).inc()
            self._write_manifest()

    def _on_tenant_message(self, client_id: int, msg) -> None:
        mt = msg.msg_type
        tenant = self.registry.by_client(client_id)
        if tenant is None and mt not in _PRE_HELLO:
            self._send_to_client(client_id, msg.reply(
                data={"error": "no tenant_hello on this connection"}))
            return
        if tenant is not None and self.registry.fence(tenant,
                                                      msg.epoch):
            # A reattach bumped this tenant's epoch: the old kernel's
            # connection is fenced exactly like a stale coordinator.
            obs_metrics.registry().counter(
                "nbd_tenant_epoch_rejected_total",
                "frames rejected from a stale tenant epoch",
                {"tenant": tenant.name}).inc()
            self.flight.record("tenant_epoch_rejected",
                               tenant=tenant.name, frame_epoch=msg.epoch,
                               epoch=tenant.epoch)
            self._send_to_client(client_id, msg.reply(
                data={"error": f"stale tenant epoch {msg.epoch} "
                               f"(this tenant reattached at epoch "
                               f"{tenant.epoch}); request ignored",
                      "stale_epoch": True}))
            return
        if mt == "tenant_hello":
            self._handle_hello(client_id, msg)
        elif mt == "execute":
            # Counted HERE (listener thread, before detach can be
            # processed on this connection) — not in the serve thread,
            # which may not have started when a detach lands.
            with self._lock:
                self._serving[tenant.name] = self._serving.get(
                    tenant.name, 0) + 1
            threading.Thread(target=self._serve_execute,
                             args=(tenant, msg, client_id),
                             name=f"nbd-gw-{tenant.name}",
                             daemon=True).start()
        elif mt in _SERVE_TYPES:
            # Off the listener thread (submit journals to disk, start
            # runs a model-spec cell); counted like execute so a
            # detach cannot evict the tenant mid-request.
            with self._lock:
                self._serving[tenant.name] = self._serving.get(
                    tenant.name, 0) + 1
            threading.Thread(target=self._serve_plane,
                             args=(tenant, msg, client_id),
                             name=f"nbd-gw-srv-{tenant.name}",
                             daemon=True).start()
        elif mt == "mailbox":
            # Off the listener thread: a drain reply carries up to the
            # whole mailbox (32 MB in-memory bound; oversized parked
            # results live in the tenant's run-dir spill partition and
            # are materialized per claim — ISSUE 20) and a slow
            # client's full socket buffer would block sendall —
            # wedging every other tenant's hellos/executes/detaches
            # behind it.  Counted
            # here (listener thread) like execute so a detach can't
            # evict the tenant while its claimed results are mid-send.
            with self._lock:
                self._serving[tenant.name] = self._serving.get(
                    tenant.name, 0) + 1
            threading.Thread(target=self._serve_mailbox,
                             args=(tenant, msg, client_id),
                             name=f"nbd-gw-mb-{tenant.name}",
                             daemon=True).start()
        elif mt == "pool_status":
            self._send_to_client(client_id, msg.reply(
                data=self.status()))
        elif mt == "detach":
            t = self.registry.detach_client(client_id)
            evicted = False
            if t is not None:
                # A clean goodbye with nothing parked and nothing in
                # flight frees the tenant's admission slot; anything
                # recoverable keeps the slot for reattach.
                with self._lock:
                    serving = self._serving.get(t.name, 0)
                if (serving == 0 and len(t.mailbox) == 0
                        and self.comm.scheduler.tenant_idle(t.name)):
                    # Eviction runs on its own thread AFTER the
                    # worker-namespace GC broadcast: until the evict
                    # lands, a new same-name hello is refused (wrong
                    # token against the still-registered tenant), so
                    # the late tenant_gc frame can never delete a NEW
                    # tenant's freshly created namespace.  Off the
                    # listener thread: send_to_ranks blocks.
                    evicted = True
                    threading.Thread(
                        target=self._evict_after_gc,
                        args=(t.name,), daemon=True,
                        name=f"nbd-gw-gc-{t.name}").start()
                self.flight.record("tenant_detached", tenant=t.name,
                                   clean=True, evicted=evicted)
                obs_metrics.registry().counter(
                    "nbd_tenant_detaches_total",
                    "tenant detaches by kind (clean = explicit "
                    "goodbye, lost = connection dropped: kernel "
                    "crash or exit)",
                    {"tenant": t.name, "kind": "clean"}).inc()
                self._write_manifest()
            self._send_to_client(client_id, msg.reply(
                data={"status": "detached", "evicted": evicted}))
        elif mt == "pool_shutdown":
            if (msg.data or {}).get("token") != self.pool_token:
                self._send_to_client(client_id, msg.reply(
                    data={"error": "pool token mismatch"}))
                return
            self._send_to_client(client_id, msg.reply(
                data={"status": "stopping"}))
            # Off-thread: close() joins the listener's IO thread —
            # the very thread running this callback.
            threading.Thread(target=self.close,
                             name="nbd-gw-stop", daemon=True).start()
        elif mt == "pool_resize":
            data = msg.data or {}
            if data.get("token") != self.pool_token:
                self._send_to_client(client_id, msg.reply(
                    data={"error": "pool token mismatch"}))
                return
            try:
                target = int(data.get("workers"))
            except (TypeError, ValueError):
                self._send_to_client(client_id, msg.reply(
                    data={"error": "pool_resize needs workers: int"}))
                return
            reason = str(data.get("reason") or "manual")

            def _do_resize():
                try:
                    out = self.resize(target, reason=reason)
                except Exception as e:
                    out = {"status": "error",
                           "error": f"{type(e).__name__}: {e}"}
                self._send_to_client(client_id, msg.reply(data=out))

            # Off the listener thread: a resize blocks for the whole
            # drain + respawn (minutes) and the listener must keep
            # serving other tenants' frames meanwhile.
            threading.Thread(target=_do_resize, name="nbd-gw-resize",
                             daemon=True).start()
        elif mt == "pool_template":
            data = msg.data or {}
            if data.get("token") != self.pool_token:
                self._send_to_client(client_id, msg.reply(
                    data={"error": "pool token mismatch"}))
                return
            code = data.get("code")
            if not isinstance(code, str) or not code.strip():
                with self._lock:
                    names = sorted(self._templates)
                self._send_to_client(client_id, msg.reply(
                    data={"status": "ok", "templates": names}))
                return
            tpl = str(data.get("name") or "default")

            def _do_template():
                self._send_to_client(client_id, msg.reply(
                    data=self.run_template(tpl, code)))

            threading.Thread(target=_do_template,
                             name="nbd-gw-template",
                             daemon=True).start()
        elif mt == "tenant_export":
            data = msg.data or {}
            if data.get("token") != self.pool_token:
                self._send_to_client(client_id, msg.reply(
                    data={"error": "pool token mismatch"}))
                return
            name = str(data.get("tenant") or "")
            snap = self.registry.export_tenant(name)
            if snap is None:
                self._send_to_client(client_id, msg.reply(
                    data={"error": f"no tenant {name!r} in this "
                                   "pool"}))
                return
            # The tenant's serving history rides along: its lines are
            # filtered out of every serving journal under the run dir
            # (a serving plane's journal interleaves all submitters),
            # and the destination's serving plane re-admits the
            # unfinished ones.
            from .serving import export_tenant_journal
            journal = export_tenant_journal(self.run_dir, name)
            if journal:
                snap["serve_journal"] = journal
            self.flight.record("tenant_exported", tenant=name,
                               parked=len(snap.get("parked") or {}))
            self._send_to_client(client_id, msg.reply(
                data={"status": "ok", "snapshot": snap}))
        elif mt == "tenant_import":
            data = msg.data or {}
            if data.get("token") != self.pool_token:
                self._send_to_client(client_id, msg.reply(
                    data={"error": "pool token mismatch"}))
                return
            snap = data.get("snapshot")
            if not isinstance(snap, dict):
                self._send_to_client(client_id, msg.reply(
                    data={"error": "tenant_import needs a snapshot"}))
                return
            t, why = self.registry.import_tenant(snap)
            if t is None:
                self._send_to_client(client_id, msg.reply(
                    data={"error": f"tenant_import refused: {why}"}))
                return
            from ..messaging.codec import Message
            with self._lock:
                for mid, d in sorted(
                        (snap.get("parked") or {}).items()):
                    # park() refreshes an existing msg_id in place, so
                    # a router retry re-importing the same snapshot
                    # converges instead of duplicating.
                    t.mailbox.park(mid, Message(
                        msg_type="response", msg_id=mid, data=d))
            journal = snap.get("serve_journal")
            if isinstance(journal, str) and journal:
                from .serving import migrated_journal_path
                jp = migrated_journal_path(self.run_dir, t.name)
                # Staged, not live: this pool's serving plane adopts
                # the stash (re-journal + re-admit) at its next
                # start.  Write-if-absent keeps the import idempotent:
                # a router retry must not clobber a stash the serving
                # plane may be mid-adoption on.
                if not os.path.exists(jp):
                    try:
                        with open(jp, "w") as f:
                            f.write(journal)
                    except OSError:
                        pass
            self.flight.record("tenant_imported", tenant=t.name,
                               epoch=t.epoch,
                               parked=len(snap.get("parked") or {}))
            obs_metrics.registry().counter(
                "nbd_tenant_migrations_total",
                "tenant migrations by direction",
                {"direction": "in"}).inc()
            self._write_manifest()
            self._send_to_client(client_id, msg.reply(
                data={"status": "imported", "tenant": t.name,
                      "epoch": t.epoch,
                      "parked": len(snap.get("parked") or {})}))
        elif mt == "tenant_release":
            data = msg.data or {}
            if data.get("token") != self.pool_token:
                self._send_to_client(client_id, msg.reply(
                    data={"error": "pool token mismatch"}))
                return
            name = str(data.get("tenant") or "")
            ok = self.registry.release(name,
                                       force=bool(data.get("force")))
            if ok:
                self.comm.scheduler.forget_tenant(name)
                obs_metrics.registry().remove_label_series("tenant",
                                                           name)
                obs_metrics.registry().counter(
                    "nbd_tenant_migrations_total",
                    "tenant migrations by direction",
                    {"direction": "out"}).inc()
                self.flight.record("tenant_released", tenant=name)
                self._write_manifest()
            self._send_to_client(client_id, msg.reply(
                data={"status": "released" if ok else "error",
                      **({} if ok else
                         {"error": f"tenant {name!r} not released "
                                   "(unknown, or attached without "
                                   "force)"})}))
        else:
            self._send_to_client(client_id, msg.reply(
                data={"error": f"unknown tenant-plane request "
                               f"{mt!r}"}))

    def _handle_hello(self, client_id: int, msg) -> None:
        data = msg.data or {}
        name = str(data.get("tenant") or "").strip()
        if not name:
            self._send_to_client(client_id, msg.reply(
                data={"error": "tenant_hello needs a tenant name"}))
            return
        prio = data.get("priority")
        if prio is not None:
            try:
                prio = int(prio)
            except (TypeError, ValueError):
                prio = None   # absent/garbage: keep current priority
        existing = self.registry.by_client(client_id)
        if existing is not None and existing.name != name:
            # One tenant identity per connection: a re-hello under a
            # DIFFERENT name would overwrite the client map while the
            # first tenant's client_id stayed pointing here — forever
            # "attached", unevictable, its slot and namespaces leaked.
            self._send_to_client(client_id, msg.reply(data={
                "error": f"connection already attached as tenant "
                         f"{existing.name!r} — open a new connection "
                         "to attach another tenant",
                "rejected": True}))
            return
        try:
            t, reply = self.registry.hello(
                name, data.get("token"), client_id, priority=prio)
        except TenantRejected as e:
            obs_metrics.registry().counter(
                "nbd_tenant_rejected_total",
                "tenant hellos refused (admission control / bad "
                "token)", {"reason": e.reason.split("=")[0][:32]}).inc()
            self.flight.record("tenant_rejected", tenant=name,
                               reason=e.reason)
            self._send_to_client(client_id, msg.reply(
                data={"error": str(e), "rejected": True}))
            return
        reply["world_size"] = self.world_size
        reply["policy"] = self.policy.describe()
        self.flight.record("tenant_" + reply["status"], tenant=name,
                           epoch=t.epoch)
        obs_metrics.registry().counter(
            "nbd_tenant_attaches_total",
            "tenant hellos accepted",
            {"tenant": name, "kind": reply["status"]}).inc()
        self._send_to_client(client_id, msg.reply(data=reply))
        self._write_manifest()

    def _handle_mailbox(self, client_id: int, tenant, msg) -> None:
        action = (msg.data or {}).get("action", "status")
        if action == "drain":
            with self._lock:
                claimed = tenant.mailbox.claim_all()
            try:
                ok = self._send_to_client(client_id, msg.reply(
                    data={"status": "ok",
                          "results": {mid: getattr(r, "data", None)
                                      for mid, r in claimed.items()}}))
            except BaseException:
                # The claim is destructive: a throwing serve thread
                # (reply construction, encode) must repark before
                # unwinding or the results are lost on BOTH sides —
                # the exactly-once contract survives only the
                # explicit ok/not-ok path below without this.
                with self._lock:
                    for mid, r in claimed.items():
                        tenant.mailbox.park(mid, r)
                self.flight.record("tenant_mailbox_reparked",
                                   tenant=tenant.name, n=len(claimed),
                                   reason="serve-thread-raise")
                raise
            if ok:
                self.flight.record("tenant_mailbox_drained",
                                   tenant=tenant.name, n=len(claimed))
            elif claimed:
                # The drain reply never left the gateway: put the
                # results back (oldest first, preserving order) so the
                # claim stays exactly-once instead of silently
                # becoming at-most-once on a dead socket.
                with self._lock:
                    for mid, r in claimed.items():
                        tenant.mailbox.park(mid, r)
                self.flight.record("tenant_mailbox_reparked",
                                   tenant=tenant.name, n=len(claimed))
                # A successor kernel may have attached in the
                # claim/repark window — its hello saw an EMPTY
                # mailbox, so nudge it (the dead drain requester is
                # excluded; no successor, no notice).
                self._notify_parked(tenant, exclude_cid=client_id)
            return
        with self._lock:
            parked = tenant.mailbox.ids()
            counters = tenant.mailbox.counters()
        self._send_to_client(client_id, msg.reply(
            data={"status": "ok", "parked": parked,
                  "counters": counters}))

    # ------------------------------------------------------------------
    # cell routing (one thread per in-flight tenant request)

    def _serve_done(self, name: str) -> None:
        """Release one serve-counter slot (incremented on the
        listener thread before the serve thread spawned)."""
        with self._lock:
            n = self._serving.get(name, 1) - 1
            if n <= 0:
                self._serving.pop(name, None)
            else:
                self._serving[name] = n

    def _serve_mailbox(self, tenant, msg, client_id: int) -> None:
        try:
            self._handle_mailbox(client_id, tenant, msg)
        finally:
            # Held until the claimed results are sent or REPARKED —
            # a clean detach racing the drain must not evict the
            # tenant while its mailbox claim is in flight.
            self._serve_done(tenant.name)

    def _serve_execute(self, tenant, msg, submit_cid: int) -> None:
        try:
            self._serve_execute_inner(tenant, msg, submit_cid)
        finally:
            # Decremented only after _deliver has sent or PARKED the
            # reply — until then the tenant must not be evictable.
            self._serve_done(tenant.name)

    def _classify_effects(self, code, tenant) -> str:
        """The cell's effects-admission class for the scheduler
        (``free`` / ``bearing`` / ``unknown``), counted in
        ``nbd_effects_{proven,unknown}_total`` and remembered in the
        preflight store.  Only called when ``policy.effects`` is on;
        anything the analyzer cannot read is ``unknown`` — the gate
        must never promote on a guess.

        Session soundness: a proof is only per-cell if the ambient
        names it leans on (``np``, ``time``, builtins…) still denote
        their modules.  A tenant cell that rebinds one poisons the
        assumption for that tenant's LATER cells
        (``tenant.ns_unsafe``, fed by ``ambient_poison``) — without
        this, ``np = weird; np.x(y)`` across two cells would be
        falsely proven free.  The read-classify-poison of
        ``tenant.ns_unsafe`` happens in ONE ``tenant.ns_lock`` section
        so that concurrent serve threads of the same tenant
        (mesh_slots > 1 with an async client) always classify against
        the latest recorded poison, never a stale snapshot — scoped
        per tenant so a big cell's analysis never stalls the
        daemon-wide ``self._lock`` plane."""
        reg = obs_metrics.registry()

        def count(cls):
            if cls == "unknown":
                reg.counter(
                    "nbd_effects_unknown_total",
                    "cells whose collective footprint the effect "
                    "analyzer could not prove (opaque or "
                    "tainted)").inc()
            else:
                reg.counter(
                    "nbd_effects_proven_total",
                    "cells with a proven collective footprint",
                    {"footprint": cls}).inc()
            return cls

        if not isinstance(code, str):
            return count("unknown")
        try:
            from ..analysis import effects as effects_mod
            from ..analysis import preflight
            with tenant.ns_lock:
                # Read-classify-poison atomically: a sibling serve
                # thread's just-recorded rebind must be visible to
                # this classification (the analyzer is pure CPU on a
                # small cell, so the hold is short).
                rep = effects_mod.infer_effects(
                    code, assume_unsafe=tenant.ns_unsafe)
                cls = effects_mod.collective_class(rep)
                poison = effects_mod.ambient_poison(rep)
                if poison:
                    tenant.ns_unsafe = tenant.ns_unsafe | poison
            from ..runtime.collective_guard import cell_hash
            preflight.note_effects(cell_hash(code), rep)
        except Exception:
            return count("unknown")
        return count(cls)

    def _serve_execute_inner(self, tenant, msg,
                             submit_cid: int) -> None:
        name = tenant.name
        mgr = self._serve_mgr
        if mgr is not None and name == mgr.tenant:
            # Serving-tenant mode: a cell queued behind the decode
            # loop would wait forever (the driver ticks continuously)
            # and could clobber the DecodeServer's params mid-decode.
            # Refuse with the serving front door named instead.
            obs_metrics.registry().counter(
                "nbd_tenant_cells_total",
                "tenant cells by terminal status",
                {"tenant": name, "status": "rejected"}).inc()
            self._deliver(tenant, msg.reply(data={
                "status": "rejected", "reason": "serving-tenant",
                "error": f"tenant {name!r} is the serving plane's "
                         "tenant — %%distributed cells cannot run "
                         "behind its decode loop; submit generation "
                         "requests with %dist_serve submit, or "
                         "attach under a different tenant name"}),
                submit_cid)
            return
        with self._lock:
            # Serve threads of the SAME tenant run concurrently when
            # mesh_slots > 1: the counter bumps are read-modify-writes.
            tenant.cells_submitted += 1
        tenant.last_seen = time.time()
        data = msg.data if isinstance(msg.data, dict) else {
            "code": msg.data}
        ranks = data.get("target_ranks")
        if not isinstance(ranks, list) or not ranks or not all(
                isinstance(r, int) and 0 <= r < self.world_size
                for r in ranks):
            ranks = list(range(self.world_size))
            data = dict(data)
            data["target_ranks"] = ranks
        try:
            prio = int(data.get("priority", tenant.priority))
        except (TypeError, ValueError):
            prio = tenant.priority
        reg = obs_metrics.registry()
        # Effects classification is the gateway's pre-submit analysis —
        # the latency observatory's "vet" stage; measured here because
        # only this layer knows how long it took.
        vet_s = None
        if self.policy.effects:
            t_vet = time.monotonic()
            eff_cls = self._classify_effects(data.get("code"), tenant)
            vet_s = time.monotonic() - t_vet
        else:
            eff_cls = "unknown"

        def on_verdict(ticket):
            v = ticket.verdict
            if v.get("status") == "queued":
                # The explicit backpressure reply: the kernel learns
                # its position instead of silently blocking.
                reg.counter("nbd_tenant_queued_total",
                            "tenant cells that waited in the pool "
                            "queue", {"tenant": name}).inc()
                reason = v.get("reason")
                if reason:
                    # Effects admission held the cell while slots were
                    # free: proof-gated serialization, named.
                    reg.counter(
                        "nbd_effects_serialized_total",
                        "cells serialized by effects admission "
                        "(unproven overlap)", {"tenant": name}).inc()
                    self.flight.record("effects_serialized",
                                       tenant=name, msg_id=msg.msg_id,
                                       reason=reason)
                # Only the SUBMITTING connection understands this
                # msg_id; after a reattach the notice is just noise.
                if tenant.client_id == submit_cid:
                    notice = {"status": "queued",
                              "position": v.get("position"),
                              "msg_id": msg.msg_id}
                    if reason:
                        notice["reason"] = reason
                    self._send_to_client(submit_cid, msg.reply(
                        msg_type="queued", data=notice))

        status = "ok"
        try:
            resps = self.comm.send_to_ranks(
                ranks, "execute", data, tenant=name, priority=prio,
                msg_id=msg.msg_id, on_verdict=on_verdict,
                collective=eff_cls, vet_s=vet_s,
                timeout=self.request_timeout)
            results = {str(r): m.data for r, m in resps.items()}
            if any(isinstance(d, dict) and d.get("error")
                   for d in results.values()):
                status = "error"
            reply = msg.reply(data={"status": status,
                                    "results": results})
        except CellShed:
            status = "shed"
            reg.counter("nbd_tenant_shed_total",
                        "tenant cells shed under overload",
                        {"tenant": name}).inc()
            reply = msg.reply(data={
                "status": "shed", "reason": "overload",
                "error": "cell shed under overload: the pool queue "
                         "was full and this was the lowest-priority "
                         "queued cell — retry, or raise priority"})
        except CellRejected as e:
            status = "rejected"
            reply = msg.reply(data={
                "status": "rejected", "reason": e.reason,
                "error": f"cell rejected: {e.reason} — wait for "
                         f"in-flight cells to finish"})
        except Exception as e:
            status = "error"
            reply = msg.reply(data={"status": "error",
                                    "error": f"{type(e).__name__}: "
                                             f"{e}"})
        if status == "ok":
            with self._lock:
                tenant.cells_done += 1
        elif status == "error":
            with self._lock:
                tenant.cells_failed += 1
        reg.counter("nbd_tenant_cells_total",
                    "tenant cells by terminal status",
                    {"tenant": name, "status": status}).inc()
        self._deliver(tenant, reply, submit_cid)

    # ------------------------------------------------------------------
    # serving plane (ISSUE 11)

    def _serve_plane(self, tenant, msg, client_id: int) -> None:
        """Dispatch one serve_* request (its own thread).  Replies go
        straight to the requesting connection — a dead requester's
        SUBMIT still stands (the request is journaled and will decode;
        its terminal result parks), only the verdict frame is lost."""
        try:
            data = msg.data if isinstance(msg.data, dict) else {}
            mt = msg.msg_type
            if mt == "serve_start":
                reply = self._serve_start(tenant, data)
            else:
                mgr = self._serve_mgr
                if mgr is None:
                    reply = {"status": "off",
                             "error": "no serving plane is running "
                                      "(start one: %dist_serve start)"}
                elif mt == "serve_submit":
                    reply = mgr.submit(
                        tenant.name, data.get("prompt") or (),
                        int(data.get("max_new_tokens") or 0),
                        priority=int(data["priority"])
                        if data.get("priority") is not None
                        else tenant.priority)
                elif mt == "serve_result":
                    reply = mgr.result(str(data.get("rid")))
                elif mt == "serve_stream":
                    reply = mgr.stream(str(data.get("rid")),
                                       int(data.get("from") or 0))
                elif mt == "serve_status":
                    reply = {"status": "serving", **mgr.describe()}
                else:  # serve_stop
                    with self._lock:
                        self._serve_mgr = None
                    mgr.stop()
                    self.flight.record("serving_stopped",
                                       tenant=mgr.tenant,
                                       by=tenant.name)
                    reply = {"status": "stopped", **mgr.describe()}
        except Exception as e:
            reply = {"status": "error",
                     "error": f"{type(e).__name__}: {e}"}
        finally:
            # The decrement must be unconditional (its siblings
            # _serve_execute/_serve_mailbox do the same): a reply that
            # fails to encode/send must not leak a _serving slot and
            # make the tenant unevictable forever.
            try:
                self._send_to_client(client_id, msg.reply(data=reply))
            finally:
                self._serve_done(tenant.name)

    def _serve_start(self, tenant, data: dict) -> dict:
        from .serving import ServingManager
        name = str(data.get("tenant") or "serve").strip() or "serve"
        if self.registry.get(name) is not None:
            return {"status": "error",
                    "error": f"tenant name {name!r} is in use by an "
                             f"attached tenant — pick another serving "
                             f"tenant name"}
        # Constructed OUTSIDE the lock (it opens the journal file);
        # the claim below is the race arbiter.
        mgr = ServingManager(
            self.comm, self.run_dir, tenant=name,
            params_name=data.get("params") or "params",
            cfg_name=data.get("cfg") or "cfg",
            spec=data.get("spec"),
            max_batch=data.get("max_batch"),
            max_len=data.get("max_len"),
            pad_to=int(data.get("pad_to") or 16),
            eos_id=data.get("eos_id"),
            temperature=float(data.get("temperature") or 0.0),
            steps=data.get("steps"),
            queue_depth=data.get("queue_depth"),
            inflight=data.get("inflight"),
            world_size=self.world_size,
            decode_ranks=data.get("decode_ranks"),
            kv_block_tokens=data.get("kv_block_tokens"),
            kv_blocks=data.get("kv_blocks"),
            prefill_chunk=data.get("prefill_chunk"),
            kv_quantized=bool(data.get("kv_quantized")),
            deliver=self._serve_deliver,
            notify=self._serve_notify, flight=self.flight)
        with self._lock:
            if self._serve_mgr is not None:
                loser = True
            else:
                loser = False
                self._serve_mgr = mgr
        if loser:
            mgr.journal.close()
            return {"status": "already-serving",
                    "error": "a serving plane is already running — "
                             "%dist_serve stop first"}
        try:
            mgr.start()
        except Exception as e:
            with self._lock:
                self._serve_mgr = None
            try:
                mgr.stop(close_workers=False)
            except Exception:
                pass
            return {"status": "error",
                    "error": f"serve_start failed: {e}"}
        self.flight.record("serving_started", tenant=name,
                           by=tenant.name)
        return {"status": "serving", **mgr.describe()}

    def _serve_deliver(self, tenant_name: str, reply) -> None:
        """Terminal serving results ride the tenant mailbox
        discipline: delivered to the live kernel or parked for
        exactly-once redelivery on reattach."""
        t = self.registry.get(tenant_name)
        if t is None:
            # Submitter evicted mid-generation: the journal still
            # holds the stream; only the push is droppable.
            self.flight.record("serve_result_dropped",
                               tenant=tenant_name,
                               msg_id=reply.msg_id)
            return
        self._deliver(t, reply)

    def _serve_notify(self, tenant_name: str, msg) -> None:
        t = self.registry.get(tenant_name)
        if t is None or t.client_id is None:
            return
        self._send_to_client(t.client_id, msg)

    def _gc_tenant_ns(self, name: str) -> bool:
        """Drop a departed tenant's per-worker namespaces from every
        LIVE rank — a dead worker's process took its namespace dicts
        with it, and targeting it would make send_to_ranks raise
        BEFORE transmitting to anyone.  Returns True only when every
        live rank confirmed the drop; a failure is flight-recorded so
        a stale-namespace postmortem has the evidence."""
        try:
            live = sorted(set(range(self.world_size))
                          - self.comm.dead_ranks())
            if live:
                self.comm.send_to_ranks(live, "tenant_gc",
                                        {"tenant": name}, timeout=30.0)
            self.flight.record("tenant_ns_gc", tenant=name,
                               ranks=live)
            return True
        except Exception as e:
            self.flight.record("tenant_ns_gc_failed", tenant=name,
                               error=f"{type(e).__name__}: {e}")
            return False

    def _evict_after_gc(self, name: str) -> None:
        """GC first, THEN free the admission slot.  The registry
        refuses a tokenless same-name hello while the departed tenant
        is still registered, so ordering the evict after the gc
        broadcast is what makes the gc unable to race a new tenant's
        first cell.  If the tenant reattached in the gap (old token),
        evict refuses and the slot — though not the namespace, which
        a clean goodbye forfeits — survives.

        The gc broadcast RETRIES with backoff: a busy mesh (one long
        cell on a serial worker loop) times the one-shot send out,
        and giving up there leaked the admission slot and the
        namespaces for the daemon's lifetime — max_tenants refusals
        against an empty pool after enough name rotations.  Retrying
        stops when the tenant reattaches (the namespace is live
        again — deleting it would wipe a running session) or the
        daemon closes; a still-failing mesh after the retry window is
        flight-recorded and keeps the slot (the stated-limit trade:
        a leaked slot over a leaked namespace handed to a stranger)."""
        delay, deadline = 2.0, time.time() + 1800.0
        while True:
            # Liveness check BEFORE every broadcast attempt, not just
            # after a failure: a tenant that reattached while this
            # thread was still being scheduled must not have its gc
            # land on a session that is live again.  (A reattach in
            # the check→send gap is safe: the per-worker control
            # channel is serial, so the reattached kernel's first
            # cell — which lazily rebuilds the namespace — is
            # processed AFTER this gc frame.)
            t = self.registry.get(name)
            if t is None or t.client_id is not None:
                return          # gone, or reattached: namespace live
            if self._gc_tenant_ns(name):
                break
            if time.time() >= deadline:
                self.flight.record("tenant_gc_abandoned", tenant=name)
                return          # slot survives; documented trade
            if self._closed.wait(delay):
                return          # daemon tearing down
            delay = min(delay * 2, 60.0)
        t = self.registry.get(name)
        if t is None or t.client_id is not None or len(t.mailbox) \
                or not self.comm.scheduler.tenant_idle(name):
            # The tenant came back during the gc window — and possibly
            # crashed AGAIN with parked work (reattach + crash fits in
            # a 30 s broadcast stall behind a busy mesh).  Evicting now
            # would destroy the mailbox and the session token the next
            # reattach needs; its clean goodbye, when it comes, will
            # run its own eviction.
            return
        if self.registry.evict(name):
            self.comm.scheduler.forget_tenant(name)
            # Metrics hygiene (ISSUE 11 satellite): an evicted
            # tenant's per-tenant label series would otherwise
            # accumulate one set per name for the daemon's lifetime
            # (the PR 8 stated limit).  Serve-plane series are keyed
            # by the SERVING tenant's name, so they survive.
            dropped = obs_metrics.registry().remove_label_series(
                "tenant", name)
            # getattr: unit tests drive this path on skeletal daemons
            # built with __new__ (no serving plane constructed).
            mgr = getattr(self, "_serve_mgr", None)
            if mgr is not None:
                mgr.forget_tenant(name)
            self.flight.record("tenant_evicted", tenant=name,
                               metric_series_dropped=dropped)
            self._write_manifest()

    def _deliver(self, tenant, reply, submit_cid: int | None = None) -> None:
        """Route a terminal reply to the tenant's live connection, or
        park it in the tenant's mailbox partition for exactly-once
        redelivery on reattach.

        When the tenant reattached WHILE the cell was in flight, the
        live connection is a NEW kernel with no waiter for this
        msg_id — a successful send there would be silently dropped
        client-side and the result lost forever.  Park instead: the
        reattached kernel's next mailbox drain redelivers it.

        Stated limit: a successful socket write counts as delivered.
        A kernel SIGKILLed after the OS accepts the bytes but before
        the user sees them loses that one reply — closing the window
        needs an app-level ack protocol, and the single-kernel orphan
        path accepts the same window by design (README "Tenant
        fencing & crash isolation")."""
        cid = tenant.client_id
        if (cid is not None
                and (submit_cid is None or cid == submit_cid)
                and self._send_to_client(cid, reply)):
            return
        with self._lock:
            tenant.mailbox.park(reply.msg_id, reply)
            tenant.parked_total += 1
        obs_metrics.registry().counter(
            "nbd_tenant_parked_total",
            "tenant replies parked for redelivery (kernel was gone "
            "when the cell finished)", {"tenant": tenant.name}).inc()
        self.flight.record("tenant_result_parked", tenant=tenant.name,
                           msg_id=reply.msg_id)
        if submit_cid is not None:
            # Parked BECAUSE the tenant reattached mid-cell: the new
            # kernel's hello listed the mailbox BEFORE this park, so
            # without a nudge nothing would ever drain it (and an
            # errored cell's traceback travels only in this reply).
            self._notify_parked(tenant, exclude_cid=submit_cid)

    def _notify_parked(self, tenant, *, exclude_cid=None) -> None:
        """Nudge the tenant's LIVE connection that its mailbox gained
        results its hello never listed — without the notice nothing
        drains them until another attach.  ``exclude_cid`` is the
        connection whose death/supersession caused the park (sending
        there is pointless).  Best effort: a lost notice just leaves
        the results claimable on the next attach."""
        cid = tenant.client_id
        if cid is None or cid == exclude_cid:
            return
        from ..messaging.codec import Message
        self._send_to_client(cid, Message(
            msg_type="parked_notice",
            data={"tenant": tenant.name}))

    def _on_stream(self, rank: int, data: dict) -> None:
        """Worker stream output: tenant-tagged prints route to the one
        kernel whose cell produced them; untagged output (gateway-
        internal probes) is dropped."""
        name = (data or {}).get("tenant")
        if not name:
            return
        t = self.registry.get(name)
        if t is None or t.client_id is None:
            return
        from ..messaging.codec import Message
        self._send_to_client(t.client_id, Message(
            msg_type="stream_output", rank=rank, data=data))

    # ------------------------------------------------------------------

    def _health_extra(self) -> dict:
        """Gateway block of the /healthz payload."""
        sched = self.comm.scheduler.snapshot()
        return {"kind": "gateway",
                "tenants": len(self.registry.describe().get("tenants")
                               or {}),
                "queued": sched.get("queued", 0),
                "active": sched.get("active", 0),
                "serving": self._serve_mgr is not None}

    def _latency_extra(self) -> dict:
        """Serving block of the /latency.json payload (ISSUE 18):
        the serving observatory's stage summary + utilization ring."""
        mgr = self._serve_mgr
        if mgr is None:
            return {}
        return {"serving": mgr.obs.status_block()}

    def status(self) -> dict:
        """The ``%dist_pool status`` payload: scheduler counters,
        tenant table, and a per-rank busy view (tenant-attributed)
        assembled from heartbeat pings — renders even mid-cell."""
        sched = self.comm.scheduler.snapshot()
        now = time.time()
        ranks = {}
        connected = self.comm.connected_ranks()
        for r in range(self.world_size):
            ping = self.comm.last_ping(r)
            row = {"alive": r in connected}
            if ping is not None:
                row["hb_age_s"] = round(now - ping[0], 1)
                if ping[1].get("busy_s") is not None:
                    row["busy_type"] = ping[1].get("busy_type")
                    row["busy_s"] = round(
                        ping[1]["busy_s"] + (now - ping[0]), 1)
                    row["tenant"] = ping[1].get("busy_tenant")
                if ping[1].get("srv") is not None:
                    # Serving telemetry piggyback: tokens/s and
                    # KV-slot occupancy for the %dist_top columns.
                    row["srv"] = ping[1]["srv"]
            ranks[str(r)] = row
        wd = None
        if self._watchdog is not None:
            wd = [dict(v) for v in self._watchdog.last_verdicts]
        a = self._autoscaler
        out = {"status": "ok", "run_dir": self.run_dir,
               "pid": os.getpid(), "world_size": self.world_size,
               "epoch": self.session_epoch,
               "membership": self.membership.describe(),
               "autoscale": (a.policy.describe()
                             if a is not None else None),
               # Decision audit ring (ISSUE 18): %dist_pool status
               # --autoscale renders these.
               "autoscale_decisions": (a.decisions(32)
                                       if a is not None else None),
               "scheduler": sched,
               "tenants": self.registry.describe(),
               "ranks": ranks, "hang_verdicts": wd,
               # Stage-attribution view (ISSUE 13): %dist_lat in
               # tenant mode reads this — the observatory lives in
               # THIS process, not the kernel's.
               "latency": self.comm.lat.status_block()}
        if self._metrics_httpd is not None:
            out["metrics_port"] = self._metrics_httpd.port
        mgr = self._serve_mgr
        if mgr is not None:
            out["serving"] = mgr.describe()
        return out

    def close(self) -> None:
        with self._close_lock:
            started, self._close_started = self._close_started, True
        self._manifest_dirty.set()      # release the writer thread
        if started:
            # Another thread owns the teardown; block until it is DONE
            # (not merely begun) so main() can't exit the process with
            # pooled workers still alive behind a half-run shutdown.
            self._closed.wait(timeout=30.0)
            return
        self.flight.record("gateway_stop")
        self._autoscale_stop.set()
        mgr = self._serve_mgr
        if mgr is not None:
            # Before the fleet teardown: the driver thread must stop
            # ticking (and flush its journal) while workers can still
            # answer the serve_close broadcast.
            try:
                mgr.stop()
            except Exception:
                pass
            self._serve_mgr = None
        if self._watchdog is not None:
            try:
                self._watchdog.stop()
            except Exception:
                pass
        if self._metrics_httpd is not None:
            try:
                self._metrics_httpd.close()
            except Exception:
                pass
        try:
            self._tenants_listener.close()
        except Exception:
            pass
        self.pm.quiesce()
        try:
            self.comm.post(self.comm.connected_ranks(), "shutdown")
            time.sleep(0.3)
        except Exception:
            pass
        try:
            self.comm.shutdown()
        except Exception:
            pass
        try:
            self.pm.shutdown()
        except Exception:
            pass
        # Under _manifest_lock: a writer-thread publish that passed
        # its _close_started check before we set the flag must not
        # os.replace a manifest back into place after these removals
        # (with pid reuse, a resurrected gateway.json reads as a LIVE
        # pool and attaches/gc target a daemon that no longer exists).
        with self._manifest_lock:
            for p in (gateway_manifest_path(self.run_dir),
                      session_mod.manifest_path(self.run_dir)):
                try:
                    os.remove(p)
                except OSError:
                    pass
        self._closed.set()

    def wait(self) -> None:
        """Block until ``close()`` (pool_shutdown or a signal)."""
        self._closed.wait()


# ----------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="nbdistributed_tpu session gateway daemon")
    p.add_argument("-n", "--workers", type=int, default=2)
    p.add_argument("--backend", default="auto",
                   choices=["auto", "cpu", "tpu"])
    p.add_argument("--host", default="127.0.0.1",
                   help="tenant-plane bind host")
    p.add_argument("--tenant-port", type=int, default=0)
    p.add_argument("--run-dir", default=None,
                   help="run directory (default: NBD_RUN_DIR, else "
                        "minted under the runs root)")
    p.add_argument("--max-tenants", type=int, default=None)
    p.add_argument("--sched", default=None, choices=[None, "fifo",
                                                     "fair"])
    p.add_argument("--mesh-slots", type=int, default=None)
    p.add_argument("--queue-depth", type=int, default=None)
    p.add_argument("--tenant-inflight", type=int, default=None)
    p.add_argument("--effects", action="store_true", default=None,
                   help="effects-aware admission: with mesh slots > 1 "
                        "only cells proven collective-free may "
                        "overlap a collective-bearing cell "
                        "(NBD_POOL_SCHED_EFFECTS)")
    p.add_argument("--request-timeout", type=float, default=None)
    p.add_argument("--attach-timeout", type=float, default=180.0)
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve GET /metrics (Prometheus), /healthz "
                        "and /latency.json on this port, token-gated "
                        "with the pool token (default: "
                        "NBD_METRICS_PORT; 0 = off; negative = bind "
                        "an ephemeral port, read it back from the "
                        "manifest's metrics block)")
    p.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                   help="arm the pressure-driven autoscaler with this "
                        "worker band (thresholds from the "
                        "NBD_AUTOSCALE_* knobs); the pool grows and "
                        "shrinks itself via drain-barrier resizes")
    args = p.parse_args(argv)

    autoscale_policy = None
    if args.autoscale:
        from ..resilience.autoscaler import AutoscalePolicy
        try:
            lo, _, hi = args.autoscale.partition(":")
            autoscale_policy = AutoscalePolicy.from_env()
            autoscale_policy.min_workers = max(1, int(lo))
            autoscale_policy.max_workers = max(
                autoscale_policy.min_workers, int(hi or lo))
        except ValueError:
            p.error(f"--autoscale wants MIN:MAX, got "
                    f"{args.autoscale!r}")

    if args.run_dir:
        os.environ["NBD_RUN_DIR"] = args.run_dir
    policy = SchedPolicy.pool_from_env()
    if args.sched:
        policy.mode = args.sched
    if args.mesh_slots is not None:
        policy.mesh_slots = max(0, args.mesh_slots)
    if args.queue_depth is not None:
        policy.queue_depth = max(0, args.queue_depth)
    if args.tenant_inflight is not None:
        policy.tenant_inflight = max(0, args.tenant_inflight)
    if args.effects:
        policy.effects = True

    # Handlers BEFORE construction: spawning the workers is exactly
    # the window where a fleet exists but no handler did — a SIGTERM
    # there (the %dist_pool start readiness-timeout path) used to die
    # with the default action and orphan the half-started workers.
    state: dict = {"gw": None}

    def _on_signal(signum, _frame):
        gw = state["gw"]
        if gw is not None:
            gw.close()
        else:
            # Mid-construction: raise through __init__, whose
            # BaseException guard reaps anything already spawned.
            raise SystemExit(128 + signum)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not the main thread (in-process embedding)
    try:
        state["gw"] = gw = GatewayDaemon(
            args.workers, backend=args.backend, host=args.host,
            tenant_port=args.tenant_port, policy=policy,
            max_tenants=args.max_tenants,
            request_timeout=args.request_timeout,
            attach_timeout=args.attach_timeout,
            metrics_port=args.metrics_port)
        if autoscale_policy is not None:
            gw.start_autoscale(autoscale_policy)
        print(f"NBD_GATEWAY_READY run_dir={gw.run_dir} "
              f"port={gw.tenant_port} world={gw.world_size}"
              + (f" metrics={gw._metrics_httpd.port}"
                 if gw._metrics_httpd is not None else ""),
              flush=True)
        gw.wait()
    finally:
        if state["gw"] is not None:
            state["gw"].close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
