"""Multi-pool routing and chaos-safe tenant migration (ISSUE 16).

A machine (or a shared runs root) can host several gateway pools.
:class:`PoolDirectory` discovers them the way ``discover_gateway``
finds one — gateway manifests under the runs root, pid-probed for
liveness — then probes each for load so :meth:`PoolDirectory.place`
can put a new tenant on the least-loaded pool.

:func:`migrate_tenant` moves a tenant between pools using the durable
primitives that already carry it across crashes: the export/import/
release admin plane (``tenancy.export_tenant`` et al.) plus the
serving journal.  The sequence is crash-ordered —

1. **export** at the source (non-destructive: parked results stay
   parked there, the serve journal is read, nothing is consumed);
2. **import** at the destination (idempotent: a retry converges,
   epochs only ever ratchet up);
3. **release** at the source (the only destructive step, last).

A death at any point leaves a recoverable state: before (3) the
tenant simply still lives at the source; after (3) it lives at the
destination.  Exactly-once delivery of parked results holds because
the kernel's destructive mailbox drain only ever runs against ONE
pool — the one its reattach lands on — and release removes the
source's copy before the manifest advertises the move.

When the source pool was SIGKILLed mid-migration (the chaos case),
the live export path is impossible — so the fallback reads what the
dead pool durably published: the tenant's token/epoch from its
on-disk gateway manifest and the serve journal from its run dir.
Parked results that lived only in the dead daemon's memory die with
it, exactly as they would have without a migration in flight; every
journaled serving request survives and re-admits at the destination.
"""

from __future__ import annotations

import os

from ..observability import metrics as obs_metrics
from ..resilience import session as session_mod
from . import client as client_mod
from .daemon import gateway_alive, read_gateway_manifest
from .serving import export_tenant_journal


class MigrationError(RuntimeError):
    pass


class PoolDirectory:
    """Discovery + placement over every live pool under a runs root.

    Stateless between calls (the manifests on disk ARE the state), so
    a router crash loses nothing — construct a fresh one and re-scan.
    """

    def __init__(self, runs_root: str | None = None):
        self.runs_root = runs_root or session_mod.default_runs_root()

    def discover(self) -> dict[str, dict]:
        """``{run_dir: manifest}`` for every live gateway under the
        root.  Dead manifests (stale pid) are skipped, not raised —
        a half-torn-down pool must not break placement for the rest."""
        out: dict[str, dict] = {}
        try:
            names = os.listdir(self.runs_root)
        except OSError:
            return out
        for name in sorted(names):
            d = os.path.join(self.runs_root, name)
            m = read_gateway_manifest(d)
            if gateway_alive(m):
                out[d] = m
        return out

    def probe(self, manifest: dict, *,
              timeout: float = 10.0) -> dict | None:
        """Live load snapshot of one pool (its ``pool_status``
        payload), or None when it stopped answering — discovery's pid
        probe can race a shutdown."""
        tp = manifest.get("tenant_plane") or {}
        try:
            return client_mod.pool_status_probe(
                tp.get("host") or "127.0.0.1", int(tp.get("port")),
                manifest.get("pool_token"), timeout=timeout)
        except Exception:
            return None

    @staticmethod
    def load_score(manifest: dict, status: dict | None) -> float:
        """Smaller is better: tenants per admission slot, plus the
        scheduler's queue pressure when the pool answered its probe."""
        tenants = len(manifest.get("tenants") or {})
        slots = max(1, int(manifest.get("max_tenants") or 1))
        score = tenants / slots
        if status:
            sched = status.get("scheduler") or {}
            score += (int(sched.get("queued") or 0)
                      + int(sched.get("active") or 0)) / 10.0
        return score

    def place(self, *, exclude: str | None = None,
              timeout: float = 10.0) -> tuple[str, dict] | None:
        """The least-loaded live pool ``(run_dir, manifest)`` — where
        a new (or migrating) tenant should land.  ``exclude`` drops
        the source pool from consideration."""
        best: tuple[float, str, dict] | None = None
        for d, m in self.discover().items():
            if exclude and os.path.abspath(d) == os.path.abspath(
                    exclude):
                continue
            score = self.load_score(m, self.probe(m, timeout=timeout))
            if best is None or score < best[0]:
                best = (score, d, m)
        return (best[1], best[2]) if best else None


def _dead_pool_snapshot(src_dir: str, tenant: str) -> dict:
    """Rebuild a migration snapshot from what a SIGKILLed source pool
    durably published: its gateway manifest's tenants block (token +
    epoch — the same record a reattaching kernel would use) and the
    tenant's on-disk serve journal."""
    m = read_gateway_manifest(src_dir)
    rec = ((m or {}).get("tenants") or {}).get(tenant)
    if not isinstance(rec, dict) or not rec.get("token"):
        raise MigrationError(
            f"tenant {tenant!r} is not recorded in the dead pool's "
            f"manifest at {src_dir} — nothing durable to migrate")
    snap: dict = {"tenant": tenant, "token": rec["token"],
                  "epoch": rec.get("epoch") or 1}
    journal = export_tenant_journal(src_dir, tenant)
    if journal:
        snap["serve_journal"] = journal
    return snap


def migrate_tenant(tenant: str, src_dir: str, dst_dir: str, *,
                   force: bool = False,
                   timeout: float = 60.0) -> dict:
    """Move ``tenant`` from the pool at ``src_dir`` to the one at
    ``dst_dir``.  Returns a summary dict; raises
    :class:`MigrationError` on refusal.  Safe to re-run after any
    partial failure — every step is idempotent except the final
    release, which is the commit point."""
    if os.path.abspath(src_dir) == os.path.abspath(dst_dir):
        raise MigrationError("source and destination are the same "
                             "pool")
    dst = read_gateway_manifest(dst_dir)
    if not gateway_alive(dst):
        raise MigrationError(f"no live gateway at {dst_dir}")
    src = read_gateway_manifest(src_dir)
    src_alive = gateway_alive(src)

    if src_alive:
        tp = src.get("tenant_plane") or {}
        out = client_mod.tenant_export(
            tp.get("host") or "127.0.0.1", int(tp.get("port")),
            src.get("pool_token"), tenant, timeout=timeout)
        if out.get("error"):
            raise MigrationError(f"export refused: {out['error']}")
        snap = out.get("snapshot") or {}
    else:
        # Chaos path: the source was SIGKILLed.  Its manifest and the
        # serve journal are on disk; memory-only parked results died
        # with the daemon (as they would have with no migration in
        # flight).
        snap = _dead_pool_snapshot(src_dir, tenant)

    dtp = dst.get("tenant_plane") or {}
    out = client_mod.tenant_import(
        dtp.get("host") or "127.0.0.1", int(dtp.get("port")),
        dst.get("pool_token"), snap, timeout=timeout)
    if out.get("error"):
        raise MigrationError(f"import refused: {out['error']}")

    released = False
    if src_alive:
        try:
            rel = client_mod.tenant_release(
                (src.get("tenant_plane") or {}).get("host")
                or "127.0.0.1",
                int((src.get("tenant_plane") or {}).get("port")),
                src.get("pool_token"), tenant, force=force,
                timeout=timeout)
            released = rel.get("status") == "released"
        except Exception:
            # The import already committed; a failed release means
            # the tenant exists at BOTH pools until the source's
            # operator re-runs the migration (idempotent) or the
            # source dies.  The kernel's reattach picks ONE pool, so
            # exactly-once still holds; we surface the state instead
            # of hiding it.
            released = False
    obs_metrics.registry().counter(
        "nbd_tenant_migrations_total",
        "tenant migrations by direction",
        {"direction": "routed"}).inc()
    return {"status": "migrated", "tenant": tenant,
            "src": src_dir, "dst": dst_dir,
            "src_alive": src_alive, "released": released,
            "parked_moved": len(snap.get("parked") or {}),
            "journal_moved": bool(snap.get("serve_journal")),
            "epoch": out.get("epoch")}
