"""Kernel-side tenant connection to a gateway pool.

A :class:`TenantClient` is what ``%dist_attach --tenant`` holds: one
authenticated connection to the gateway's tenant plane, a reader
thread correlating replies by message id, and the tenant's session
identity (token + epoch) from the ``tenant_hello`` exchange.  Every
request after the hello is epoch-stamped, so a crashed kernel's stale
connection can never act on a tenant that has since reattached —
the PR 4 stale-coordinator fence, client side.

The client is deliberately thin: admission, queueing, shedding, and
parking all happen gateway-side; this class just surfaces the
explicit verdicts (``on_queued`` fires with the full backpressure
notice dict — ``position`` plus, under effects admission, the
``reason`` naming why the cell was serialized;
:class:`CellSubmitError` carries a shed/rejected verdict, and
:meth:`drain` claims parked results exactly once on reattach).
"""

from __future__ import annotations

import secrets
import threading
import time

from ..messaging.codec import Message
from ..messaging.transport import TransportError, WorkerChannel


class GatewayGone(RuntimeError):
    """The tenant-plane connection died (gateway stopped/crashed)."""


class CellSubmitError(RuntimeError):
    """The pool refused the cell with an explicit verdict (shed under
    overload, or rejected at the tenant in-flight cap)."""

    def __init__(self, verdict: dict):
        super().__init__(verdict.get("error")
                         or f"cell {verdict.get('status')}")
        self.verdict = verdict


class TenantFenced(RuntimeError):
    """This connection's tenant epoch is stale: the tenant reattached
    from another kernel, which fenced this one out (the PR 4
    stale-coordinator rejection, scoped to one tenant)."""


class _Call:
    __slots__ = ("event", "reply", "notices", "late_cb", "notice_cb")

    def __init__(self):
        self.event = threading.Event()
        self.reply: Message | None = None
        self.notices: list[dict] = []
        # Set when the waiter gave up (interrupt): the reader invokes
        # it with the terminal reply instead of dropping the result.
        self.late_cb = None
        # Interim "queued" frames fire this from the reader thread —
        # the waiter no longer fast-polls for them (a multi-hour cell
        # used to wake its kernel thread 10x/s just in case).
        self.notice_cb = None


class TenantClient:
    """One tenant's live connection to the pool."""

    def __init__(self, host: str, port: int, name: str, *,
                 token: str | None = None,
                 pool_token: str | None = None,
                 priority: int | None = None,
                 hello_timeout: float = 30.0, on_stream=None):
        self.name = name
        # The preamble "rank" is this connection's client id — unique
        # per connection so the gateway can route replies; never a
        # worker rank (the tenant plane has no ranks).
        self.client_id = secrets.randbelow((1 << 30) - (1 << 20)) \
            + (1 << 20)
        self.on_stream = on_stream    # callable(rank, data) or None
        # callable(data) or None — fires (reader thread) when the
        # gateway parks a result AFTER this connection's hello (a cell
        # that was in flight across the reattach finished): the hello's
        # parked list predates it, so this nudge is the only signal to
        # drain.  Do NOT call request() from inside it (the reader
        # delivers the reply it would wait on) — hand off to a thread.
        self.on_parked = None
        # callable(data) or None — fires (reader thread) for serving-
        # plane pushes: incremental ``serve_tokens`` notices
        # ({"rid", "o", "t"}) and live terminal ``serve_done`` results
        # ({"rid", "status", "tokens"}).  Same reader-thread caveats
        # as on_parked.
        self.on_serve = None
        self._ch = WorkerChannel(host, port, rank=self.client_id,
                                 auth_token=pool_token,
                                 connect_timeout=min(hello_timeout,
                                                     30.0))
        self._lock = threading.Lock()
        self._calls: dict[str, _Call] = {}
        self._dead: Exception | None = None
        self._closed = False
        self.token = token
        self.epoch = 0
        self.parked: list[str] = []
        self.world_size = 0
        self.policy: dict = {}
        self.attach_status = ""
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f"nbd-tenant-{name}",
                                        daemon=True)
        self._reader.start()
        try:
            hello = self.request(
                "tenant_hello",
                {"tenant": name, "token": token, "priority": priority},
                timeout=hello_timeout, stamp_epoch=False)
        except BaseException:
            # A hello that times out or dies mid-flight must not leak
            # the socket + reader thread into the kernel process.
            self.close()
            raise
        data = hello.data or {}
        if data.get("error"):
            self.close()
            raise RuntimeError(f"tenant attach refused: "
                               f"{data['error']}")
        self.token = data.get("token")
        self.epoch = int(data.get("epoch") or 0)
        self.parked = list(data.get("parked") or ())
        self.world_size = int(data.get("world_size") or 0)
        self.policy = dict(data.get("policy") or {})
        self.attach_status = data.get("status") or "admitted"

    # ------------------------------------------------------------------

    def _read_loop(self) -> None:
        while True:
            try:
                msg = self._ch.recv()
            except Exception as e:
                with self._lock:
                    self._dead = e if not self._closed else None
                    calls = list(self._calls.values())
                    self._calls.clear()
                for c in calls:
                    c.event.set()
                return
            if msg.msg_type == "stream_output":
                cb = self.on_stream
                if cb is not None:
                    try:
                        cb(msg.rank, msg.data or {})
                    except Exception:
                        pass
                continue
            if msg.msg_type == "parked_notice":
                cb = self.on_parked
                if cb is not None:
                    try:
                        cb(msg.data or {})
                    except Exception:
                        pass
                continue
            if msg.msg_type in ("serve_tokens", "serve_done"):
                # Serving-plane pushes are uncorrelated (no waiter):
                # token stream notices while a request decodes, and a
                # live terminal result.  (A terminal result with NO
                # live connection parks instead and arrives through
                # drain().)
                cb = self.on_serve
                if cb is not None:
                    try:
                        cb(dict(msg.data or {}))
                    except Exception:
                        pass
                continue
            with self._lock:
                c = self._calls.get(msg.msg_id)
            if c is None:
                continue  # late reply to an abandoned request
            if msg.msg_type == "queued":
                c.notices.append(msg.data or {})
                cb = c.notice_cb
                if cb is not None:
                    try:
                        cb(msg.data or {})
                    except Exception:
                        pass
                continue
            # reply-set + late_cb read happen under the lock so the
            # handoff with an interrupted waiter (which checks reply
            # then sets late_cb under the same lock) can't lose the
            # terminal reply to a race.
            with self._lock:
                c.reply = msg
                self._calls.pop(msg.msg_id, None)
                cb = c.late_cb
            c.event.set()
            if cb is not None:
                try:
                    cb(msg)
                except Exception:
                    pass

    @property
    def alive(self) -> bool:
        return self._dead is None and not self._closed

    def _check(self) -> None:
        if self._closed:
            raise GatewayGone("tenant client is closed")
        if self._dead is not None:
            raise GatewayGone(f"gateway connection lost: "
                              f"{self._dead}")

    # ------------------------------------------------------------------

    def request(self, msg_type: str, data=None, *,
                timeout: float | None = 60.0, on_notice=None,
                stamp_epoch: bool = True, late_cb=None) -> Message:
        """One request/response round trip.  ``on_notice`` fires from
        the READER thread for interim ``queued`` frames (queue-
        position backpressure) — keep it cheap and non-blocking.
        ``late_cb(reply)``, when given, fires from the reader thread
        if the waiter abandons the request (KeyboardInterrupt) and the
        terminal reply arrives later on this live connection — without
        it the result would be silently dropped (delivered, so never
        parked gateway-side)."""
        self._check()
        msg = Message(msg_type=msg_type, data=data,
                      rank=self.client_id)
        if stamp_epoch and self.epoch:
            msg.epoch = self.epoch
        call = _Call()
        call.notice_cb = on_notice   # fires from the reader thread
        with self._lock:
            self._calls[msg.msg_id] = call
        try:
            self._ch.send(msg)
        except Exception as e:
            with self._lock:
                self._calls.pop(msg.msg_id, None)
            raise GatewayGone(f"gateway connection lost: {e}") from e
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        try:
            while True:
                step = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                # Notices arrive via the reader thread's notice_cb, so
                # the wait can use long chunks — bounded (not
                # infinite) only so Ctrl-C stays responsive on every
                # platform.
                done = call.event.wait(5.0 if step is None
                                       else min(5.0, step))
                if done:
                    break
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    # Same delivered-or-parked discipline as the
                    # KeyboardInterrupt path below: with a late_cb
                    # the call stays registered so the terminal
                    # reply — which the gateway will count as
                    # DELIVERED and never park — is surfaced instead
                    # of silently dropped.
                    with self._lock:
                        if call.reply is not None:
                            break            # landed at the wire
                        if late_cb is not None:
                            call.late_cb = late_cb
                        else:
                            self._calls.pop(msg.msg_id, None)
                    raise TimeoutError(
                        f"no gateway reply to '{msg_type}' within "
                        f"{timeout}s")
        except KeyboardInterrupt:
            if late_cb is not None:
                with self._lock:
                    landed = call.reply      # set under this lock by
                    if landed is None:       # the reader thread
                        call.late_cb = late_cb   # reader fires later
                if landed is not None:       # landed while unwinding
                    try:
                        late_cb(landed)
                    except Exception:
                        pass
            else:
                with self._lock:
                    self._calls.pop(msg.msg_id, None)
            raise
        if call.reply is None:
            self._check()
            raise GatewayGone("gateway connection lost mid-request")
        if (call.reply.data or {}).get("stale_epoch"):
            # Central fence: EVERY request type surfaces a reattach-
            # elsewhere as TenantFenced (drain()/pool_status() used to
            # swallow it as an empty result).
            raise TenantFenced((call.reply.data or {}).get("error")
                               or "stale tenant epoch")
        return call.reply

    def execute(self, code: str, *, priority: int | None = None,
                deadline_s: float | None = None,
                target_ranks: list[int] | None = None,
                timeout: float | None = None,
                on_queued=None, on_late=None) -> dict:
        """Submit one cell to the pool and wait for its terminal
        verdict.  Returns the gateway reply data
        (``{"status": "ok", "results": {rank: result}}``); raises
        :class:`CellSubmitError` on a shed/rejected verdict.
        ``on_queued(notice)`` fires with the full backpressure notice
        dict — ``position`` plus, under effects admission, the
        ``reason`` naming why the cell was serialized.
        ``on_late(data)`` fires if the waiter is interrupted and the
        cell's result arrives later on this connection.
        ``target_ranks`` narrows the cell to specific pool ranks
        (default: every rank — which fails fast with an error verdict
        when any rank is dead)."""
        payload: dict = {"code": code}
        if priority is not None:
            payload["priority"] = int(priority)
        if deadline_s is not None:
            payload["deadline_s"] = float(deadline_s)
        if target_ranks is not None:
            payload["target_ranks"] = [int(r) for r in target_ranks]

        def _notice(n: dict) -> None:
            if on_queued is not None and n.get("status") == "queued":
                on_queued(dict(n))

        reply = self.request(
            "execute", payload, timeout=timeout, on_notice=_notice,
            late_cb=(None if on_late is None
                     else lambda m: on_late(m.data or {})))
        data = reply.data or {}
        if data.get("status") in ("shed", "rejected"):
            raise CellSubmitError(data)
        return data

    def drain(self, *, timeout: float | None = 60.0,
              on_late=None) -> dict:
        """Claim every result parked for this tenant — exactly once
        (the gateway's claim is destructive; a second drain returns
        an empty dict).  ``on_late({msg_id: reply_data})`` fires from
        the reader thread if the waiter times out or is interrupted
        and the claimed results arrive later — without it a destroyed
        claim whose reply outlived the wait would be lost on both
        sides."""
        reply = self.request(
            "mailbox", {"action": "drain"}, timeout=timeout,
            late_cb=(None if on_late is None
                     else lambda m: on_late(
                         dict((m.data or {}).get("results") or {}))))
        return dict((reply.data or {}).get("results") or {})

    def pool_status(self, *, timeout: float | None = 30.0) -> dict:
        return dict(self.request("pool_status",
                                 timeout=timeout).data or {})

    # ------------------------------------------------------------------
    # serving plane (%dist_serve, ISSUE 11)

    def serve_start(self, spec: str | None = None, *,
                    tenant: str | None = None,
                    params: str | None = None, cfg: str | None = None,
                    max_batch: int | None = None,
                    max_len: int | None = None,
                    pad_to: int | None = None,
                    eos_id: int | None = None,
                    temperature: float | None = None,
                    steps: int | None = None,
                    queue_depth: int | None = None,
                    inflight: int | None = None,
                    decode_ranks: int | None = None,
                    kv_block_tokens: int | None = None,
                    kv_blocks: int | None = None,
                    prefill_chunk: int | None = None,
                    kv_quantized: bool | None = None,
                    timeout: float | None = 600.0) -> dict:
        """Start the pool's serving plane: run ``spec`` (a cell that
        binds the model params/config in the serving tenant's
        namespace on every rank) and open the decode loop.  Returns
        the serving status dict; raises on an explicit refusal."""
        payload = {k: v for k, v in {
            "spec": spec, "tenant": tenant, "params": params,
            "cfg": cfg, "max_batch": max_batch, "max_len": max_len,
            "pad_to": pad_to, "eos_id": eos_id,
            "temperature": temperature, "steps": steps,
            "queue_depth": queue_depth, "inflight": inflight,
            "decode_ranks": decode_ranks,
            "kv_block_tokens": kv_block_tokens,
            "kv_blocks": kv_blocks, "prefill_chunk": prefill_chunk,
            "kv_quantized": kv_quantized,
        }.items() if v is not None}
        data = dict(self.request("serve_start", payload,
                                 timeout=timeout).data or {})
        if data.get("error"):
            raise RuntimeError(f"serve_start refused: {data['error']}")
        return data

    def serve_submit(self, prompt, max_new_tokens: int, *,
                     priority: int | None = None,
                     timeout: float | None = 60.0) -> dict:
        """Submit one generation request.  Returns the accepted
        verdict (``{"status": "accepted", "rid": ..., "queued": ...}``);
        raises :class:`CellSubmitError` on an explicit shed/rejected
        verdict — the same overload contract cells have."""
        payload: dict = {"prompt": [int(t) for t in prompt],
                         "max_new_tokens": int(max_new_tokens)}
        if priority is not None:
            payload["priority"] = int(priority)
        data = dict(self.request("serve_submit", payload,
                                 timeout=timeout).data or {})
        if data.get("status") in ("shed", "rejected"):
            raise CellSubmitError(data)
        if data.get("error") and data.get("status") != "accepted":
            raise RuntimeError(f"serve_submit failed: {data['error']}")
        return data

    def serve_result(self, rid: str, *,
                     timeout: float | None = 60.0) -> dict:
        """Poll one request: ``{"status", "tokens", "done"}``."""
        return dict(self.request("serve_result", {"rid": rid},
                                 timeout=timeout).data or {})

    def serve_stream(self, rid: str, from_offset: int = 0, *,
                     timeout: float | None = 60.0) -> dict:
        """Claim the stream suffix past ``from_offset`` — the
        reattach-mid-generation resume: pass the last offset this
        client acked and the gateway replays only what is missing
        (live pushes continue via :attr:`on_serve`)."""
        return dict(self.request(
            "serve_stream", {"rid": rid, "from": int(from_offset)},
            timeout=timeout).data or {})

    def serve_status(self, *, timeout: float | None = 30.0) -> dict:
        return dict(self.request("serve_status",
                                 timeout=timeout).data or {})

    def serve_stop(self, *, timeout: float | None = 60.0) -> dict:
        return dict(self.request("serve_stop",
                                 timeout=timeout).data or {})

    def close(self, *, detach: bool = False) -> None:
        if self._closed:
            return
        if detach and self._dead is None:
            try:
                self.request("detach", timeout=5.0)
            except Exception:
                pass
        self._closed = True
        try:
            self._ch.close()
        except Exception:
            pass
        # Closing the channel unblocks the reader loop; reap it so a
        # closed client never leaves a thread that takes self._lock
        # running into interpreter teardown (daemon threads die
        # mid-critical-section there).  close() may be invoked from a
        # reader-thread callback — a thread cannot join itself.
        if threading.current_thread() is not self._reader:
            self._reader.join(timeout=2.0)


# ----------------------------------------------------------------------
# pool admin probes (no tenant slot consumed)


def _admin_request(host: str, port: int, pool_token: str | None,
                   msg_type: str, data=None, *,
                   timeout: float = 30.0) -> dict:
    """One-shot tenant-plane request outside any tenant session —
    the gateway serves ``pool_status``/``pool_shutdown`` pre-hello."""
    cid = secrets.randbelow(1 << 20) + (1 << 30)
    ch = WorkerChannel(host, port, rank=cid, auth_token=pool_token,
                       connect_timeout=timeout)
    try:
        msg = Message(msg_type=msg_type, data=data, rank=cid)
        ch.send(msg)
        deadline = time.monotonic() + timeout
        while True:
            step = max(0.1, deadline - time.monotonic())
            reply = ch.recv(timeout=step)
            if reply.msg_id == msg.msg_id:
                return dict(reply.data or {})
            if time.monotonic() >= deadline:
                raise TimeoutError(f"no {msg_type} reply within "
                                   f"{timeout}s")
    finally:
        try:
            ch.close()
        except (OSError, TransportError):
            pass


def pool_status_probe(host: str, port: int,
                      pool_token: str | None, *,
                      timeout: float = 30.0) -> dict:
    return _admin_request(host, port, pool_token, "pool_status",
                          timeout=timeout)


def pool_shutdown(host: str, port: int, pool_token: str | None, *,
                  timeout: float = 30.0) -> dict:
    return _admin_request(host, port, pool_token, "pool_shutdown",
                          {"token": pool_token}, timeout=timeout)


def pool_resize(host: str, port: int, pool_token: str | None,
                workers: int, *, reason: str = "manual",
                timeout: float = 600.0) -> dict:
    """Resize the pool's worker fleet (drain barrier + epoch bump).
    Long default timeout: the reply lands only after the drain and
    the respawned fleet's readiness."""
    return _admin_request(host, port, pool_token, "pool_resize",
                          {"token": pool_token, "workers": workers,
                           "reason": reason}, timeout=timeout)


def pool_template(host: str, port: int, pool_token: str | None,
                  code: str | None = None, *, name: str = "default",
                  timeout: float = 600.0) -> dict:
    """Register (and run) a warm-start template cell, or list the
    registered templates when ``code`` is None."""
    data = {"token": pool_token, "name": name}
    if code is not None:
        data["code"] = code
    return _admin_request(host, port, pool_token, "pool_template",
                          data, timeout=timeout)


def tenant_export(host: str, port: int, pool_token: str | None,
                  tenant: str, *, timeout: float = 60.0) -> dict:
    """Non-destructive migration snapshot of a tenant's durable
    state (token, epoch, parked results, serve journal)."""
    return _admin_request(host, port, pool_token, "tenant_export",
                          {"token": pool_token, "tenant": tenant},
                          timeout=timeout)


def tenant_import(host: str, port: int, pool_token: str | None,
                  snapshot: dict, *, timeout: float = 60.0) -> dict:
    """Idempotently adopt an exported tenant at this pool."""
    return _admin_request(host, port, pool_token, "tenant_import",
                          {"token": pool_token, "snapshot": snapshot},
                          timeout=timeout)


def tenant_release(host: str, port: int, pool_token: str | None,
                   tenant: str, *, force: bool = False,
                   timeout: float = 60.0) -> dict:
    """Drop a migrated-away tenant from its source pool."""
    return _admin_request(host, port, pool_token, "tenant_release",
                          {"token": pool_token, "tenant": tenant,
                           "force": force}, timeout=timeout)
