"""The serving plane: continuous-batching generation through the
gateway (``%dist_serve``, ISSUE 11).

The tenant plane, admission control, and mailbox discipline (PR 8)
*are* a serving front door; :class:`~..models.serving.DecodeServer` is
the continuous-batching engine.  This module connects them:

* **Request ingress.**  ``serve_submit`` enters a generation request
  as a ticket of the serving :class:`~.scheduler.Scheduler` — one KV
  slot per mesh-slot, the submitting tenant's SLO priority as the
  fair-share key — so overload degrades with the SAME explicit
  verdicts cells get: ``accepted`` (dispatch/queued with a position),
  ``shed`` (queue full, lowest priority lost the round), ``rejected``
  (submitter at its in-flight cap).  The pool never wedges behind a
  flood of prompts.

* **Decode loop.**  A single driver thread ticks the pool: each tick
  sends one ``serve_step`` per *decode rank* (the highest
  ``decode_ranks`` live ranks — see
  :meth:`ServingManager._pick_ranks` for why the fleet fills from the
  top) carrying that rank's admissions/releases and a step budget;
  the worker runs the admissions plus up to ``steps`` decode steps on
  its :class:`DecodeServer` and replies with per-request emissions at
  explicit offsets.  With several decode ranks the steps are
  pre-submitted through the ISSUE 14 submission/completion split so
  the ranks decode concurrently — continuous batching across the
  whole slice (ISSUE 17), each request living entirely on ONE rank so
  failover and exactness arguments are unchanged.  Admission is
  bounded by free KV *blocks* per rank (a gateway-side
  :class:`~..serving_fast.paging.BlockAllocator` mirrors each
  worker's paged cache), not just sequence slots.  The worker's
  serial request loop is the interleaving point with notebook cells —
  a decode tick waits its turn like any other request, so serving
  never starves tenants (and vice versa, at step granularity).

* **Durability (the robustness headline).**  An accepted request is
  journaled — prompt, sampling budget, and every emitted token — in
  an append-only :class:`ServeJournal` under the run dir *before* its
  verdict returns.  When the decode rank is SIGKILLed mid-decode (a
  seeded ``FaultPlan``, or a real preemption) the driver fails over to
  the next live rank, re-opens a fresh ``DecodeServer`` there, and
  **re-admits every unfinished request from its journal**: the new
  prompt is ``prompt + emitted-prefix`` and the budget is what
  remains, so greedy decoding continues bit-identically (prefill of a
  prefix computes the same cache rows decode did — the exactness
  argument :meth:`DecodeServer.cache_prefix` already makes).  Every
  emission carries its worker-side offset; the journal's length is
  the delivery cursor, so redelivered or replayed tokens are DROPPED
  by offset (``nbd_serve_dup_dropped_total`` — pinned to zero by the
  chaos tests) and each request's stream is emitted exactly once.

* **Delivery.**  Tokens stream to the submitting tenant's live
  connection as ``serve_tokens`` notices with offsets; a kernel that
  reattaches mid-generation resumes with ``serve_stream`` from its
  last acked offset.  A request that finishes while its tenant has no
  kernel parks a terminal ``serve_done`` reply in that tenant's
  mailbox partition — the PR 4 delivered-or-parked-exactly-once
  discipline extended to generation results.

Thread discipline: ``self._lock`` guards the request table and
counters; helpers suffixed ``_locked`` assert their callers hold it
(self-lint enforced).  All wire IO (``send_to_ranks``, journal
appends) happens OUTSIDE the lock; the journal serializes its file
writes with its own lock and is always acquired under the manager
lock-free path or strictly after ``self._lock`` (acyclic order).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque

from ..messaging.codec import Message
from ..observability import latency as obs_latency
from ..observability import metrics as obs_metrics
from ..observability.servingobs import ServingObservatory
from ..serving_fast.paging import BlockAllocator, blocks_needed
from ..utils import knobs
from .scheduler import ACTIVE, SchedPolicy, Scheduler
from .scheduler import SHED as TICKET_SHED

# Request lifecycle (gateway-side; scheduler states are the admission
# half, these are the serving half).
ACCEPTED = "accepted"
COMPLETED = "completed"
SHED_V = "shed"
REJECTED_V = "rejected"
FAILED = "failed"

SERVE_JOURNAL_NAME = "serve-{tenant}.jsonl"

# A migrated tenant's journal records, staged by ``tenant_import``
# (ISSUE 16) for the destination's serving plane to adopt at its next
# start.  Named so the export scan below re-exports an unconsumed
# stash on a second migration hop.
SERVE_MIGRATED_NAME = "serve-migrated-{tenant}.jsonl"
_MIGRATED_PREFIX = "serve-migrated-"


def journal_path(run_dir: str, tenant: str) -> str:
    return os.path.join(run_dir, SERVE_JOURNAL_NAME.format(tenant=tenant))


def migrated_journal_path(run_dir: str, tenant: str) -> str:
    return os.path.join(run_dir,
                        SERVE_MIGRATED_NAME.format(tenant=tenant))


def export_tenant_journal(run_dir: str, tenant: str, *,
                          cap: int = 32 << 20) -> str:
    """Every serving-journal line that belongs to ``tenant`` across
    ALL journals under ``run_dir``, as a journal-formatted string
    (empty when the tenant has no serving history).

    A serving plane's journal is keyed by the SERVING tenant and
    interleaves every submitter's records, so a migrating tenant's
    lines must be filtered out of each — matching the ``accept``
    records' ``tenant`` field, then keeping the matched rids' ``emit``
    and ``done`` lines.  Unconsumed migrated stashes are scanned too
    (their names share the ``serve-`` prefix), so a tenant that hops
    pools twice before serving carries its history the whole way."""
    out: list[str] = []
    size = 0
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return ""
    for fn in names:
        if not fn.startswith("serve-") or not fn.endswith(".jsonl"):
            continue
        rids: set = set()
        try:
            with open(os.path.join(run_dir, fn),
                      encoding="utf-8") as f:
                for line in f:
                    line = line.rstrip("\n")
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue   # torn tail (death mid-write)
                    if not isinstance(rec, dict):
                        continue
                    if rec.get("e") == "accept":
                        if rec.get("tenant") != tenant:
                            continue
                        rids.add(rec.get("rid"))
                    elif rec.get("rid") not in rids:
                        continue
                    size += len(line) + 1
                    if size > cap:
                        return "\n".join(out) + "\n"
                    out.append(line)
        except OSError:
            continue
    return ("\n".join(out) + "\n") if out else ""


def merge_emission(have: int, base: int, offset: int,
                   toks: list[int]) -> tuple[list[int], int]:
    """Offset-deduplicated merge of one worker emission into a stream
    that already holds ``have`` tokens.

    ``base`` is the stream offset the request's CURRENT placement
    started at (0 for a first admission; the journaled prefix length
    after a re-admission), ``offset`` the worker-side offset of this
    emission within that placement.  Returns ``(new_tokens,
    dup_count)``: the suffix beyond ``have`` and how many tokens were
    dropped as already-delivered (a replayed or redelivered emission).
    A *gap* (emission starts beyond ``have``) cannot happen under the
    protocol — the driver only advances the journal on received
    replies — and is surfaced as ``(None, 0)`` so the caller can
    refuse to journal around a hole instead of silently corrupting
    the stream.
    """
    goff = base + offset
    if goff > have:
        return None, 0
    skip = have - goff
    if skip >= len(toks):
        return [], len(toks)
    return list(toks[skip:]), skip


class ServeJournal:
    """Append-only JSONL journal of accepted requests and their token
    streams — the durability core.  One line per event::

        {"e": "accept", "rid": r, "tenant": t, "prompt": [...],
         "max_new": n, "prio": p}
        {"e": "emit", "rid": r, "o": offset, "t": [tokens]}
        {"e": "done", "rid": r, "status": "completed"|"shed"|"failed"}

    The file handle is opened once (append mode) and each event is
    written + flushed under the journal's own lock, so concurrent
    submit threads and the driver thread interleave whole lines.
    :meth:`load` tolerates a torn final line (the process died
    mid-write) exactly like the manifest readers do.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")

    def _append(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":"))
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def accept(self, rid: str, tenant: str, prompt: list[int],
               max_new: int, priority: int) -> None:
        self._append({"e": "accept", "rid": rid, "tenant": tenant,
                      "prompt": list(prompt), "max_new": int(max_new),
                      "prio": int(priority)})

    def emit(self, rid: str, offset: int, toks: list[int]) -> None:
        self._append({"e": "emit", "rid": rid, "o": int(offset),
                      "t": list(toks)})

    def done(self, rid: str, status: str) -> None:
        self._append({"e": "done", "rid": rid, "status": status})

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    @staticmethod
    def load(path: str) -> dict[str, dict]:
        """Replay the journal into ``{rid: {"tenant", "prompt",
        "max_new", "prio", "tokens", "done"}}``.  Emissions are merged
        by offset with the same dedup rule the live path uses, so a
        journal that recorded a replayed emission twice still loads a
        single exact stream."""
        out: dict[str, dict] = {}
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            return out
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail (death mid-write) — skip
            if not isinstance(rec, dict):
                continue
            e, rid = rec.get("e"), rec.get("rid")
            if rid is None:
                continue
            if e == "accept":
                out[rid] = {"tenant": rec.get("tenant"),
                            "prompt": list(rec.get("prompt") or ()),
                            "max_new": int(rec.get("max_new") or 0),
                            "prio": int(rec.get("prio") or 0),
                            "tokens": [], "done": None}
            elif e == "emit" and rid in out:
                r = out[rid]
                new, _dup = merge_emission(len(r["tokens"]), 0,
                                           int(rec.get("o") or 0),
                                           list(rec.get("t") or ()))
                if new:
                    r["tokens"].extend(new)
            elif e == "done" and rid in out:
                out[rid]["done"] = rec.get("status") or COMPLETED
        return out

    @staticmethod
    def unfinished(state: dict[str, dict]) -> list[dict]:
        """Re-admission plan from :meth:`load` output: every accepted
        request without a terminal record, as ``{"rid", "tenant",
        "prompt" (original + emitted prefix), "max_new" (remaining),
        "base" (tokens already delivered), "prio"}`` — exactly the
        admit the driver sends after a heal."""
        plan = []
        for rid, r in state.items():
            if r["done"] is not None:
                continue
            emitted = r["tokens"]
            remaining = r["max_new"] - len(emitted)
            if remaining <= 0:
                continue
            plan.append({"rid": rid, "tenant": r["tenant"],
                         "prompt": list(r["prompt"]) + list(emitted),
                         "max_new": remaining, "base": len(emitted),
                         "prio": r["prio"]})
        return plan


class _Req:
    __slots__ = ("rid", "tenant", "prompt", "max_new", "priority",
                 "tokens", "state", "base", "placed", "replay",
                 "ticket", "released", "submitted_ts", "finished_ts",
                 "resumes", "stream_resumed", "error",
                 "placed_ts", "first_tok_ts", "last_emit_ts",
                 "first_batch", "rank")

    def __init__(self, rid: str, tenant: str, prompt: list[int],
                 max_new: int, priority: int, ticket):
        self.rid = rid
        self.tenant = tenant
        self.prompt = prompt
        self.max_new = max_new
        self.priority = priority
        self.tokens: list[int] = []
        self.state = ACCEPTED          # accepted | completed | shed | failed
        self.base = 0                  # stream offset of current placement
        self.placed = False            # admitted to a decode rank
        self.rank: int | None = None   # which decode rank holds it
        self.replay = False            # next admit is a journal replay
        self.released = False          # host-side record freed worker-side
        self.ticket = ticket
        self.submitted_ts = time.time()
        self.finished_ts: float | None = None
        self.resumes = 0               # journal re-admissions (heals)
        self.stream_resumed = False    # counted one client resume
        self.error: str | None = None
        # SLO stamps (ISSUE 13): first KV-slot placement, first token
        # arrival (TTFT), newest emission arrival (TPOT gaps), and the
        # size of the first emission batch (excluded from the
        # per-token rate — it includes prefill).
        self.placed_ts: float | None = None
        self.first_tok_ts: float | None = None
        self.last_emit_ts: float | None = None
        self.first_batch = 0


class _RankLost(RuntimeError):
    """A decode rank died or stopped answering: fail over.

    ``rank`` names the lost rank so the multi-rank driver un-places
    only ITS requests; ``None`` means "whoever is open" (the legacy
    single-rank paths)."""

    def __init__(self, msg: str, rank: int | None = None):
        super().__init__(msg)
        self.rank = rank


class ServingManager:
    """One serving tenant's request plane + decode driver.

    Owned by the :class:`~.daemon.GatewayDaemon` (``serve_start``),
    but deliberately decoupled from it: the constructor takes the
    coordinator-side ``comm`` plus two delivery callables, so unit
    tests drive the whole admission/journal/failover machinery against
    a fake comm with no pool.

    ``deliver(tenant_name, reply_message)`` routes a TERMINAL result
    (delivered-or-parked — the daemon wires it to its mailbox path);
    ``notify(tenant_name, message)`` best-effort pushes a live
    ``serve_tokens`` notice.
    """

    def __init__(self, comm, run_dir: str, *, tenant: str = "serve",
                 params_name: str = "params", cfg_name: str = "cfg",
                 spec: str | None = None,
                 max_batch: int | None = None,
                 max_len: int | None = None, pad_to: int = 16,
                 eos_id: int | None = None, temperature: float = 0.0,
                 steps: int | None = None,
                 step_timeout: float | None = None,
                 queue_depth: int | None = None,
                 inflight: int | None = None,
                 world_size: int | None = None,
                 decode_ranks: int | None = None,
                 kv_block_tokens: int | None = None,
                 kv_blocks: int | None = None,
                 prefill_chunk: int | None = None,
                 kv_quantized: bool = False,
                 deliver=None, notify=None, flight=None):
        self.comm = comm
        self.run_dir = run_dir
        self.tenant = tenant
        self.params_name = params_name
        self.cfg_name = cfg_name
        self.spec = spec
        self.max_batch = max_batch if max_batch is not None \
            else knobs.get_int("NBD_SERVE_MAX_BATCH", 8)
        self.max_len = max_len if max_len is not None \
            else knobs.get_int("NBD_SERVE_MAX_LEN", 512)
        self.pad_to = max(1, int(pad_to))
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.steps = steps if steps is not None \
            else knobs.get_int("NBD_SERVE_STEPS", 8)
        self.step_timeout = step_timeout if step_timeout is not None \
            else knobs.get_float("NBD_SERVE_STEP_TIMEOUT_S", 120.0)
        qd = queue_depth if queue_depth is not None \
            else knobs.get_int("NBD_SERVE_QUEUE_DEPTH", 64)
        infl = inflight if inflight is not None \
            else knobs.get_int("NBD_SERVE_INFLIGHT", 32)
        self.world_size = world_size if world_size is not None \
            else getattr(comm, "num_workers", 1)
        # Serving fast path (ISSUE 17): how many decode ranks to drive
        # (0 = every live rank), and the paged-KV geometry mirrored on
        # each of them.  The gateway keeps one accounting
        # BlockAllocator per open rank so admission is bounded by free
        # KV *blocks*, not sequence slots.
        self.decode_ranks = decode_ranks if decode_ranks is not None \
            else knobs.get_int("NBD_SERVE_DECODE_RANKS", 1)
        self.kv_block_tokens = kv_block_tokens \
            if kv_block_tokens is not None \
            else knobs.get_int("NBD_KV_BLOCK_TOKENS", 64)
        kvb = kv_blocks if kv_blocks is not None \
            else knobs.get_int("NBD_KV_BLOCKS_PER_RANK", 0)
        # 0 = derived dense capacity: max_batch rows of max_len each.
        self.kv_blocks_per_rank = int(kvb) if kvb else (
            self.max_batch
            * blocks_needed(self.max_len, self.kv_block_tokens))
        pck = prefill_chunk if prefill_chunk is not None \
            else knobs.get_int("NBD_PREFILL_CHUNK_TOKENS", 0)
        self.prefill_chunk = int(pck) if pck else None
        self.kv_quantized = bool(kv_quantized)
        self._deliver = deliver or (lambda _t, _m: None)
        self._notify = notify or (lambda _t, _m: None)
        self._flight = flight
        # One KV slot per scheduler mesh-slot: a granted ticket IS a
        # free slot on a decode server, so admission, queueing, and
        # shedding reuse the pool scheduler's exact verdict machinery
        # (fair mode: the submitting tenant's SLO priority first).
        # With K decode ranks the mesh has K * max_batch slots; block
        # accounting in _place_admits_locked is the finer-grained gate
        # underneath the ticket.
        n_target = self.decode_ranks if self.decode_ranks > 0 \
            else max(1, self.world_size)
        self.sched = Scheduler(SchedPolicy(
            "fair", mesh_slots=self.max_batch * n_target,
            tenant_inflight=infl, queue_depth=qd))
        self.journal = ServeJournal(journal_path(run_dir, tenant))
        self._lock = threading.Lock()
        self._reqs: dict[str, _Req] = {}
        self._next_rid = 0
        # rank -> gateway-side accounting BlockAllocator (owner = rid),
        # one per OPEN decode rank.  Mirrors the worker's device
        # allocator by construction: both see the same admit/release
        # order, and the free list is deterministic.  The gateway's
        # copy frees at _finish (one tick before the worker processes
        # the release) — optimistic by at most one tick; the worker's
        # DecodeServer keeps an over-admitted request pending until
        # blocks free, so the skew self-heals without a verdict.
        self._open: dict[int, BlockAllocator] = {}
        # rank -> monotonic deadline to avoid it: a rank whose
        # serve_open failed (missing namespace after a reconnect,
        # OOM building the server) must not be retried forever while
        # lower ranks could serve.
        self._avoid: dict[int, float] = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        # Drain barrier (ISSUE 16): while _pause is set the driver
        # parks between ticks; _tick_idle is set whenever no decode
        # tick is mid-flight, so pause() can wait for the in-flight
        # tick to FINISH (a tick interrupted mid-step would redeliver
        # into the new epoch and be fenced as stale).
        self._pause = threading.Event()
        self._tick_idle = threading.Event()
        self._tick_idle.set()
        self._driver: threading.Thread | None = None
        self.started_ts = time.time()
        # Counters (all read under the lock for describe()).
        self.accepted = 0
        self.completed = 0
        self.shed = 0
        self.rejected = 0
        self.replayed = 0       # re-admissions after a failover
        self.resumed = 0        # stream resumes from a client offset
        self.failovers = 0
        self.step_retries = 0
        self.dup_dropped = 0
        self.tokens_total = 0
        self.last_error: str | None = None
        # SLO ring (ISSUE 13): one entry per COMPLETED request —
        # {tenant, ttft, tpot, queue, e2e} seconds — backing the
        # p50/p99 columns of %dist_serve status / %dist_pool status.
        # The histograms below carry the full distributions for
        # /metrics; the ring keeps exact recent percentiles cheap.
        self._slo: deque = deque(maxlen=256)
        # Serving observatory (ISSUE 18): per-request stage
        # attribution + per-tick utilization telemetry.  Worker
        # emission stamps are corrected through the coordinator's
        # per-rank offset estimator when the comm carries one.
        self.obs = ServingObservatory(
            clock=getattr(comm, "clock", None))
        # Deferred-placement memo: the last set of rids that waited a
        # tick with no rank able to hold them, so the flight ring gets
        # ONE record per defer episode, not one per tick.
        self._last_deferred: frozenset = frozenset()
        # Ranks whose KV gauges were last published (driver thread
        # only): a retired rank's series is zeroed the next tick.
        self._gauged_ranks: set[int] = set()

    def _slo_hist(self, name: str, help: str, tenant: str):
        """Per-SUBMITTING-tenant SLO histogram, resolved through the
        registry at every use so tenant eviction's
        ``remove_label_series("tenant", name)`` really retires the
        series (the no-cached-handles rule metrics.py documents)."""
        return obs_metrics.registry().histogram(
            name, help, {"tenant": tenant},
            buckets=obs_metrics.LATENCY_BUCKETS)

    # ------------------------------------------------------------------
    # lifecycle

    def start(self, *, spec_timeout: float = 600.0) -> dict:
        """Seed the serving tenant's namespace (run the model-spec
        cell on every live rank) and start the decode driver.  Raises
        on a spec error — a serving plane without a model is refused
        at start, not discovered at the first submit.

        A pre-existing journal for this tenant (the previous daemon
        died, or a serve_stop/serve_start cycle) is RECOVERED first:
        every journaled request without a terminal record is re-entered
        through the scheduler and re-admitted from prompt + emitted
        prefix — "accepted" survives gateway death too, not just rank
        death."""
        self._recover_from_journal()
        if self.spec:
            live = self._live_ranks()
            if not live:
                raise RuntimeError("no live ranks to serve on")
            resps = self.comm.send_to_ranks(
                live, "execute",
                {"code": self.spec, "target_ranks": live},
                tenant=self.tenant, timeout=spec_timeout)
            for r, m in resps.items():
                err = (m.data or {}).get("error")
                if err:
                    raise RuntimeError(
                        f"model spec failed on rank {r}: {err}")
        self._driver = threading.Thread(target=self._run,
                                        name=f"nbd-serve-{self.tenant}",
                                        daemon=True)
        self._driver.start()
        self._record("serve_start", tenant=self.tenant,
                     max_batch=self.max_batch, max_len=self.max_len)
        return self.describe()

    def _recover_from_journal(self) -> None:
        """Re-enter every journaled-but-unfinished request from a
        previous serving plane's journal (same run dir + tenant).
        Each one goes back through the scheduler under its original
        submitter and priority, carries its already-emitted prefix
        (the offset dedup takes it from there), and counts as a
        replay.  Over-budget admission at recovery (a smaller queue
        than the previous plane's) sheds with a delivered verdict —
        never silently.  Migrated tenants' staged journals (ISSUE 16)
        are adopted right after."""
        state = ServeJournal.load(self.journal.path)
        recovered = self._readmit_state(state) if state else 0
        if recovered:
            self._record("serve_recovered", n=recovered)
            obs_metrics.registry().counter(
                "nbd_serve_recovered_total",
                "journaled requests re-entered by a successor "
                "serving plane", {"tenant": self.tenant}).inc(recovered)
            self._wake.set()
        self._consume_migrated(set(state))

    def _readmit_state(self, state: dict) -> int:
        """Re-enter loaded journal state; returns how many unfinished
        requests were re-admitted."""
        recovered = 0
        for rid, r in sorted(state.items()):
            # Keep fresh rids past every journaled one, finished or
            # not — reusing a rid would cross-wire journal streams.
            try:
                n = int(rid.lstrip("r"))
            except ValueError:
                n = -1
            with self._lock:
                self._next_rid = max(self._next_rid, n + 1)
                known = rid in self._reqs
            if known:
                continue
            if r["done"] is not None \
                    or len(r["tokens"]) >= r["max_new"]:
                continue
            self.obs.begin(rid, r["tenant"] or "unknown")
            ticket = self.sched.submit(r["tenant"] or "unknown", rid,
                                       r["prio"])
            req = _Req(rid, r["tenant"], list(r["prompt"]),
                       r["max_new"], r["prio"], ticket)
            req.tokens = list(r["tokens"])
            req.replay = True
            with self._lock:
                self._reqs[rid] = req
                self.accepted += 1
            recovered += 1
            if ticket.verdict.get("status") in ("shed", "rejected"):
                self._finish(req, SHED_V,
                             error="journaled request shed at "
                                   "recovery: the restarted serving "
                                   "plane's admission bounds could "
                                   "not re-admit it")
        return recovered

    def _consume_migrated(self, own_rids: set) -> None:
        """Adopt migrated tenants' staged journals (written by
        ``tenant_import``): re-journal their records into OUR journal
        first — durability must transfer before the stash is deleted —
        then re-admit the unfinished ones and remove the stash.  A
        crash between re-journal and unlink leaves a stash whose rids
        are already in our journal; the collision skip makes the next
        consume a no-op, so adoption happens at most once.  Stated
        limit: rids are per-pool monotonic (``r{n}``), so a migrated
        rid the destination ALREADY used names a different request —
        those are skipped and flight-recorded, never cross-wired."""
        try:
            names = sorted(os.listdir(self.run_dir))
        except OSError:
            return
        adopted = 0
        for fn in names:
            if not fn.startswith(_MIGRATED_PREFIX) \
                    or not fn.endswith(".jsonl"):
                continue
            path = os.path.join(self.run_dir, fn)
            state = ServeJournal.load(path)
            fresh = {rid: r for rid, r in state.items()
                     if rid not in own_rids}
            if len(fresh) < len(state):
                self._record("serve_migrated_rid_collision",
                             stash=fn, n=len(state) - len(fresh))
            for rid, r in sorted(fresh.items()):
                self.journal.accept(rid, r["tenant"] or "unknown",
                                    r["prompt"], r["max_new"],
                                    r["prio"])
                if r["tokens"]:
                    self.journal.emit(rid, 0, r["tokens"])
                if r["done"] is not None:
                    self.journal.done(rid, r["done"])
                own_rids.add(rid)
            adopted += self._readmit_state(fresh)
            try:
                os.remove(path)
            except OSError:
                pass
        if adopted:
            self._record("serve_migrated_adopted", n=adopted)
            obs_metrics.registry().counter(
                "nbd_serve_migrated_total",
                "migrated journal requests adopted by a destination "
                "serving plane", {"tenant": self.tenant}).inc(adopted)
            self._wake.set()

    def pause(self, *, timeout: float = 30.0) -> bool:
        """Arm the serving half of the resize drain barrier: no new
        decode tick starts, and this call returns once the in-flight
        tick (if any) has finished — True when the driver is known
        parked, False on timeout (the resize proceeds anyway; a tick
        caught mid-step redelivers into the new epoch and is fenced
        by the ``ep`` header like any stale frame).  Submits keep
        being ACCEPTED and journaled throughout — accepted requests
        are never lost to a resize, they just wait for the new
        fleet."""
        self._pause.set()
        self._wake.set()
        if self._driver is None or not self._driver.is_alive():
            return True
        ok = self._tick_idle.wait(timeout)
        self._record("serve_paused", drained=ok)
        return ok

    def resume_after_resize(self, world_size: int) -> None:
        """The fleet was resized (new epoch, new world): retarget the
        driver.  Everything placed on the old fleet is un-placed and
        marked for journal replay — the re-admission path that already
        carries requests across rank death and gateway restarts — and
        the model spec is re-run on the new fleet so serve_open finds
        its params (the resized-in workers' namespaces start empty;
        the persistent compile cache is what makes this re-seed warm
        instead of a cold compile)."""
        with self._lock:
            self.world_size = int(world_size)
            self._open.clear()
            self._avoid.clear()
            for r in self._reqs.values():
                r.rank = None
                if r.state == ACCEPTED and r.placed:
                    r.placed = False
                    r.replay = True
        if self.spec:
            live = self._live_ranks()
            if live:
                try:
                    resps = self.comm.send_to_ranks(
                        live, "execute",
                        {"code": self.spec, "target_ranks": live},
                        tenant=self.tenant, timeout=600.0)
                    errs = {r: (m.data or {}).get("error")
                            for r, m in resps.items()
                            if (m.data or {}).get("error")}
                    if errs:
                        self._record("serve_reseed_error", errors={
                            str(r): str(e)[:200]
                            for r, e in errs.items()})
                except Exception as e:
                    # The driver's serve_open path will keep retrying
                    # (and avoiding failed ranks); the journal holds
                    # every accepted request meanwhile.
                    self._record("serve_reseed_error",
                                 error=f"{type(e).__name__}: {e}")
        self._pause.clear()
        self._wake.set()
        self._record("serve_resized", world_size=world_size)

    def stop(self, *, close_workers: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        d = self._driver
        if d is not None and d is not threading.current_thread():
            d.join(timeout=max(5.0, self.step_timeout + 5.0))
        if close_workers:
            try:
                self.comm.post(self._live_ranks(), "serve_close",
                               {"tenant": self.tenant})
            except Exception:
                pass
        self.journal.close()
        self._record("serve_stop", tenant=self.tenant)

    # ------------------------------------------------------------------
    # ingress (tenant-plane threads)

    def submit(self, tenant_name: str, prompt, max_new: int, *,
               priority: int = 0) -> dict:
        """Admit one generation request; returns its explicit verdict.

        ``{"status": "accepted", "rid": ..., "queued": bool,
        "position": n?}`` — journaled, will decode;
        ``{"status": "shed"| "rejected", ...}`` — refused with the
        reason; nothing journaled.  Accepted-then-shed (a LATER burst
        pushed this request out of the bounded queue) is delivered as
        a terminal shed verdict through the mailbox discipline."""
        reg = obs_metrics.registry()
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError):
            return {"status": REJECTED_V, "reason": "bad-prompt",
                    "error": "prompt must be a list of token ids"}
        if not prompt or max_new < 1:
            return {"status": REJECTED_V, "reason": "bad-prompt",
                    "error": "prompt must be non-empty and "
                             "max_new_tokens >= 1"}
        if len(prompt) + int(max_new) > self.max_len:
            return {"status": REJECTED_V, "reason": "too-long",
                    "error": f"prompt ({len(prompt)}) + max_new_tokens "
                             f"({max_new}) exceeds the server's "
                             f"max_len {self.max_len}"}
        # Block-capacity admission (ISSUE 17): a request whose
        # worst-case KV footprint exceeds a whole rank's block pool can
        # NEVER be placed — refuse it now with an explicit verdict
        # instead of letting it starve in the queue forever.
        need = blocks_needed(len(prompt) + int(max_new),
                             self.kv_block_tokens)
        if need > self.kv_blocks_per_rank:
            # Capacity decision on the flight ring (ISSUE 18): the
            # allocator state that drove it is static here — no rank
            # can EVER hold this footprint.
            self._record("serve_kv_reject", tenant=tenant_name,
                         need_blocks=need,
                         kv_blocks_per_rank=self.kv_blocks_per_rank,
                         prompt_len=len(prompt), max_new=int(max_new))
            return {"status": REJECTED_V, "reason": "kv-exhausted",
                    "error": f"request needs {need} KV blocks "
                             f"({len(prompt)} prompt + {max_new} new "
                             f"tokens at {self.kv_block_tokens}/block) "
                             f"but each decode rank has only "
                             f"{self.kv_blocks_per_rank} blocks"}
        with self._lock:
            rid = f"r{self._next_rid}"
            self._next_rid += 1
        self.obs.begin(rid, tenant_name)
        ticket = self.sched.submit(tenant_name, rid, int(priority))
        v = ticket.verdict
        if v["status"] == "rejected":
            with self._lock:
                self.rejected += 1
            reg.counter("nbd_serve_requests_total",
                        "serving requests by admission verdict",
                        {"tenant": self.tenant,
                         "verdict": "rejected"}).inc()
            self.obs.drop(rid)
            return {"status": REJECTED_V,
                    "reason": v.get("reason", "rejected"),
                    "error": f"request rejected: "
                             f"{v.get('reason', 'admission')} — wait "
                             f"for in-flight requests to finish"}
        if v["status"] == "shed":
            with self._lock:
                self.shed += 1
            reg.counter("nbd_serve_requests_total",
                        "serving requests by admission verdict",
                        {"tenant": self.tenant, "verdict": "shed"}).inc()
            self.obs.drop(rid)
            self._shed_victims(v.get("victims") or ())
            return {"status": SHED_V, "reason": "overload",
                    "error": "request shed under overload: the serve "
                             "queue was full and this was the lowest-"
                             "priority pending request — retry, or "
                             "raise priority"}
        # Accepted (dispatch = a KV slot is free now; queued = waits
        # for one).  Journal BEFORE the verdict returns: "accepted"
        # must mean "survives a rank death".
        req = _Req(rid, tenant_name, prompt, int(max_new),
                   int(priority), ticket)
        self.journal.accept(rid, tenant_name, prompt, int(max_new),
                            int(priority))
        with self._lock:
            self._reqs[rid] = req
            self.accepted += 1
        reg.counter("nbd_serve_requests_total",
                    "serving requests by admission verdict",
                    {"tenant": self.tenant, "verdict": "accepted"}).inc()
        self.obs.note_admit(rid)
        self._record("serve_accept", rid=rid, tenant=tenant_name,
                     queued=v["status"] == "queued")
        self._shed_victims(v.get("victims") or ())
        # A CONCURRENT submit may have shed this ticket as a victim in
        # the window before the _reqs insertion above — its
        # _shed_victims found nothing to finish, which would leave the
        # request ACCEPTED-forever (and the driver spinning on work it
        # can never admit).  Re-check after insertion; _finish is
        # idempotent under the lock, so racing a late victim pass is
        # safe.
        if req.ticket.state == TICKET_SHED:
            self._finish(req, SHED_V,
                         error="request shed under overload after "
                               "acceptance: a concurrent burst filled "
                               "the serve queue and this was the "
                               "lowest-priority pending request")
        self._wake.set()
        out = {"status": ACCEPTED, "rid": rid,
               "queued": v["status"] == "queued"}
        if v.get("position") is not None:
            out["position"] = v["position"]
        return out

    def _shed_victims(self, victims) -> None:
        """An admission round shed OTHER pending requests: finish them
        with a terminal shed verdict (their submitters already hold an
        'accepted' — the shed must be delivered, not silent)."""
        for vic in victims:
            rid = vic.get("msg_id")
            with self._lock:
                req = self._reqs.get(rid)
                if req is None or req.state != ACCEPTED:
                    continue
            self._finish(req, SHED_V,
                         error="request shed under overload after "
                               "acceptance: a later burst filled the "
                               "serve queue and this was the lowest-"
                               "priority pending request")

    def result(self, rid: str) -> dict:
        with self._lock:
            req = self._reqs.get(rid)
            if req is None:
                return {"status": "unknown",
                        "error": f"unknown request {rid!r}"}
            return {"status": req.state, "rid": rid,
                    "tokens": list(req.tokens),
                    "done": req.state != ACCEPTED,
                    **({"error": req.error} if req.error else {})}

    def stream(self, rid: str, from_offset: int = 0) -> dict:
        """The reattach-resume path: everything past the client's last
        acked offset, plus done/status so a finished stream closes.

        A *resume* is counted at most once per request, and only when
        the read actually replays tokens the caller did not have
        (``from_offset`` strictly inside the stream) — an incremental
        polling loop that stays caught up never inflates the
        counter."""
        with self._lock:
            req = self._reqs.get(rid)
            if req is None:
                return {"status": "unknown",
                        "error": f"unknown request {rid!r}"}
            o = max(0, int(from_offset))
            resumed = (0 < o < len(req.tokens)
                       and not req.stream_resumed)
            if resumed:
                req.stream_resumed = True
                self.resumed += 1
            toks = list(req.tokens[o:])
            done = req.state != ACCEPTED
            st = req.state
        if resumed:
            obs_metrics.registry().counter(
                "nbd_serve_resumed_total",
                "token streams resumed from a client-acked offset "
                "(reattach mid-generation)",
                {"tenant": self.tenant}).inc()
        return {"status": st, "rid": rid, "offset": o, "tokens": toks,
                "done": done}

    @staticmethod
    def _slo_summary(entries) -> dict:
        """p50/p99 (milliseconds) per SLO metric, overall and per
        submitting tenant, from the recent-completions ring."""
        def stats(vals):
            sv = sorted(v for v in vals if v is not None)
            if not sv:
                return None
            return {"p50": round(obs_latency.percentile(sv, 0.50)
                                 * 1e3, 3),
                    "p99": round(obs_latency.percentile(sv, 0.99)
                                 * 1e3, 3),
                    "n": len(sv)}

        def block(rows):
            out = {}
            for k in ("ttft", "tpot", "queue", "e2e"):
                st = stats([r.get(k) for r in rows])
                if st is not None:
                    out[k + "_ms"] = st
            return out

        if not entries:
            return {}
        out = block(entries)
        tenants = sorted({r["tenant"] for r in entries})
        if len(tenants) > 1:
            out["tenants"] = {
                t: block([r for r in entries if r["tenant"] == t])
                for t in tenants}
        return out

    def describe(self) -> dict:
        with self._lock:
            slo_entries = list(self._slo)
            active = sum(1 for r in self._reqs.values()
                         if r.state == ACCEPTED and r.placed)
            pending = sum(1 for r in self._reqs.values()
                          if r.state == ACCEPTED and not r.placed)
            # "decode_rank" stays the single headline rank (the
            # highest open one) for every pre-ISSUE-17 surface;
            # "decode_ranks"/"ranks" carry the multi-rank truth.
            d = {"tenant": self.tenant,
                 "decode_rank": max(self._open) if self._open else None,
                 "decode_ranks": sorted(self._open),
                 "accepted": self.accepted, "completed": self.completed,
                 "shed": self.shed, "rejected": self.rejected,
                 "replayed": self.replayed, "resumed": self.resumed,
                 "failovers": self.failovers,
                 "step_retries": self.step_retries,
                 "dup_dropped": self.dup_dropped,
                 "tokens_total": self.tokens_total,
                 "decoding": active, "pending": pending,
                 "slots": self.max_batch, "max_len": self.max_len,
                 "last_error": self.last_error}
            ranks = {}
            for rank in sorted(self._open):
                alloc = self._open[rank]
                placed = sum(1 for r in self._reqs.values()
                             if r.state == ACCEPTED and r.placed
                             and r.rank == rank)
                ranks[str(rank)] = {"placed": placed,
                                    "kv_used": alloc.used_blocks,
                                    "kv_free": alloc.free_blocks,
                                    "frag": alloc.largest_free_run()}
            d["ranks"] = ranks
            # Per-SUBMITTING-tenant block counts (%dist_serve status).
            by_tenant: dict[str, int] = {}
            used = free = 0
            for alloc in self._open.values():
                used += alloc.used_blocks
                free += alloc.free_blocks
                for rid, n in alloc.snapshot()["owners"].items():
                    req = self._reqs.get(rid)
                    t = req.tenant if req is not None else "unknown"
                    by_tenant[t] = by_tenant.get(t, 0) + n
            d["kv"] = {"block_tokens": self.kv_block_tokens,
                       "blocks_per_rank": self.kv_blocks_per_rank,
                       "used": used, "free": free,
                       "tenants": by_tenant}
        d["scheduler"] = self.sched.snapshot()
        d["slo"] = self._slo_summary(slo_entries)
        # Serving observatory (ISSUE 18): stage-attribution summary +
        # recent records (the %dist_serve lat table/waterfall source)
        # and per-tick utilization for the status surfaces.
        d["lat"] = self.obs.status_block(records=64)
        return d

    def forget_tenant(self, name: str) -> None:
        """Mirror the pool scheduler's eviction hygiene for the serve
        scheduler's per-submitter stats."""
        try:
            self.sched.forget_tenant(name)
        except Exception:
            pass

    # ------------------------------------------------------------------
    # decode driver (one thread)

    def _record(self, event: str, **kw) -> None:
        fl = self._flight
        if fl is not None:
            try:
                fl.record(event, **kw)
            except Exception:
                pass

    def _live_ranks(self) -> list[int]:
        try:
            dead = self.comm.dead_ranks()
        except Exception:
            dead = set()
        return sorted(set(range(self.world_size)) - set(dead))

    def _pick_ranks(self) -> list[int]:
        """The decode ranks: the HIGHEST ``decode_ranks`` live ranks
        (0 = every live rank), highest first.  Highest, not lowest, on
        purpose — rank 0 hosts the jax.distributed coordination
        service, whose death kills every other rank's process (that
        failure class is the supervisor's full-world heal, not a
        serving failover), so the decode fleet fills from the top and
        touches rank 0 last.  Ranks whose serve_open recently failed
        are skipped until their backoff expires; with every live rank
        avoided, the backoff is overridden (retrying beats
        stalling)."""
        live = self._live_ranks()
        if not live:
            return []
        now = time.monotonic()
        with self._lock:
            usable = [r for r in live
                      if self._avoid.get(r, 0.0) <= now]
        pool = usable or live
        k = self.decode_ranks if self.decode_ranks > 0 else len(pool)
        return sorted(pool, reverse=True)[:max(1, min(k, len(pool)))]

    def _has_work_locked(self) -> bool:
        return any(r.state == ACCEPTED for r in self._reqs.values())

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._pause.is_set():
                # Drained: no tick starts until resume_after_resize.
                self._wake.wait(timeout=0.2)
                self._wake.clear()
                continue
            with self._lock:
                work = self._has_work_locked()
            if not work:
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            self._tick_idle.clear()
            try:
                try:
                    self._tick()
                finally:
                    self._tick_idle.set()
            except _RankLost as e:
                self._on_rank_lost(e.rank)
            except Exception as e:  # never kill the driver
                with self._lock:
                    self.last_error = f"{type(e).__name__}: {e}"
                self._record("serve_driver_error",
                             error=self.last_error)
                if self._stop.wait(0.5):
                    return

    def _unbind_rank_locked(self, rank: int | None) -> None:
        """Detach every request bound to ``rank`` (None = any rank):
        accepted-and-placed ones go back to the journal-replay path;
        finished-but-unreleased ones are marked released — the rank's
        server is gone (or will be reset), so there is nothing left to
        release worker-side.  The rank's accounting allocator is
        dropped with the rank, so no per-request free is needed."""
        for r in self._reqs.values():
            if rank is not None and r.rank != rank:
                continue
            if r.state == ACCEPTED and r.placed:
                r.placed = False
                r.replay = True
            elif r.placed and not r.released:
                r.released = True
            r.rank = None

    def _on_rank_lost(self, rank: int | None = None) -> None:
        """A decode rank died (or stopped answering within the retry
        budget): un-place ITS in-flight requests — the next tick
        re-opens capacity on the remaining live ranks and re-admits
        each one from its journaled prompt + emitted prefix.  With
        ``rank=None`` (a legacy caller, or a loss detected before any
        placement) every open rank is dropped."""
        with self._lock:
            if rank is None:
                lost = sorted(self._open)
                snaps = {str(r): self._open[r].snapshot()
                         for r in lost}
                self._open.clear()
            else:
                gone = self._open.pop(rank, None)
                snaps = {str(rank): gone.snapshot()} \
                    if gone is not None else {}
                lost = [rank]
            self.failovers += 1
            self._unbind_rank_locked(rank)
        obs_metrics.registry().counter(
            "nbd_serve_failovers_total",
            "decode-rank failovers (rank death or step-retry budget "
            "exhausted)", {"tenant": self.tenant}).inc()
        self._record("serve_failover", lost_ranks=lost, kv=snaps)
        for lr in lost:
            # Best-effort: if the rank is merely unreachable (not
            # dead), free its now-orphaned DecodeServer.
            try:
                self.comm.post([lr], "serve_close",
                               {"tenant": self.tenant})
            except Exception:
                pass
        self._stop.wait(0.2)

    def _retire_rank(self, rank: int) -> None:
        """An open rank fell out of the target set (a higher rank
        healed back, or the fleet shrank): move its requests to the
        replay path and close its server.  Not a failover — the rank
        is healthy, just no longer chosen."""
        with self._lock:
            gone = self._open.pop(rank, None)
            if gone is None:
                return
            snap = gone.snapshot()
            self._unbind_rank_locked(rank)
        try:
            self.comm.post([rank], "serve_close",
                           {"tenant": self.tenant})
        except Exception:
            pass
        self._record("serve_rank_retired", rank=rank, kv=snap)

    def _open_on(self, rank: int) -> None:
        resp = self.comm.send_to_ranks(
            [rank], "serve_open",
            {"tenant": self.tenant, "params": self.params_name,
             "cfg": self.cfg_name, "max_batch": self.max_batch,
             "max_len": self.max_len, "pad_to": self.pad_to,
             "eos_id": self.eos_id, "temperature": self.temperature,
             "kv_block_tokens": self.kv_block_tokens,
             "kv_blocks": self.kv_blocks_per_rank,
             "prefill_chunk": self.prefill_chunk,
             "kv_quantized": self.kv_quantized,
             "reset": True},
            tenant=self.tenant, timeout=self.step_timeout)
        err = (resp[rank].data or {}).get("error")
        if err:
            # Back off this rank so the next tick can fail over to a
            # lower live rank instead of wedging on one broken open
            # (e.g. a rank that reconnected after the model spec ran).
            with self._lock:
                self._avoid[rank] = time.monotonic() + 60.0
            raise RuntimeError(f"serve_open failed on rank {rank}: "
                               f"{err}")
        with self._lock:
            # A fresh server has no placements or blocks: anything
            # that thought it lived on this rank must replay.
            self._unbind_rank_locked(rank)
            self._open[rank] = BlockAllocator(self.kv_blocks_per_rank,
                                              self.kv_block_tokens)
            self._avoid.pop(rank, None)
        self._record("serve_open", rank=rank)

    def _place_admits_locked(self) -> tuple[dict, dict, list]:
        """Per-rank placement of requests holding an ACTIVE scheduler
        ticket but not yet placed — first admissions AND journal
        re-admissions (the latter carry the emitted prefix).

        Each request reserves its WORST-CASE block count
        (``ceil((prompt + max_new) / block_tokens)`` of the ORIGINAL
        prompt/budget — invariant across replays, so a re-admission
        reserves exactly what the first placement did) on the open
        rank with a free sequence slot and the most free blocks.  A
        request no rank can hold right now simply waits — blocks free
        as peers finish, and the ticket stays ACTIVE.

        Returns ``(admits, release, qwaits, events)``: per-rank admit
        payload lists, per-rank release rid lists, ``(tenant,
        queue_wait_s)`` for each FIRST placement — observed into the
        SLO histograms by the caller, outside the lock — and flight
        events (placement / defer decisions with the allocator
        snapshots that drove them, ISSUE 18) the caller records
        outside the lock."""
        admits: dict[int, list[dict]] = {}
        release: dict[int, list[str]] = {}
        qwaits = []
        events: list[dict] = []
        deferred: list[str] = []
        replays = 0
        now = time.time()
        placed_n = {rank: 0 for rank in self._open}
        for r in self._reqs.values():
            if r.state == ACCEPTED and r.placed \
                    and r.rank in placed_n:
                placed_n[r.rank] += 1
        for r in self._reqs.values():
            if r.state != ACCEPTED or r.placed \
                    or r.ticket.state != ACTIVE:
                continue
            need = blocks_needed(len(r.prompt) + r.max_new,
                                 self.kv_block_tokens)
            best = None
            for rank, alloc in self._open.items():
                if placed_n.get(rank, 0) >= self.max_batch \
                        or alloc.free_blocks < need:
                    continue
                if best is None or alloc.free_blocks \
                        > self._open[best].free_blocks:
                    best = rank
            if best is None:
                # Park: the ticket stays ACTIVE and blocks free as
                # peers finish.  The defer decision reaches the flight
                # ring (once per episode) with the occupancy that
                # drove it.
                deferred.append(r.rid)
                continue
            t_alloc0 = time.perf_counter()
            self._open[best].alloc(r.rid, need)
            kv_alloc_s = time.perf_counter() - t_alloc0
            placed_n[best] += 1
            r.rank = best
            r.base = len(r.tokens)
            r.placed = True
            pf_chunk = self.prefill_chunk or self.max_len
            self.obs.note_placed(
                r.rid, best, kv_alloc_s=kv_alloc_s, need_blocks=need,
                pf_total=-(-len(r.prompt) // max(1, pf_chunk)), t=now)
            events.append({"event": "serve_place", "rid": r.rid,
                           "rank": best, "need_blocks": need,
                           "kv_free": self._open[best].free_blocks,
                           "replay": bool(r.replay)})
            if r.placed_ts is None:
                # First placement only: a failover re-admission is a
                # heal, not queue wait.
                r.placed_ts = now
                qwaits.append((r.tenant, now - r.submitted_ts))
            if r.replay:
                r.replay = False
                r.resumes += 1
                self.replayed += 1
                replays += 1
            admits.setdefault(best, []).append(
                {"rid": r.rid,
                 "prompt": list(r.prompt) + list(r.tokens),
                 "max_new": r.max_new - r.base})
        for r in self._reqs.values():
            if r.state != ACCEPTED and r.placed and not r.released \
                    and r.rank in self._open:
                r.released = True
                release.setdefault(r.rank, []).append(r.rid)
        if replays:
            obs_metrics.registry().counter(
                "nbd_serve_replayed_total",
                "requests re-admitted from the journal after a "
                "failover (re-prefill from prompt + emitted prefix)",
                {"tenant": self.tenant}).inc(replays)
        dset = frozenset(deferred)
        if dset and dset != self._last_deferred:
            events.append({
                "event": "serve_defer", "rids": sorted(dset),
                "kv": {str(rank): {
                    "free": a.free_blocks,
                    "largest_run": a.largest_free_run()}
                    for rank, a in self._open.items()}})
        self._last_deferred = dset
        return admits, release, qwaits, events

    def _tick(self) -> None:
        target = self._pick_ranks()
        if not target:
            # Whole pool dead/unreachable: keep the journal and WAIT
            # for a heal — accepted requests survive by contract.  A
            # wait state, not a failover: any prior placement was
            # already un-placed by the rank-lost path.
            self._stop.wait(1.0)
            return
        with self._lock:
            stale = [r for r in self._open if r not in target]
        for rank in stale:
            self._retire_rank(rank)
        for rank in target:
            with self._lock:
                if rank in self._open:
                    continue
            self._open_on(rank)
        with self._lock:
            admits, release, qwaits, events = \
                self._place_admits_locked()
            busy = {r.rank for r in self._reqs.values()
                    if r.state == ACCEPTED and r.placed
                    and r.rank is not None}
            ticks = sorted((set(admits) | set(release) | busy)
                           & set(self._open))
        for ev in events:
            self._record(**ev)
        for tenant_name, wait in qwaits:
            self._slo_hist(
                "nbd_serve_queue_wait_seconds",
                "serving queue wait: submit → first KV-slot placement",
                tenant_name).observe(wait)
        if not ticks:
            self._update_kv_gauges()
            return
        payloads = {rank: {"tenant": self.tenant,
                           "admit": admits.get(rank, []),
                           "release": release.get(rank, []),
                           "steps": self.steps}
                    for rank in ticks}
        replies, lost = self._step_all(payloads)
        for rank in ticks:
            data = replies.get(rank)
            if data is None:
                continue
            if data.get("error"):
                # Whole-step refusal (e.g. the rank lost its serving
                # state): treat like a dead rank — re-open and
                # re-admit from the journal instead of spinning.
                self._record("serve_step_refused", rank=rank,
                             error=str(data["error"])[:200])
                lost.append((rank, str(data["error"])))
                continue
            self._apply_reply(data, rank=rank)
        self._note_tick_util(ticks, replies)
        self._update_kv_gauges()
        if lost:
            # Every received reply above is already applied, so the
            # failover surgery is scoped to the lost rank alone.  With
            # several lost in one tick the rest re-raise next tick.
            rank, why = lost[0]
            raise _RankLost(why, rank=rank)

    def _step_all(self, payloads: dict[int, dict]
                  ) -> tuple[dict[int, dict], list]:
        """One serve_step round per rank.  When the comm supports the
        submission/completion split (ISSUE 14) and more than one rank
        is ticking, every step is pre-submitted so the ranks decode
        CONCURRENTLY — the multi-rank throughput claim — then each
        handle is awaited (wait() drives the same-msg-id redelivery
        schedule).  Otherwise (unit-test fakes, single rank) the
        legacy sequential path runs unchanged.

        Returns ``(replies, lost)`` — every reply that arrived, plus
        ``(rank, reason)`` for ranks that died or exhausted their
        retry budget.  Replies are always harvested before the caller
        surfaces a loss: an abandoned reply would desynchronize the
        emission offsets of the SURVIVING ranks' requests."""
        from ..messaging.coordinator import WorkerDied
        replies: dict[int, dict] = {}
        lost: list = []
        if len(payloads) > 1 and hasattr(self.comm, "submit"):
            handles = {}
            for rank, payload in payloads.items():
                try:
                    handles[rank] = self.comm.submit(
                        [rank], "serve_step", payload,
                        tenant=self.tenant, msg_id=uuid.uuid4().hex,
                        timeout=self.step_timeout)
                except WorkerDied as e:
                    lost.append((rank, str(e)))
                except Exception as e:
                    self._note_step_retry(rank, 0, e)
                    lost.append((rank, f"submit failed: {e}"))
            for rank, h in handles.items():
                try:
                    resp = h.wait()
                    replies[rank] = resp[rank].data or {}
                except WorkerDied as e:
                    lost.append((rank, str(e)))
                except Exception as e:
                    self._note_step_retry(rank, 1, e)
                    with self._lock:
                        self._avoid[rank] = time.monotonic() + 60.0
                    lost.append((rank,
                                 f"step retry budget exhausted: {e}"))
            return replies, lost
        for rank, payload in payloads.items():
            try:
                replies[rank] = self._send_step(rank, payload)
            except _RankLost as e:
                lost.append((rank, str(e)))
        return replies, lost

    def _note_step_retry(self, rank: int, attempt: int,
                         e: Exception) -> None:
        with self._lock:
            self.step_retries += 1
        obs_metrics.registry().counter(
            "nbd_serve_step_retries_total",
            "serve_step dispatches redelivered after a "
            "timeout (same msg_id; replay-cache dedup)",
            {"tenant": self.tenant}).inc()
        self._record("serve_step_retry", rank=rank,
                     attempt=attempt + 1,
                     error=f"{type(e).__name__}: {e}")

    def _note_tick_util(self, ticks, replies) -> None:
        """One utilization sample per decode tick (ISSUE 18): batch
        fill / KV occupancy / fragmentation from the gateway-side
        allocator mirrors, prefill-vs-decode token split and worker
        park depth from the serve_step replies' ``tick`` block."""
        pf_toks = dc_toks = 0
        pending: dict[int, int] = {}
        for rank in ticks:
            data = replies.get(rank) or {}
            tk = data.get("tick") or {}
            pf_toks += int(tk.get("pf") or 0)
            dc_toks += int(tk.get("dc") or 0)
            if data.get("pending") is not None:
                pending[rank] = int(data["pending"])
        util_ranks: dict[int, dict] = {}
        with self._lock:
            placed_by: dict[int, int] = {}
            backlog = 0
            for r in self._reqs.values():
                if r.state != ACCEPTED:
                    continue
                if r.placed and r.rank is not None:
                    placed_by[r.rank] = placed_by.get(r.rank, 0) + 1
                elif not r.placed:
                    backlog += 1
            for rank, alloc in self._open.items():
                util_ranks[rank] = {
                    "placed": placed_by.get(rank, 0),
                    "slots": self.max_batch,
                    "kv_used": alloc.used_blocks,
                    "kv_free": alloc.free_blocks,
                    "frag": alloc.largest_free_run(),
                    **({"pending": pending[rank]}
                       if rank in pending else {}),
                }
        self.obs.note_util(ranks=util_ranks, prefill_toks=pf_toks,
                           decode_toks=dc_toks, backlog=backlog,
                           tenant=self.tenant)

    def _update_kv_gauges(self) -> None:
        with self._lock:
            per_rank = {rank: (a.used_blocks, a.free_blocks)
                        for rank, a in self._open.items()}
        reg = obs_metrics.registry()
        # Aggregate series keep their pre-ISSUE-18 label shape
        # (rank="all") next to the new per-rank series; everything
        # carries the serving tenant, so tenant eviction's
        # remove_label_series("tenant", ...) retires rank series too.
        used = sum(u for u, _ in per_rank.values())
        free = sum(f for _, f in per_rank.values())
        reg.gauge("nbd_kv_blocks_used",
                  "KV cache blocks allocated per open decode rank "
                  "(rank=\"all\" aggregates the fleet)",
                  {"tenant": self.tenant, "rank": "all"}).set(used)
        reg.gauge("nbd_kv_blocks_free",
                  "KV cache blocks free per open decode rank "
                  "(rank=\"all\" aggregates the fleet)",
                  {"tenant": self.tenant, "rank": "all"}).set(free)
        for rank, (u, f) in per_rank.items():
            reg.gauge("nbd_kv_blocks_used",
                      "KV cache blocks allocated per open decode rank "
                      "(rank=\"all\" aggregates the fleet)",
                      {"tenant": self.tenant,
                       "rank": str(rank)}).set(u)
            reg.gauge("nbd_kv_blocks_free",
                      "KV cache blocks free per open decode rank "
                      "(rank=\"all\" aggregates the fleet)",
                      {"tenant": self.tenant,
                       "rank": str(rank)}).set(f)
        # A retired/lost rank's last gauge value must not linger as a
        # live-looking series: zero it the tick after it closes.  (The
        # series itself is retired with the tenant — never via a rank-
        # label sweep, which would hit other metrics' rank series.)
        stale = self._gauged_ranks - set(per_rank)
        for rank in stale:
            reg.gauge("nbd_kv_blocks_used",
                      "KV cache blocks allocated per open decode rank "
                      "(rank=\"all\" aggregates the fleet)",
                      {"tenant": self.tenant,
                       "rank": str(rank)}).set(0)
            reg.gauge("nbd_kv_blocks_free",
                      "KV cache blocks free per open decode rank "
                      "(rank=\"all\" aggregates the fleet)",
                      {"tenant": self.tenant,
                       "rank": str(rank)}).set(0)
        self._gauged_ranks = set(per_rank)

    def _send_step(self, rank: int, payload: dict) -> dict:
        """One serve_step round trip, redelivered under the SAME
        message id on timeouts (the worker replay cache answers a
        request that already ran — decode never double-steps).  A dead
        rank, or a rank that exhausts the retry budget, raises
        :class:`_RankLost` for the failover path."""
        from ..messaging.coordinator import WorkerDied
        mid = uuid.uuid4().hex
        last: Exception | None = None
        for attempt in range(3):
            try:
                resp = self.comm.send_to_ranks(
                    [rank], "serve_step", payload, tenant=self.tenant,
                    msg_id=mid, timeout=self.step_timeout)
                return resp[rank].data or {}
            except WorkerDied as e:
                raise _RankLost(str(e), rank=rank) from e
            except Exception as e:
                last = e
                self._note_step_retry(rank, attempt, e)
                if self._stop.is_set():
                    raise _RankLost("stopping", rank=rank) from e
        # Alive-but-unresponsive: it stays in the live set, so back it
        # off explicitly or the next tick would pick it right back.
        with self._lock:
            self._avoid[rank] = time.monotonic() + 60.0
        raise _RankLost(f"step retry budget exhausted: {last}",
                        rank=rank)

    def _apply_reply(self, data: dict,
                     rank: int | None = None) -> None:
        reg = obs_metrics.registry()
        emitted = data.get("emitted") or {}
        errors = data.get("errors") or {}
        # ISSUE 18 tick telemetry: the worker's wall clock at reply
        # time (clock-corrected per rank inside the observatory), the
        # tick's compute time, and per-request chunked-prefill
        # progress.
        tick = data.get("tick") or {}
        t_worker = tick.get("now")
        step_s = float(tick.get("step_s") or 0.0)
        pf_chunk = max(1, self.prefill_chunk or self.max_len)
        for rid, wn in (data.get("pfp") or {}).items():
            try:
                written, total = int(wn[0]), int(wn[1])
            except (TypeError, ValueError, IndexError):
                continue
            self.obs.note_prefill_progress(
                rid, -(-written // pf_chunk), -(-total // pf_chunk))
        for rid, err in errors.items():
            with self._lock:
                req = self._reqs.get(rid)
            if req is not None and req.state == ACCEPTED:
                self._finish(req, FAILED, error=str(err))
        for rid, em in emitted.items():
            t_em0 = time.perf_counter()
            with self._lock:
                req = self._reqs.get(rid)
                if req is None or req.state != ACCEPTED:
                    continue
                have = len(req.tokens)
                base = req.base
            new, dup = merge_emission(have, base,
                                      int(em.get("o") or 0),
                                      list(em.get("t") or ()))
            if new is None:
                # A gap would corrupt the stream: fail the request
                # loudly rather than journal around a hole.
                self._finish(req, FAILED,
                             error="emission gap (protocol bug): "
                                   f"offset {base + int(em.get('o') or 0)} "
                                   f"past stream length {have}")
                continue
            if dup:
                with self._lock:
                    self.dup_dropped += dup
                reg.counter(
                    "nbd_serve_dup_dropped_total",
                    "tokens dropped by offset dedup (replayed or "
                    "redelivered emissions) — exactly-once delivery's "
                    "receipt", {"tenant": self.tenant}).inc(dup)
            if not new:
                continue
            self.journal.emit(rid, have, new)
            now = time.time()
            with self._lock:
                req.tokens.extend(new)
                self.tokens_total += len(new)
                done = (len(req.tokens) >= req.max_new
                        or (self.eos_id is not None
                            and self.eos_id in new))
                offset = have
                first = req.first_tok_ts is None
                if first:
                    req.first_tok_ts = now
                    req.first_batch = len(new)
                    ttft = now - req.submitted_ts
                else:
                    gap = ((now - req.last_emit_ts) / len(new)
                           if req.last_emit_ts is not None else None)
                req.last_emit_ts = now
            # Stage attribution (ISSUE 18): arrival + worker stamp
            # (clock-corrected inside), the tick's decode compute,
            # and the gateway's own emit-handling time so far.
            self.obs.note_emission(
                rid, rank if rank is not None else 0, len(new),
                t_recv=now, t_worker=t_worker,
                emit_s=time.perf_counter() - t_em0)
            self.obs.note_decode(rid, step_s)
            # SLO observations (outside the lock; per-SUBMITTING-
            # tenant labels so eviction retires the series).
            if first:
                self._slo_hist(
                    "nbd_serve_ttft_seconds",
                    "serving time-to-first-token (submit → first "
                    "emission delivered to the gateway)",
                    req.tenant).observe(ttft)
            elif gap is not None:
                # Mean per-token gap of this emission batch — the
                # inter-emission latency the client actually sees.
                self._slo_hist(
                    "nbd_serve_tpot_seconds",
                    "serving per-token inter-emission latency",
                    req.tenant).observe(gap)
            reg.counter("nbd_serve_tokens_total",
                        "generated tokens delivered",
                        {"tenant": self.tenant}).inc(len(new))
            if done:
                self._finish(req, COMPLETED)
            else:
                self._notify_tokens(req, offset, new)

    def _finish(self, req: _Req, status: str,
                error: str | None = None) -> None:
        """Terminal transition: journal the verdict, free the KV slot
        (promoting queued requests), and deliver the result
        delivered-or-parked-exactly-once."""
        slo = None
        with self._lock:
            if req.state != ACCEPTED:
                return
            req.state = status
            req.error = error
            req.finished_ts = time.time()
            # Return the request's KV blocks to its rank's accounting
            # pool.  One tick optimistic versus the worker (which
            # frees at the release in the NEXT serve_step); the
            # worker's DecodeServer parks an early re-admission as
            # pending until its own blocks free, so the skew never
            # corrupts — see the ctor comment on self._open.
            if req.rank is not None:
                alloc = self._open.get(req.rank)
                if alloc is not None:
                    alloc.free(req.rid)
            if status == COMPLETED:
                self.completed += 1
                # SLO record (seconds; None = not applicable): exact
                # recent percentiles for the status surfaces.
                extra_toks = len(req.tokens) - req.first_batch
                slo = {
                    "tenant": req.tenant,
                    "e2e": req.finished_ts - req.submitted_ts,
                    "queue": (req.placed_ts - req.submitted_ts
                              if req.placed_ts is not None else None),
                    "ttft": (req.first_tok_ts - req.submitted_ts
                             if req.first_tok_ts is not None
                             else None),
                    "tpot": ((req.last_emit_ts - req.first_tok_ts)
                             / extra_toks
                             if req.first_tok_ts is not None
                             and req.last_emit_ts is not None
                             and extra_toks > 0 else None),
                }
                self._slo.append(slo)
            elif status == SHED_V:
                self.shed += 1
        rec = self.obs.complete(
            req.rid, status, t_finish=req.finished_ts,
            tracer=getattr(self.comm, "tracer", None))
        if slo is not None and rec is not None \
                and rec.get("tpot_s") is not None:
            # Clock-corrected TPOT (worker emission stamps through
            # the per-rank offset estimator, clamped >= 0) supersedes
            # the gateway-arrival estimate when stamps were present.
            slo["tpot"] = rec["tpot_s"]
        if slo is not None:
            self._slo_hist(
                "nbd_serve_e2e_seconds",
                "serving end-to-end latency (submit → completed)",
                req.tenant).observe(slo["e2e"])
        self.journal.done(req.rid, status)
        self.sched.complete(req.rid)
        self._wake.set()
        obs_metrics.registry().counter(
            "nbd_serve_finished_total",
            "serving requests reaching a terminal state",
            {"tenant": self.tenant, "status": status}).inc()
        self._record("serve_finish", rid=req.rid, status=status,
                     n_tokens=len(req.tokens))
        # Terminal delivery through the mailbox discipline: parked for
        # exactly-once redelivery when the submitter has no kernel.
        # This (not a last serve_tokens notice) is the ONE terminal
        # signal, so a live client never sees the finish twice.
        reply = Message(
            msg_type="serve_done", msg_id=f"serve:{req.rid}",
            data={"status": status, "rid": req.rid,
                  "tokens": list(req.tokens),
                  **({"error": error} if error else {})})
        try:
            self._deliver(req.tenant, reply)
        except Exception:
            pass

    def _notify_tokens(self, req: _Req, offset: int,
                       toks: list[int]) -> None:
        """Best-effort live streaming: tokens push to the submitting
        tenant's connection as they land.  A lost notice costs
        nothing — the journaled stream is claimable via serve_stream
        (offset resume) and the terminal serve_done."""
        msg = Message(msg_type="serve_tokens",
                      data={"rid": req.rid, "o": offset, "t": toks})
        try:
            self._notify(req.tenant, msg)
        except Exception:
            pass
