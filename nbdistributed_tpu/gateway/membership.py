"""Pool membership, split out from scheduling (ISSUE 16).

The scheduler answers "which cell runs next"; this module answers
"which workers exist right now, and under which epoch did each join".
Keeping the two separate is what lets either change at runtime: a
resize rewrites membership while the scheduler merely pauses, and the
scheduler can shed/queue without ever caring that rank 3 is mid-drain.

``PoolMembership`` is pure bookkeeping — no IO, no spawning, no
clock of its own (callers pass ``now``) — so every transition is
unit-testable the way ``SkewDetector`` and the scheduler are.  The
daemon drives it through exactly three moves::

    begin_resize(target, new_epoch)   # all current ranks -> draining
    complete_resize(world, epoch)     # new active set, generation+1
    abort_resize()                    # drain failed: restore active

A resize in this design is an attach-like epoch bump with a re-seeded
mesh (the jax.distributed world and every rank's world_size are fixed
at spawn, so the fleet restarts at the new size under epoch N+1); the
membership record is what makes that visible as a *transition* instead
of a blink — ``%dist_pool status`` renders the generation and each
rank's join-epoch, and a half-completed resize shows ``draining``
ranks rather than dead ones.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

# Rank lifecycle states.  RETIRED records live only in the bounded
# history (describe() shows the live set plus the in-flight drain).
ACTIVE = "active"
DRAINING = "draining"
RETIRED = "retired"

_HISTORY_MAX = 16   # retired epoch-sets kept for postmortems


@dataclass
class RankRecord:
    """One worker's membership row."""
    rank: int
    join_epoch: int
    state: str = ACTIVE
    joined_ts: float = 0.0

    def describe(self) -> dict:
        return {"join_epoch": self.join_epoch, "state": self.state,
                "joined_ts": self.joined_ts}


class PoolMembership:
    """Generation-stamped ownership of the pooled fleet.

    Thread-safe: the daemon mutates it from the resize thread while
    ``status()`` reads it from the listener thread.  The generation
    bumps once per *completed* resize; the per-epoch worker sets
    (``epoch_set``) are what lets a late frame's ``ep`` header be
    explained — "that rank belonged to epoch 2, which retired at
    generation 3".
    """

    def __init__(self, world_size: int = 0, epoch: int = 1, *,
                 now: float = 0.0):
        self._lock = threading.Lock()
        self.generation = 1
        self._epoch = int(epoch)
        self._ranks: dict[int, RankRecord] = {}
        self._transition: dict | None = None
        self._history: list[dict] = []
        if world_size:
            self._install_locked(world_size, epoch, now)

    # -- internals (callers hold self._lock) ---------------------------

    def _install_locked(self, world_size: int, epoch: int,
                        now: float) -> None:
        self._epoch = int(epoch)
        self._ranks = {r: RankRecord(r, int(epoch), ACTIVE, now)
                       for r in range(world_size)}

    # -- transitions ---------------------------------------------------

    def begin_resize(self, target: int, new_epoch: int, *,
                     reason: str = "manual",
                     now: float = 0.0) -> dict:
        """Start a resize: every current rank enters ``draining`` and
        the in-flight transition is recorded (one at a time — a second
        begin while one is open raises, the daemon's resize lock should
        have prevented it)."""
        with self._lock:
            if self._transition is not None:
                raise RuntimeError(
                    f"resize already in flight: {self._transition}")
            for rec in self._ranks.values():
                rec.state = DRAINING
            self._transition = {
                "from_world": len(self._ranks),
                "to_world": int(target),
                "from_epoch": self._epoch,
                "to_epoch": int(new_epoch),
                "reason": reason, "started_ts": now,
            }
            return dict(self._transition)

    def complete_resize(self, world_size: int, epoch: int, *,
                        now: float = 0.0) -> int:
        """The new fleet is up: retire the old epoch-set into history,
        install the new active set, bump the generation.  Returns the
        new generation."""
        with self._lock:
            if self._ranks:
                self._history.append({
                    "epoch": self._epoch,
                    "generation": self.generation,
                    "ranks": sorted(self._ranks),
                    "retired_ts": now,
                })
                del self._history[:-_HISTORY_MAX]
            self._install_locked(world_size, epoch, now)
            self._transition = None
            self.generation += 1
            return self.generation

    def abort_resize(self) -> None:
        """Drain failed or the respawn never came up: the old fleet is
        still the fleet."""
        with self._lock:
            for rec in self._ranks.values():
                if rec.state == DRAINING:
                    rec.state = ACTIVE
            self._transition = None

    # -- views ---------------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._transition is not None

    def transition(self) -> dict | None:
        with self._lock:
            return dict(self._transition) if self._transition else None

    def rank_state(self, rank: int) -> str | None:
        with self._lock:
            rec = self._ranks.get(rank)
            return rec.state if rec else None

    def active_ranks(self) -> list[int]:
        with self._lock:
            return sorted(r for r, rec in self._ranks.items()
                          if rec.state == ACTIVE)

    def epoch_set(self, epoch: int) -> list[int]:
        """The worker set that served ``epoch`` (current or retired);
        empty when unknown."""
        with self._lock:
            if epoch == self._epoch:
                return sorted(self._ranks)
            for h in reversed(self._history):
                if h["epoch"] == epoch:
                    return list(h["ranks"])
            return []

    def describe(self) -> dict:
        """The ``%dist_pool status`` membership block."""
        with self._lock:
            return {
                "generation": self.generation,
                "epoch": self._epoch,
                "transition": (dict(self._transition)
                               if self._transition else None),
                "ranks": {str(r): rec.describe()
                          for r, rec in sorted(self._ranks.items())},
                "retired_epochs": [h["epoch"] for h in self._history],
            }
