"""Tenant identity, fencing, and parked-result partitions.

Per-tenant reuse of the durable-session machinery (PR 4): every tenant
gets its own session **token** (minted with
:func:`~nbdistributed_tpu.resilience.session.mint_token`) and its own
monotonically increasing **epoch**.  A tenant kernel that crashes and
reattaches (``%dist_attach --tenant``) proves the token and bumps the
epoch — from then on, frames from the dead kernel's old connection
(stamped with the older epoch) are rejected with ``stale_epoch``,
exactly the stale-coordinator fence, scoped to one tenant.  Results
that finish while a tenant has no live connection park in that
tenant's own
:class:`~nbdistributed_tpu.resilience.dedup.ResultMailbox` partition;
a reattach drains them destructively — exactly once.

The registry is also the **admission** gate for the pool's tenant
count (``max_tenants``): the per-tenant in-flight cap and queue-depth
backpressure live in the :class:`~.scheduler.Scheduler`; the headcount
lives here, at hello time, where a new tenant can be refused before it
costs anything.
"""

from __future__ import annotations

import os
import threading
import time

from ..resilience.dedup import ResultMailbox
from ..resilience.session import mint_token, token_fingerprint


def _tenant_spill_dir(name: str) -> str | None:
    """Run-dir spill partition for one tenant's mailbox (best-effort:
    a gateway without a run dir just keeps the in-memory bound)."""
    try:
        from ..observability import flightrec
        safe = "".join(c for c in name if c.isalnum() or c in "-_")
        return os.path.join(flightrec.run_dir(), f"spill-tenant-{safe}")
    except Exception:
        return None


class TenantRejected(RuntimeError):
    def __init__(self, reason: str, name: str):
        super().__init__(f"tenant {name!r} rejected: {reason}")
        self.reason = reason


class Tenant:
    __slots__ = ("name", "token", "epoch", "client_id", "mailbox",
                 "priority", "admitted_ts", "last_seen", "reattaches",
                 "cells_submitted", "cells_done", "cells_failed",
                 "parked_total", "ns_unsafe", "ns_lock")

    def __init__(self, name: str, token: str, priority: int = 0):
        self.name = name
        self.token = token
        self.epoch = 1
        self.client_id: int | None = None   # live tenant-plane conn
        # This tenant's parked-reply partition.  Shares the bulk-plane
        # spill path (ISSUE 20): a slow/detached client's oversized
        # results land on disk under the run dir with explicit
        # too_large/disk_full verdicts instead of evicting the
        # tenant's whole 32 MB mailbox.
        self.mailbox = ResultMailbox(spill_dir=_tenant_spill_dir(name))
        self.priority = int(priority)
        # Ambient names (np/time/builtins…) a dispatched cell of THIS
        # tenant rebound: the effect analyzer must not prove a later
        # cell collective-free on the assumption they still denote
        # their modules (analysis/effects.ambient_poison).  ns_lock
        # scopes the read-classify-poison to this tenant, so one
        # tenant's big-cell analysis never stalls the daemon-wide
        # plane.
        self.ns_unsafe: frozenset = frozenset()
        self.ns_lock = threading.Lock()
        self.admitted_ts = time.time()
        self.last_seen = time.time()
        self.reattaches = 0
        self.cells_submitted = 0
        self.cells_done = 0
        self.cells_failed = 0
        self.parked_total = 0

    @property
    def attached(self) -> bool:
        return self.client_id is not None

    def describe(self) -> dict:
        return {"name": self.name,
                "token_fp": token_fingerprint(self.token),
                "epoch": self.epoch,
                "attached": self.attached,
                "priority": self.priority,
                "reattaches": self.reattaches,
                "cells_submitted": self.cells_submitted,
                "cells_done": self.cells_done,
                "cells_failed": self.cells_failed,
                "parked": len(self.mailbox),
                "parked_total": self.parked_total,
                "last_seen_age_s": round(time.time() - self.last_seen,
                                         1)}


class TenantRegistry:
    """Name -> :class:`Tenant`, with the hello/fence state machine."""

    def __init__(self, max_tenants: int = 8):
        self.max_tenants = max(1, int(max_tenants))
        self._lock = threading.Lock()
        self._tenants: dict[str, Tenant] = {}
        self._by_client: dict[int, str] = {}

    # ------------------------------------------------------------------

    def hello(self, name: str, token: str | None, client_id: int, *,
              priority: int | None = None) -> tuple[Tenant, dict]:
        """Admit or reattach a tenant connection.

        - Unknown ``name``: admit (minting a token) unless the pool is
          at ``max_tenants`` — admission control's headcount bound.
        - Known ``name`` + matching token: **reattach** — bump the
          tenant epoch (fencing out the previous connection) and
          rebind the live client id.
        - Known ``name`` + wrong/absent token: rejected — a tenant
          name cannot be hijacked without its session token.

        Returns ``(tenant, reply_data)``; raises
        :class:`TenantRejected` on refusal.
        """
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                if len(self._tenants) >= self.max_tenants:
                    raise TenantRejected(
                        f"pool is at max_tenants={self.max_tenants}",
                        name)
                t = Tenant(name, token or mint_token(),
                           priority=priority if priority is not None
                           else 0)
                self._tenants[name] = t
                event = "admitted"
            else:
                if token != t.token:
                    raise TenantRejected(
                        "session token mismatch (not this tenant's "
                        "session)", name)
                t.epoch += 1
                t.reattaches += 1
                # A DECLARED priority wins on reattach (`%dist_attach
                # --tenant NAME --priority N` after a crash used to be
                # silently ignored); an OMITTED one (None) keeps the
                # tenant's current value — the argparse default must
                # not demote a priority-5 tenant to 0 on every plain
                # reattach.
                if priority is not None:
                    t.priority = priority
                event = "reattached"
            # The previous connection's client id stays mapped to this
            # tenant ON PURPOSE: its frames must resolve to the tenant
            # so the epoch fence can answer them with an explicit
            # ``stale_epoch`` (not a generic no-hello error).  The
            # mapping dies with the connection (detach_client on EOF).
            t.client_id = client_id
            self._by_client[client_id] = name
            t.last_seen = time.time()
            return t, {"status": event, "tenant": name,
                       "token": t.token, "epoch": t.epoch,
                       "parked": t.mailbox.ids()}

    def fence(self, tenant: Tenant, frame_epoch: int | None) -> bool:
        """True when a frame stamped ``frame_epoch`` is STALE for this
        tenant (an older connection's traffic after a reattach bumped
        the epoch).  Unstamped frames are never fenced — same contract
        as the session-epoch fence."""
        return frame_epoch is not None and frame_epoch < tenant.epoch

    # ------------------------------------------------------------------

    def by_client(self, client_id: int) -> Tenant | None:
        with self._lock:
            name = self._by_client.get(client_id)
            return self._tenants.get(name) if name else None

    def get(self, name: str) -> Tenant | None:
        with self._lock:
            return self._tenants.get(name)

    def detach_client(self, client_id: int) -> Tenant | None:
        """The tenant's connection dropped (kernel crash or exit):
        keep the tenant — its queued/in-flight work and mailbox survive
        for reattach — but stop routing replies to the dead socket.

        Returns the tenant only when this client id WAS its live
        connection; a superseded (fenced) old connection finally
        EOF-ing returns None, so callers never count a reattached
        tenant as detached."""
        with self._lock:
            name = self._by_client.pop(client_id, None)
            t = self._tenants.get(name) if name else None
            if t is not None and t.client_id == client_id:
                t.client_id = None
                return t
            return None

    def evict(self, name: str) -> bool:
        """Forget a DEPARTED tenant outright, freeing its
        ``max_tenants`` slot.  The daemon calls this only on a clean
        detach with an empty mailbox and nothing queued/active —
        without it, a rotation of N distinct tenant names would wedge
        the pool's admission forever.  A crashed tenant (or one with
        parked/in-flight work) keeps its slot for reattach."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None or t.attached:
                return False
            self._by_client = {c: n
                               for c, n in self._by_client.items()
                               if n != name}
            del self._tenants[name]
            return True

    # ------------------------------------------------------------------
    # migration (ISSUE 16): export/import/release move a tenant's
    # durable identity — token, epoch, priority, parked results —
    # between pools.  Export is non-destructive and import is
    # idempotent, so the sequence survives a router (or source pool)
    # death at any point: re-running it converges.

    def export_tenant(self, name: str) -> dict | None:
        """Snapshot a tenant's durable state for migration.  Parked
        replies travel as ``{msg_id: data}`` — the same shape a
        mailbox drain sends — and stay parked HERE until
        :meth:`release`; exactly-once holds because only one pool's
        mailbox is ever drained by the kernel."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                return None
            return {"tenant": t.name, "token": t.token,
                    "epoch": t.epoch, "priority": t.priority,
                    "reattaches": t.reattaches,
                    "parked": {mid: getattr(r, "data", None)
                               for mid, r in
                               t.mailbox.peek_all().items()}}

    def import_tenant(self, snap: dict) -> tuple[Tenant | None, str]:
        """Adopt an exported tenant.  Idempotent: a re-import of the
        same snapshot (router retry after a crash) merges instead of
        failing — epochs take the max, so the fence never regresses.
        Returns ``(tenant, why)``; tenant is None on refusal."""
        name = str(snap.get("tenant") or "").strip()
        token = snap.get("token")
        if not name or not token:
            return None, "snapshot missing tenant name or token"
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                if len(self._tenants) >= self.max_tenants:
                    return None, (f"pool is at max_tenants="
                                  f"{self.max_tenants}")
                try:
                    prio = int(snap.get("priority") or 0)
                except (TypeError, ValueError):
                    prio = 0
                t = Tenant(name, str(token), priority=prio)
                self._tenants[name] = t
            elif t.token != token:
                return None, ("tenant name in use with a different "
                              "session token")
            try:
                t.epoch = max(t.epoch, int(snap.get("epoch") or 1))
            except (TypeError, ValueError):
                pass
            return t, "imported"

    def release(self, name: str, *, force: bool = False) -> bool:
        """Forget a tenant whose export was imported elsewhere.
        Unlike :meth:`evict`, parked results do NOT pin the slot —
        the destination pool owns them now.  A live connection does,
        unless ``force``: then the epoch is bumped first so the old
        kernel's frames fence with ``stale_epoch`` instead of
        resolving against a ghost."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                return False
            if t.attached:
                if not force:
                    return False
                t.epoch += 1        # fence the still-live connection
                t.client_id = None
            self._by_client = {c: n
                               for c, n in self._by_client.items()
                               if n != name}
            del self._tenants[name]
            return True

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._tenants)

    def describe(self) -> dict:
        with self._lock:
            return {"max_tenants": self.max_tenants,
                    "tenants": {n: t.describe()
                                for n, t in sorted(
                                    self._tenants.items())}}

    def manifest_block(self) -> dict:
        """The ``tenants`` block of the gateway manifest: enough for a
        local kernel to reattach by name (token + epoch), mirroring
        how ``session.json`` records the single-kernel session token."""
        with self._lock:
            return {n: {"token": t.token, "epoch": t.epoch,
                        "attached": t.attached}
                    for n, t in sorted(self._tenants.items())}
