"""Session gateway: N notebook kernels sharing one pooled worker fleet.

The single-kernel stack maps one kernel to one fleet; this package
breaks that mapping (ROADMAP item 1, the "millions of users"
direction).  A :class:`~.daemon.GatewayDaemon` owns the workers and a
second, tenant-facing listener over the same authenticated codec;
notebook kernels attach as *tenants* (:class:`~.client.TenantClient`,
``%dist_attach --tenant``), and their cells are admitted, queued, and
scheduled onto the mesh by the :class:`~.scheduler.Scheduler` — the
same object the single-kernel path routes through inside
``CommunicationManager.send_to_ranks`` (no forked code path; a plain
``%dist_init`` world simply runs an unlimited-slot FIFO with one
implicit tenant).

Robustness is the headline: per-tenant session tokens and epochs
(:mod:`~.tenancy`) fence a stale or crashed tenant exactly like a
stale coordinator, a crashed tenant's in-flight results park in its
own :class:`~nbdistributed_tpu.resilience.dedup.ResultMailbox`
partition for exactly-once redelivery on reattach, and overload sheds
the lowest-priority queued cells with a visible verdict instead of
wedging the mesh.
"""

from .scheduler import (CellRejected, CellShed, SchedPolicy,  # noqa: F401
                        Scheduler, Ticket)
from .tenancy import Tenant, TenantRegistry  # noqa: F401

# daemon/client are lazy (PEP 562): the coordinator imports
# .scheduler at startup, and daemon.py imports the coordinator back —
# an eager import here would be a cycle.  They also pull in the
# manager/transport stack, which scheduler-only consumers (every
# single-kernel session) should not pay for.
_LAZY = {
    "GatewayDaemon": "daemon", "read_gateway_manifest": "daemon",
    "gateway_manifest_path": "daemon", "gateway_alive": "daemon",
    "discover_gateway": "daemon", "GATEWAY_MANIFEST_NAME": "daemon",
    "TenantClient": "client", "CellSubmitError": "client",
    "GatewayGone": "client", "TenantFenced": "client",
    "pool_status_probe": "client", "pool_shutdown": "client",
    "ServingManager": "serving", "ServeJournal": "serving",
    "merge_emission": "serving", "journal_path": "serving",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
