"""The mesh scheduler: who runs the next cell on the pooled fleet.

Extracted from the coordinator's request routing (ISSUE 8 tentpole) so
the gateway daemon and the single-kernel path share ONE dispatch
decision point: ``CommunicationManager.send_to_ranks`` submits every
``execute`` request here before it touches the wire.  A plain
``%dist_init`` world runs the default policy — unlimited mesh slots,
one implicit tenant — where every submit dispatches immediately, so
the single-kernel path pays one dict lookup and keeps its exact
pre-gateway behavior while exercising the same code the pool does.

Pure state machine by design: no threads of its own, an injectable
monotonic clock (``now=``), and every transition returns an explicit
verdict dict — the unit tests drive fairness/priority/backpressure/
shedding with a fake clock and zero sleeps.  The only concession to
its callers is the per-ticket ``threading.Event`` a queued submitter
can block on; the scheduler itself never waits.

Admission control and overload behavior (the robustness contract):

- **per-tenant in-flight cap** (``tenant_inflight``): a tenant whose
  queued+active cells hit the cap gets ``{"status": "rejected"}`` —
  one tenant's runaway notebook loop cannot monopolize the queue.
- **queue-depth backpressure** (``queue_depth``): a submit that finds
  the mesh busy is QUEUED and told so explicitly —
  ``{"status": "queued", "position": n}`` — never silently blocked.
- **graceful shedding**: when the queue itself is full, the lowest-
  priority, youngest queued cell is SHED with a visible verdict (its
  ticket's event fires so its submitter learns immediately); older and
  higher-priority work always survives.  The mesh never wedges.

Scheduling policy (``mode``): ``"fifo"`` dispatches in arrival order;
``"fair"`` (the pool default) picks the highest priority first, then
the tenant that has been served least, then arrival order — so an
interactive tenant's occasional cells interleave with a batch tenant's
flood instead of starving behind it.

**Effects-aware admission** (``NBD_POOL_SCHED_EFFECTS=1``, ISSUE 9):
with more than one mesh slot, every submit carries its cell's
collective class from :mod:`..analysis.effects` — ``"free"`` (proven
collective-free), ``"bearing"`` (statically enumerable collective
sites), or ``"unknown"`` (opaque/tainted).  Only *proven*-free cells
may overlap a non-free cell: at most one bearing/unknown cell holds
the mesh at a time, because two concurrent collective streams carry no
cross-rank ordering and can pair mismatched (the PR 8 hazard this gate
retires).  A cell held back while slots are free gets an explicit
``{"status": "queued", "reason": "serialized: …"}`` verdict naming
why, and proven-free cells promote AROUND held cells — overlap is the
point.  With effects off (the default) or a serial mesh
(``mesh_slots=1``), the gate is inert and behavior is exactly
pre-ISSUE-9.

Thread discipline: helper methods suffixed ``_locked`` assert their
callers hold ``self._lock`` — the self-lint's thread pass treats their
bodies as locked and flags any call to them from an unlocked context.
"""

from __future__ import annotations

import threading
import time

# Ticket states.
QUEUED = "queued"
ACTIVE = "active"
SHED = "shed"          # overload: a queued cell lost a shedding round
REJECTED = "rejected"  # admission: refused at the tenant-inflight cap
DONE = "done"

_DISPATCH = {"status": "dispatch"}


class CellRejected(RuntimeError):
    """Admission control refused the cell outright (tenant cap)."""

    def __init__(self, reason: str, tenant: str):
        super().__init__(f"cell rejected ({reason}) for tenant "
                         f"{tenant!r}")
        self.reason = reason
        self.tenant = tenant


class CellShed(RuntimeError):
    """The cell was shed under overload (queue full, lowest priority)."""

    def __init__(self, tenant: str, msg_id: str):
        super().__init__(
            f"cell shed under overload (tenant {tenant!r}): the queue "
            f"was full and this was the lowest-priority queued cell")
        self.tenant = tenant
        self.msg_id = msg_id


class SchedPolicy:
    """Scheduler configuration.  ``0`` means *unlimited* for every
    bound — the single-kernel default is all-unlimited FIFO, which
    reproduces pre-gateway behavior exactly."""

    __slots__ = ("mode", "mesh_slots", "tenant_inflight", "queue_depth",
                 "effects")

    def __init__(self, mode: str = "fifo", mesh_slots: int = 0,
                 tenant_inflight: int = 0, queue_depth: int = 0,
                 effects: bool = False):
        if mode not in ("fifo", "fair"):
            raise ValueError(f"unknown scheduler mode {mode!r} "
                             "(fifo|fair)")
        self.mode = mode
        self.mesh_slots = max(0, int(mesh_slots))
        self.tenant_inflight = max(0, int(tenant_inflight))
        self.queue_depth = max(0, int(queue_depth))
        self.effects = bool(effects)

    @classmethod
    def pool_from_env(cls, env=None) -> "SchedPolicy":
        """The gateway's policy from the ``NBD_POOL_*`` /
        ``NBD_TENANT_*`` knobs (serial mesh, fair-share, bounded
        queue by default)."""
        from ..utils import knobs
        mode = knobs.get_str("NBD_POOL_SCHED", "fair", env=env) or "fair"
        if mode not in ("fifo", "fair"):
            # Knobs convention: an env typo degrades to the default
            # instead of killing the daemon at construction.
            mode = "fair"
        return cls(
            mode=mode,
            mesh_slots=knobs.get_int("NBD_POOL_MESH_SLOTS", 1, env=env),
            tenant_inflight=knobs.get_int("NBD_TENANT_MAX_INFLIGHT", 8,
                                          env=env),
            queue_depth=knobs.get_int("NBD_POOL_QUEUE_DEPTH", 64,
                                      env=env),
            effects=knobs.get_bool("NBD_POOL_SCHED_EFFECTS", False,
                                   env=env))

    def describe(self) -> dict:
        return {"mode": self.mode, "mesh_slots": self.mesh_slots,
                "tenant_inflight": self.tenant_inflight,
                "queue_depth": self.queue_depth,
                "effects": self.effects}


class Ticket:
    """One scheduled cell.  ``event`` fires when the ticket leaves the
    queue — promoted to ACTIVE (run it) or SHED (report the verdict);
    check ``state`` after the wait."""

    __slots__ = ("tenant", "msg_id", "priority", "seq", "state",
                 "enqueued_at", "verdict", "event", "collective")

    def __init__(self, tenant: str, msg_id: str, priority: int,
                 seq: int, now: float, collective: str = "unknown"):
        self.tenant = tenant
        self.msg_id = msg_id
        self.priority = priority
        self.seq = seq
        self.state = QUEUED
        self.enqueued_at = now
        self.verdict: dict = {}
        self.event = threading.Event()
        # Effects-admission class: "free" | "bearing" | "unknown"
        # (analysis/effects.collective_class); only consulted when the
        # policy's effects gate is armed.
        self.collective = collective


class _TenantStats:
    __slots__ = ("queued", "active", "served", "completed", "shed",
                 "rejected")

    def __init__(self):
        self.queued = 0
        self.active = 0
        self.served = 0      # total dispatches granted (fair-share key)
        self.completed = 0
        self.shed = 0
        self.rejected = 0

    def as_dict(self) -> dict:
        return {"queued": self.queued, "active": self.active,
                "served": self.served, "completed": self.completed,
                "shed": self.shed, "rejected": self.rejected}


class Scheduler:
    """Thread-safe dispatch gate over the mesh.  See module docstring
    for the policy contract."""

    def __init__(self, policy: SchedPolicy | None = None, *,
                 now=time.monotonic):
        self.policy = policy or SchedPolicy()
        self._now = now
        self._lock = threading.Lock()
        self._seq = 0
        self._queue: list[Ticket] = []          # queued, arrival order
        self._active: dict[str, Ticket] = {}    # msg_id -> ticket
        self._tenants: dict[str, _TenantStats] = {}
        self.shed_total = 0
        # Submissions held back by the effects gate while slots were
        # free (the "serialized: …" verdicts).
        self.effects_serialized_total = 0
        # Drain barrier (ISSUE 16): while paused, nothing is granted —
        # submits queue with an explicit verdict and promotion stops —
        # so "active == 0" eventually means the mesh is DRAINED and a
        # resize may bump the epoch.  Holds the pause reason, None
        # when running.
        self._paused: str | None = None

    # ------------------------------------------------------------------
    # `_locked` suffix = caller holds self._lock (self-lint-enforced).

    def _stats_locked(self, tenant: str) -> _TenantStats:
        st = self._tenants.get(tenant)
        if st is None:
            st = self._tenants[tenant] = _TenantStats()
        return st

    def _slots_free_locked(self) -> bool:
        return (not self.policy.mesh_slots
                or len(self._active) < self.policy.mesh_slots)

    def _effects_ok_locked(self, t: Ticket) -> bool:
        """May this cell take a slot NOW, under effects admission?
        Proven-free cells overlap anything; a bearing/unknown cell
        needs every active cell to be proven free (at most one
        non-free collective stream on the mesh).  Inert when the gate
        is off or the mesh is serial anyway."""
        if not self.policy.effects or self.policy.mesh_slots == 1:
            return True
        if t.collective == "free":
            return True
        return all(a.collective == "free"
                   for a in self._active.values())

    @staticmethod
    def _serialized_reason(t: Ticket) -> str:
        if t.collective == "bearing":
            return ("serialized: collective-bearing cell — another "
                    "collective-bearing (or unproven) cell holds the "
                    "mesh, and concurrent collective streams can pair "
                    "mismatched across ranks")
        return ("serialized: collective footprint unknown — only "
                "cells proven collective-free may overlap a running "
                "collective-bearing cell")

    def _grant_locked(self, t: Ticket) -> None:
        # QUEUED/fresh -> ACTIVE.
        st = self._stats_locked(t.tenant)
        if t.state == QUEUED and t in self._queue:
            self._queue.remove(t)
            st.queued -= 1
        t.state = ACTIVE
        st.active += 1
        st.served += 1
        self._active[t.msg_id] = t
        t.event.set()

    def _shed_locked(self, t: Ticket) -> None:
        # QUEUED -> SHED, visible verdict, event fired.
        if t in self._queue:
            self._queue.remove(t)
        st = self._stats_locked(t.tenant)
        st.queued -= 1
        st.shed += 1
        self.shed_total += 1
        t.state = SHED
        t.verdict = {"status": "shed", "reason": "overload",
                     "tenant": t.tenant, "msg_id": t.msg_id}
        t.event.set()

    def _pick_next_locked(self) -> Ticket | None:
        # FIFO: arrival order.  Fair: highest priority, then
        # least-served tenant, then arrival order.  Under effects
        # admission only COMPATIBLE tickets are eligible — a proven-
        # free cell promotes around a held bearing/unknown cell
        # (overlap is the point of the gate).
        eligible = [t for t in self._queue
                    if self._effects_ok_locked(t)]
        if not eligible:
            return None
        if self.policy.mode == "fifo":
            return eligible[0]
        return min(eligible,
                   key=lambda t: (-t.priority,
                                  self._stats_locked(t.tenant).served,
                                  t.seq))

    def _promote_locked(self) -> list[Ticket]:
        # Fill free slots from the queue (never while draining).
        promoted = []
        if self._paused is not None:
            return promoted
        while self._queue and self._slots_free_locked():
            t = self._pick_next_locked()
            if t is None:
                break
            self._grant_locked(t)
            promoted.append(t)
        return promoted

    # ------------------------------------------------------------------

    def submit(self, tenant: str, msg_id: str, priority: int = 0,
               collective: str = "unknown") -> Ticket:
        """Admit one cell.  The returned ticket's ``verdict`` is one
        of::

            {"status": "dispatch"}                    # run it now
            {"status": "queued", "position": n}       # wait on .event
            {"status": "rejected", "reason": ...}     # tenant cap hit
            {"status": "shed", "reason": "overload",  # queue full and
             ...}                                     # this was lowest

        ``collective`` is the cell's effects-admission class
        (``analysis.effects.collective_class``); under an armed
        effects gate, a non-free cell that cannot overlap the active
        set queues with ``"reason": "serialized: …"`` even when slots
        are free.  A queued submit that later loses a shedding
        decision flips to SHED and fires its event — the waiter must
        re-check ``state``.  ``verdict`` may also carry ``"victims"``:
        JSON-safe summaries (``{"tenant", "msg_id", "priority"}``) of
        OTHER submitters' cells this admission shed.  Informational
        only — each victim's own blocked submit thread is what
        delivers its shed verdict."""
        now = self._now()
        with self._lock:
            st = self._stats_locked(tenant)
            t = Ticket(tenant, msg_id, int(priority), self._seq, now,
                       collective)
            self._seq += 1
            cap = self.policy.tenant_inflight
            if cap and st.queued + st.active >= cap:
                st.rejected += 1
                # Distinct terminal state: a consumer branching on
                # ``state`` (send_to_ranks raises CellShed on SHED)
                # must not misreport a capacity refusal as an
                # overload shed.
                t.state = REJECTED
                t.verdict = {"status": "rejected",
                             "reason": "tenant-inflight-cap",
                             "limit": cap, "tenant": tenant}
                t.event.set()
                return t
            serialized = None
            if (self._paused is None and self._slots_free_locked()
                    and not self._queue):
                if self._effects_ok_locked(t):
                    self._grant_locked(t)
                    t.verdict = dict(_DISPATCH)
                    return t
                # Slots free, but overlap is unproven-safe: serialize
                # with a verdict naming the reason.
                serialized = self._serialized_reason(t)
                self.effects_serialized_total += 1
            # Mesh busy (or effects-held): queue with an explicit
            # position reply.
            self._queue.append(t)
            st.queued += 1
            victims: list[dict] = []
            depth = self.policy.queue_depth
            while depth and len(self._queue) > depth:
                # Overload: shed the lowest-priority, youngest queued
                # cell (max seq among min priority) — older and
                # higher-priority work survives.
                victim = max(self._queue,
                             key=lambda q: (-q.priority, q.seq))
                self._shed_locked(victim)
                if victim is not t:
                    victims.append({"tenant": victim.tenant,
                                    "msg_id": victim.msg_id,
                                    "priority": victim.priority})
            if t.state == SHED:
                if victims:
                    t.verdict["victims"] = victims
                return t
            t.verdict = {"status": "queued",
                         "position": self._queue.index(t) + 1}
            if serialized:
                t.verdict["reason"] = serialized
            if self._paused is not None:
                # Not the effects "reason" key: the daemon counts that
                # as proof-gated serialization; a drain hold is its own
                # story.
                t.verdict["paused"] = self._paused
            if victims:
                t.verdict["victims"] = victims
            # A compatible cell may still fit a free slot even though
            # the queue is non-empty (effects-held cells in front of
            # it): promotion grants it — and, under the gate, lets
            # proven-free work overlap instead of convoying.  Only
            # THIS ticket can be granted here (no slot was freed, so
            # nothing else became eligible); if it was, the queued
            # verdict is stale — the submitter must see a plain
            # dispatch, not a backpressure notice for a cell that
            # never waited.
            self._promote_locked()
            if t.state == ACTIVE:
                t.verdict = dict(_DISPATCH)
                if victims:
                    t.verdict["victims"] = victims
            return t

    def complete(self, msg_id: str) -> list[Ticket]:
        """Release the cell's mesh slot (success OR failure) and
        promote queued work into the freed capacity.  Returns the
        promoted tickets (their events are already set)."""
        with self._lock:
            t = self._active.pop(msg_id, None)
            if t is not None:
                t.state = DONE
                st = self._stats_locked(t.tenant)
                st.active -= 1
                st.completed += 1
            return self._promote_locked()

    def cancel(self, msg_id: str) -> bool:
        """Withdraw a queued or active cell (submitter timeout / tenant
        gone before dispatch).  Frees capacity like :meth:`complete`
        but counts nothing as completed."""
        with self._lock:
            t = self._active.pop(msg_id, None)
            if t is not None:
                t.state = DONE
                st = self._stats_locked(t.tenant)
                st.active -= 1
                self._promote_locked()
                return True
            for t in self._queue:
                if t.msg_id == msg_id:
                    self._queue.remove(t)
                    self._stats_locked(t.tenant).queued -= 1
                    t.state = DONE
                    t.event.set()
                    return True
        return False

    def pause(self, reason: str = "drain") -> None:
        """Arm the drain barrier: stop granting slots.  In-flight
        cells keep their slots and complete normally; new submits
        queue with a ``"paused"``-annotated verdict.  Idempotent —
        the latest reason wins."""
        with self._lock:
            self._paused = str(reason)

    def resume(self) -> list[Ticket]:
        """Drop the drain barrier and promote everything the pause
        held back.  Returns the promoted tickets (events already
        fired), mirroring :meth:`complete`."""
        with self._lock:
            self._paused = None
            return self._promote_locked()

    @property
    def paused(self) -> str | None:
        with self._lock:
            return self._paused

    def active_count(self) -> int:
        """In-flight cells holding mesh slots — the drain barrier's
        "is the mesh quiet yet" probe."""
        with self._lock:
            return len(self._active)

    def tenant_idle(self, tenant: str) -> bool:
        """True when this tenant has nothing queued and nothing
        active — the gateway may safely forget it."""
        with self._lock:
            st = self._tenants.get(tenant)
            return st is None or (st.queued == 0 and st.active == 0)

    def forget_tenant(self, tenant: str) -> bool:
        """Drop an evicted tenant's stats entry.  Without this the
        per-tenant dict grows one entry per name forever, snapshot()
        lists long-gone tenants, and a NEW tenant reusing the name
        inherits the old ``served`` count — fair mode would
        deprioritize it against genuinely fresh tenants.  Refused
        while the tenant still has queued/active work."""
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None or st.queued or st.active:
                return st is None
            del self._tenants[tenant]
            return True

    def position(self, msg_id: str) -> int | None:
        """1-based queue position, or None when not queued."""
        with self._lock:
            for i, t in enumerate(self._queue):
                if t.msg_id == msg_id:
                    return i + 1
        return None

    def snapshot(self) -> dict:
        """Counters for ``%dist_pool status`` / metrics export."""
        with self._lock:
            return {
                "policy": self.policy.describe(),
                "queued": len(self._queue),
                "active": len(self._active),
                "paused": self._paused,
                "shed_total": self.shed_total,
                "effects_serialized_total":
                    self.effects_serialized_total,
                "tenants": {k: v.as_dict()
                            for k, v in sorted(self._tenants.items())},
            }
