"""Serving fast path (ISSUE 17): paged KV blocks + closed-loop load.

Two deliberately dependency-free modules shared by the gateway driver,
the worker-side decode server, and the load harness:

- :mod:`.paging` — the fixed-size KV block allocator.  Pure host-side
  bookkeeping (no jax import): the gateway instantiates one allocator
  per decode rank to gate admission on free *blocks* instead of
  sequence slots, and the device layer (:mod:`..models.paged_kv`)
  instantiates the same class to manage physical block ids inside the
  pooled cache.  One implementation, two owners, identical arithmetic
  — the admission verdict and the device table can never disagree
  about capacity.
- :mod:`.loadgen` — the closed-loop load generator core: deterministic
  arrival/length schedules, a pluggable transport (HTTP shim or an
  in-process ``TenantClient``), SLO scoring against the PR 12
  TTFT/TPOT histograms, and a machine-readable report with a pinned
  schema.  ``tools/nbd_loadgen.py`` is a thin CLI over this module so
  bench and the unit tests drive the exact code the CLI runs.
"""

from .loadgen import (LoadConfig, run_load, score_slo, synth_schedule,
                      validate_report)
from .paging import BlockAllocator, BlocksExhausted, blocks_needed

__all__ = ["BlockAllocator", "BlocksExhausted", "blocks_needed",
           "LoadConfig", "run_load", "score_slo", "synth_schedule",
           "validate_report"]
