"""Closed-loop load generator for the serving plane (ISSUE 17).

The serving fast path's throughput and SLO claims need a harness that
can actually falsify them: offer load at a configured rate, watch
every request to a TERMINAL verdict, and score the observed latency
distributions against explicit targets.  This module is that harness'
core — deliberately dependency-free (stdlib only, no jax) so the unit
tests, ``bench.py``'s ``extra.serving`` row, the CI smoke, and the
``tools/nbd_loadgen.py`` CLI all drive the exact same code.

Three pieces:

* :func:`synth_schedule` — a DETERMINISTIC arrival/shape plan from a
  seed: Poisson (exponential gaps) or uniform arrivals at ``rps``,
  with prompt/output lengths drawn uniformly from configured ranges.
  Same config -> same schedule, byte for byte, so a chaos run and its
  solo reference offer identical work.
* :func:`run_load` — the closed loop: submit each request at its
  scheduled offset through a pluggable *transport* (the HTTP shim or
  an in-process :class:`~..gateway.client.TenantClient`), poll every
  accepted request's stream to completion, and stamp client-side
  TTFT/TPOT/e2e from token arrival times.  Every offered request ends
  in an explicit bucket — accepted→completed, accepted→shed (the
  delivered overload verdict), rejected/shed at submit, failed, or
  ``hung`` (accepted but never terminal within the drain budget,
  which FAILS the run: zero silent drops is the contract).
* :func:`score_slo` / :func:`validate_report` — pass/fail against
  p99 targets (client-observed percentiles, with the server's PR 12
  histogram summary attached for cross-checking) and the pinned
  machine-readable report schema CI and bench consume.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request

REPORT_SCHEMA_VERSION = 1

# The pinned report surface: consumers (CI smoke, bench.py, dashboards)
# key on these.  Adding a field is fine; removing or renaming one is a
# breaking change the schema unit test is meant to catch.
REPORT_REQUIRED_KEYS = frozenset({
    "schema", "config", "offered", "accepted", "rejected", "shed",
    "completed", "failed", "hung", "shed_rate", "tokens_total",
    "tokens_per_s", "duration_s", "client", "server_slo", "slo",
})
CLIENT_REQUIRED_KEYS = frozenset({"ttft_ms", "tpot_ms", "e2e_ms"})
SLO_REQUIRED_KEYS = frozenset({"targets", "checks", "pass"})


class LoadConfig:
    """One load run's shape.  ``arrival`` is ``"poisson"`` (memoryless
    gaps — the bursty realistic case) or ``"uniform"`` (constant gap —
    the pure-throughput case).  Lengths are inclusive ``(lo, hi)``
    ranges sampled per request."""

    def __init__(self, *, rps: float = 4.0, duration_s: float = 15.0,
                 arrival: str = "poisson", seed: int = 0,
                 prompt_len: tuple[int, int] = (4, 16),
                 max_new: tuple[int, int] = (4, 16),
                 vocab: int = 50, priority: int = 0,
                 slo_ttft_p99_ms: float | None = None,
                 slo_tpot_p99_ms: float | None = None,
                 drain_s: float = 60.0, poll_s: float = 0.02,
                 detail: bool = False):
        if rps <= 0:
            raise ValueError(f"rps must be > 0, got {rps}")
        if duration_s <= 0:
            raise ValueError(f"duration_s must be > 0, got {duration_s}")
        if arrival not in ("poisson", "uniform"):
            raise ValueError(f"arrival must be 'poisson' or 'uniform', "
                             f"got {arrival!r}")
        for name, (lo, hi) in (("prompt_len", prompt_len),
                               ("max_new", max_new)):
            if not (1 <= lo <= hi):
                raise ValueError(f"{name} must satisfy 1 <= lo <= hi, "
                                 f"got ({lo}, {hi})")
        self.rps = float(rps)
        self.duration_s = float(duration_s)
        self.arrival = arrival
        self.seed = int(seed)
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.max_new = (int(max_new[0]), int(max_new[1]))
        self.vocab = int(vocab)
        self.priority = int(priority)
        self.slo_ttft_p99_ms = slo_ttft_p99_ms
        self.slo_tpot_p99_ms = slo_tpot_p99_ms
        self.drain_s = float(drain_s)
        self.poll_s = float(poll_s)
        # detail=True adds a per-request ``requests`` list to the
        # report (plan index, rid, terminal status, tokens) — the
        # chaos integration test keys exactness assertions on it.
        self.detail = bool(detail)

    def to_dict(self) -> dict:
        return {"rps": self.rps, "duration_s": self.duration_s,
                "arrival": self.arrival, "seed": self.seed,
                "prompt_len": list(self.prompt_len),
                "max_new": list(self.max_new), "vocab": self.vocab,
                "priority": self.priority,
                "slo_ttft_p99_ms": self.slo_ttft_p99_ms,
                "slo_tpot_p99_ms": self.slo_tpot_p99_ms}


def synth_schedule(cfg: LoadConfig) -> list[dict]:
    """The deterministic offered-load plan: ``[{"at", "prompt",
    "max_new"}]`` sorted by arrival offset (seconds from run start).
    A pure function of the config — replaying the same config against
    a chaos run and a solo reference offers bit-identical work."""
    rng = random.Random(cfg.seed)
    out = []
    t = 0.0
    while True:
        if cfg.arrival == "poisson":
            t += rng.expovariate(cfg.rps)
        else:
            t += 1.0 / cfg.rps
        if t >= cfg.duration_s:
            break
        plen = rng.randint(*cfg.prompt_len)
        out.append({
            "at": t,
            "prompt": [rng.randrange(1, cfg.vocab)
                       for _ in range(plen)],
            "max_new": rng.randint(*cfg.max_new),
        })
    return out


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        raise ValueError("empty sample")
    i = min(len(sorted_vals) - 1,
            max(0, int(q * len(sorted_vals) + 0.5) - 1))
    return sorted_vals[i]


def _stats_ms(vals: list[float]) -> dict | None:
    sv = sorted(v for v in vals if v is not None)
    if not sv:
        return None
    return {"p50": round(percentile(sv, 0.50) * 1e3, 3),
            "p99": round(percentile(sv, 0.99) * 1e3, 3),
            "mean": round(sum(sv) / len(sv) * 1e3, 3),
            "max": round(sv[-1] * 1e3, 3),
            "n": len(sv)}


# ----------------------------------------------------------------------
# transports


class HTTPTransport:
    """The shim transport (``tools/nbd_serve.py``): everything over
    the ``/v1`` JSON endpoints.  Explicit 429/503 overload verdicts
    come back as verdict dicts, never exceptions — the loadgen scores
    them, it does not retry them."""

    def __init__(self, base_url: str, *, timeout: float = 30.0):
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str) -> dict:
        with urllib.request.urlopen(self.base + path,
                                    timeout=self.timeout) as r:
            return json.loads(r.read().decode("utf-8"))

    def submit(self, prompt: list[int], max_new: int,
               priority: int = 0) -> dict:
        body = json.dumps({"prompt": prompt,
                           "max_new_tokens": max_new,
                           "priority": priority}).encode("utf-8")
        req = urllib.request.Request(
            self.base + "/v1/submit", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            # 429/503 carry the explicit verdict as their body.
            try:
                return json.loads(e.read().decode("utf-8"))
            except Exception:
                return {"status": "failed",
                        "error": f"HTTP {e.code}"}

    def result(self, rid: str) -> dict:
        return self._get(f"/v1/result/{rid}")

    def status(self) -> dict:
        return self._get("/v1/status")


class ClientTransport:
    """In-process transport over a connected
    :class:`~..gateway.client.TenantClient` — what bench and the CI
    smoke use (no HTTP server needed; same verdict surface)."""

    def __init__(self, client):
        self.client = client

    def submit(self, prompt: list[int], max_new: int,
               priority: int = 0) -> dict:
        from ..gateway.client import CellSubmitError
        try:
            return self.client.serve_submit(prompt, max_new,
                                            priority=priority)
        except CellSubmitError as e:
            return dict(e.verdict)

    def result(self, rid: str) -> dict:
        return self.client.serve_result(rid)

    def status(self) -> dict:
        return self.client.serve_status()


# ----------------------------------------------------------------------
# the closed loop


def run_load(transport, cfg: LoadConfig, *,
             on_progress=None) -> dict:
    """Offer :func:`synth_schedule`'s plan through ``transport``,
    follow every accepted request to a terminal state, and return the
    scored report.

    Single-threaded on purpose: one loop submits due arrivals and
    polls open requests, so the harness itself cannot reorder or race
    the offered load.  Polling granularity (``cfg.poll_s``) bounds
    client-side TTFT/TPOT resolution — fine for SLO targets in the
    tens of milliseconds and above.
    """
    plan = synth_schedule(cfg)
    t0 = time.monotonic()
    nxt = 0
    open_reqs: dict[str, dict] = {}
    done_reqs: list[dict] = []
    counts = {"offered": 0, "accepted": 0, "rejected": 0, "shed": 0,
              "completed": 0, "failed": 0, "hung": 0}
    tokens_total = 0

    def poll_open() -> None:
        nonlocal tokens_total
        now = time.monotonic()
        for rid in list(open_reqs):
            st = open_reqs[rid]
            try:
                r = transport.result(rid)
            except Exception as e:
                st["error"] = f"{type(e).__name__}: {e}"
                continue
            n = len(r.get("tokens") or ())
            if n > st["seen"]:
                if st["first_tok"] is None:
                    st["first_tok"] = now
                st["last_tok"] = now
                st["seen"] = n
            if r.get("done"):
                st["end"] = now
                st["status"] = r.get("status")
                st["tokens"] = list(r.get("tokens") or ())
                tokens_total += n
                if st["status"] == "completed":
                    counts["completed"] += 1
                elif st["status"] == "shed":
                    # Accepted-then-shed: a delivered overload
                    # verdict, not a failure.
                    counts["shed"] += 1
                else:
                    counts["failed"] += 1
                done_reqs.append(st)
                del open_reqs[rid]

    while nxt < len(plan) or open_reqs:
        now = time.monotonic() - t0
        if nxt < len(plan) and now >= plan[nxt]["at"]:
            item, idx = plan[nxt], nxt
            nxt += 1
            counts["offered"] += 1
            sub_t = time.monotonic()
            try:
                v = transport.submit(item["prompt"], item["max_new"],
                                     cfg.priority)
            except Exception as e:
                counts["failed"] += 1
                done_reqs.append({"i": idx, "status": "failed",
                                  "seen": 0,
                                  "submit": sub_t, "first_tok": None,
                                  "last_tok": None, "end": sub_t,
                                  "error": f"{type(e).__name__}: {e}"})
                continue
            status = v.get("status")
            if status == "accepted":
                counts["accepted"] += 1
                open_reqs[v["rid"]] = {
                    "i": idx, "rid": v["rid"], "status": "accepted",
                    "submit": sub_t, "first_tok": None,
                    "last_tok": None, "end": None, "seen": 0}
            elif status in ("rejected", "shed"):
                counts[status] += 1
                done_reqs.append({"i": idx, "status": status,
                                  "seen": 0,
                                  "submit": sub_t, "first_tok": None,
                                  "last_tok": None, "end": sub_t})
            else:
                counts["failed"] += 1
                done_reqs.append({"i": idx, "status": "failed",
                                  "seen": 0,
                                  "submit": sub_t, "first_tok": None,
                                  "last_tok": None, "end": sub_t,
                                  "error": str(v)[:200]})
            continue   # drain the due arrivals before sleeping
        poll_open()
        if on_progress is not None:
            on_progress(counts, len(open_reqs))
        if nxt >= len(plan):
            # Drain phase: bounded — an accepted request that never
            # terminalizes is a HUNG verdict, not an infinite wait.
            if time.monotonic() - t0 > cfg.duration_s + cfg.drain_s:
                for st in open_reqs.values():
                    st["status"] = "hung"
                    st["end"] = time.monotonic()
                    counts["hung"] += 1
                    done_reqs.append(st)
                open_reqs.clear()
                break
        wake = time.monotonic() + cfg.poll_s
        if nxt < len(plan):
            wake = min(wake, t0 + plan[nxt]["at"])
        delay = wake - time.monotonic()
        if delay > 0:
            time.sleep(delay)
    wall = time.monotonic() - t0

    ttft = [st["first_tok"] - st["submit"] for st in done_reqs
            if st.get("first_tok") is not None]
    tpot = [(st["last_tok"] - st["first_tok"]) / (st["seen"] - 1)
            for st in done_reqs
            if st.get("first_tok") is not None
            and st.get("last_tok") is not None and st["seen"] > 1
            and st["last_tok"] > st["first_tok"]]
    e2e = [st["end"] - st["submit"] for st in done_reqs
           if st.get("status") == "completed"
           and st.get("end") is not None]

    try:
        server_slo = (transport.status() or {}).get("slo") or {}
    except Exception:
        server_slo = {}

    report = {
        "schema": REPORT_SCHEMA_VERSION,
        "config": cfg.to_dict(),
        **counts,
        "shed_rate": round((counts["shed"] + counts["rejected"])
                           / max(1, counts["offered"]), 4),
        "tokens_total": tokens_total,
        "tokens_per_s": round(tokens_total / wall, 2) if wall > 0
        else 0.0,
        "duration_s": round(wall, 3),
        "client": {"ttft_ms": _stats_ms(ttft),
                   "tpot_ms": _stats_ms(tpot),
                   "e2e_ms": _stats_ms(e2e)},
        "server_slo": server_slo,
    }
    if cfg.detail:
        report["requests"] = [
            {"i": st.get("i"), "rid": st.get("rid"),
             "status": st.get("status"),
             "tokens": st.get("tokens")}
            for st in sorted(done_reqs,
                             key=lambda s: s.get("i", -1))]
    report["slo"] = score_slo(report, cfg)
    return report


def score_slo(report: dict, cfg: LoadConfig) -> dict:
    """Pass/fail verdicts against the configured p99 targets, from the
    CLIENT-observed percentiles (what a user feels; the server's PR 12
    histogram summary rides along in the report for cross-checking).
    A run with hung requests fails regardless of latency — silent
    drops are never a pass."""
    checks = []
    for metric, target in (("ttft", cfg.slo_ttft_p99_ms),
                           ("tpot", cfg.slo_tpot_p99_ms)):
        if target is None:
            continue
        obs = (report["client"].get(metric + "_ms") or {}).get("p99")
        checks.append({"metric": metric + "_p99_ms",
                       "target": float(target), "observed": obs,
                       "ok": obs is not None and obs <= float(target)})
    if report.get("hung", 0):
        checks.append({"metric": "hung", "target": 0.0,
                       "observed": float(report["hung"]),
                       "ok": False})
    return {"targets": {"ttft_p99_ms": cfg.slo_ttft_p99_ms,
                        "tpot_p99_ms": cfg.slo_tpot_p99_ms},
            "checks": checks,
            "pass": all(c["ok"] for c in checks)}


def validate_report(report: dict) -> None:
    """Assert the pinned report shape; raises ``ValueError`` naming
    the first violation.  CI's schema unit test calls this on a real
    run's output, so a drifting field shows up as a test failure, not
    a broken dashboard."""
    if not isinstance(report, dict):
        raise ValueError("report must be a dict")
    missing = REPORT_REQUIRED_KEYS - set(report)
    if missing:
        raise ValueError(f"report missing keys: {sorted(missing)}")
    if report["schema"] != REPORT_SCHEMA_VERSION:
        raise ValueError(f"unknown schema version {report['schema']!r}"
                         f" (expected {REPORT_SCHEMA_VERSION})")
    if not isinstance(report["client"], dict) \
            or CLIENT_REQUIRED_KEYS - set(report["client"]):
        raise ValueError("report.client must carry "
                         f"{sorted(CLIENT_REQUIRED_KEYS)}")
    slo = report["slo"]
    if not isinstance(slo, dict) or SLO_REQUIRED_KEYS - set(slo):
        raise ValueError("report.slo must carry "
                         f"{sorted(SLO_REQUIRED_KEYS)}")
    for k in ("offered", "accepted", "rejected", "shed", "completed",
              "failed", "hung", "tokens_total"):
        if not isinstance(report[k], int) or report[k] < 0:
            raise ValueError(f"report.{k} must be a non-negative int")
    terminal = (report["completed"] + report["failed"]
                + report["shed"] + report["rejected"]
                + report["hung"])
    if terminal != report["offered"]:
        raise ValueError(
            f"conservation broken: {terminal} terminal verdicts for "
            f"{report['offered']} offered requests — a request was "
            f"silently dropped or double-counted")
