"""Fixed-size KV block allocator: admission measured in blocks.

The dense serving cache reserves ``max_len`` tokens of KV per slot the
moment a request is admitted, so admission capacity is "sequences",
and a pool of short chats wastes almost all of it.  Paging carves the
cache into fixed-size blocks of ``block_tokens`` tokens; a request
holds exactly ``ceil((prompt + max_new) / block_tokens)`` blocks and
admission is bounded by *free blocks* — the quantized-KV capacity the
EQuARX line of work says is the real resource (PAPER.md motivation).

Pure host-side bookkeeping on purpose: no jax import, O(1) alloc/free,
a deterministic free-list (lowest id first) so the gateway-side
accounting replica and the worker-side device allocator make identical
decisions from identical event streams.  Exhaustion raises
:class:`BlocksExhausted` — an explicit verdict carrying need/free —
never a silent wedge; callers turn it into a scheduler-style
``{"status": ...}`` dict.

``defrag()`` compacts live blocks toward low ids and returns the
``{old_id: new_id}`` move map; the device layer applies the same map
to the physical pool with one gather so host tables and device storage
move in lock-step.  ``check()`` asserts the conservation invariants
(used + free == total, no block owned twice, tables match ownership)
and is called by the unit tests after every mutation batch.

Thread discipline: the allocator is NOT internally locked — each owner
(ServingManager under its driver lock, DecodeServer on the worker's
serve thread) already serializes access.
"""

from __future__ import annotations


def blocks_needed(tokens: int, block_tokens: int) -> int:
    """Blocks required to hold ``tokens`` KV entries (ceil division).

    A request that may grow to ``prompt + max_new`` tokens allocates
    its worst case up front — continuous batching never stalls
    mid-decode on allocation, and admission verdicts are decidable at
    submit time.
    """
    if tokens <= 0:
        return 0
    return -(-int(tokens) // int(block_tokens))


class BlocksExhausted(RuntimeError):
    """Allocation refused: the pool has fewer free blocks than needed.

    The explicit-verdict exception (never a silent wedge): carries the
    shortfall so the caller's verdict can say exactly why admission
    failed (``need`` blocks requested, ``free`` available).
    """

    def __init__(self, need: int, free: int):
        super().__init__(
            f"KV blocks exhausted: need {need}, {free} free")
        self.need = need
        self.free = free


class BlockAllocator:
    """Fixed-size block pool with per-owner block tables.

    Owners are opaque strings (request ids on the serving plane).  The
    free list is kept sorted ascending so allocation order is a pure
    function of the alloc/free history — the property that lets the
    gateway mirror the worker without any wire chatter.
    """

    def __init__(self, n_blocks: int, block_tokens: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}")
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self._free: list[int] = list(range(self.n_blocks))
        self._tables: dict[str, list[int]] = {}

    # -- capacity accounting ------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.n_blocks - len(self._free)

    def can_fit(self, tokens: int) -> bool:
        """Would a request needing ``tokens`` KV entries be admitted?"""
        return blocks_needed(tokens, self.block_tokens) <= len(self._free)

    def largest_free_run(self) -> int:
        """Longest contiguous run of free block ids — the
        fragmentation number the observatory exports next to the free
        count (ISSUE 18): the free list is kept sorted, so one linear
        scan answers it."""
        best = run = 0
        prev = None
        for b in self._free:
            run = run + 1 if prev is not None and b == prev + 1 else 1
            if run > best:
                best = run
            prev = b
        return best

    def owners(self) -> list[str]:
        return list(self._tables)

    def table(self, owner: str) -> list[int]:
        """The owner's block table (a copy), in logical order."""
        return list(self._tables[owner])

    def owner_blocks(self, owner: str) -> int:
        t = self._tables.get(owner)
        return 0 if t is None else len(t)

    # -- alloc / grow / free ------------------------------------------
    def alloc(self, owner: str, n: int) -> list[int]:
        """Allocate ``n`` blocks for a new owner; returns the table.

        Raises :class:`BlocksExhausted` (nothing is taken) when the
        pool cannot satisfy the request, and ``ValueError`` if the
        owner already holds blocks — double-admission is a caller bug,
        not a capacity condition.
        """
        if owner in self._tables:
            raise ValueError(f"owner {owner!r} already has blocks")
        n = int(n)
        if n < 0:
            raise ValueError(f"negative block count {n}")
        if n > len(self._free):
            raise BlocksExhausted(n, len(self._free))
        taken, self._free = self._free[:n], self._free[n:]
        self._tables[owner] = taken
        return list(taken)

    def extend(self, owner: str, n: int) -> list[int]:
        """Grow an existing owner's table by ``n`` blocks.

        Block-table growth for requests whose budget is raised after
        admission.  All-or-nothing like :meth:`alloc`.
        """
        if owner not in self._tables:
            raise KeyError(f"unknown owner {owner!r}")
        n = int(n)
        if n < 0:
            raise ValueError(f"negative block count {n}")
        if n > len(self._free):
            raise BlocksExhausted(n, len(self._free))
        taken, self._free = self._free[:n], self._free[n:]
        self._tables[owner].extend(taken)
        return list(taken)

    def free(self, owner: str) -> int:
        """Release every block the owner holds; returns how many.

        Freeing an unknown owner is a no-op returning 0 — release and
        failover paths may race a finish, and double-free must not
        corrupt the pool.
        """
        t = self._tables.pop(owner, None)
        if t is None:
            return 0
        self._free.extend(t)
        self._free.sort()
        return len(t)

    def reset(self) -> None:
        """Drop every table and return all blocks to the free list."""
        self._tables.clear()
        self._free = list(range(self.n_blocks))

    # -- defrag --------------------------------------------------------
    def defrag(self) -> dict[int, int]:
        """Compact live blocks toward low ids; returns ``{old: new}``.

        After churn the live blocks are scattered across the id space.
        Compaction renumbers them densely from 0 (stable owner order,
        logical order preserved within each table) so the device pool's
        hot region is contiguous.  Only genuinely moving blocks appear
        in the returned map; the device layer applies it with a single
        gather.  Conservation is untouched — ``check()`` holds before
        and after.
        """
        moves: dict[int, int] = {}
        nxt = 0
        for owner in self._tables:
            tbl = self._tables[owner]
            for i, old in enumerate(tbl):
                if old != nxt:
                    moves[old] = nxt
                    tbl[i] = nxt
                nxt += 1
        self._free = list(range(nxt, self.n_blocks))
        return moves

    # -- invariants ----------------------------------------------------
    def check(self) -> None:
        """Assert conservation: every block owned exactly once or free."""
        seen: set[int] = set()
        for owner, tbl in self._tables.items():
            for b in tbl:
                if not (0 <= b < self.n_blocks):
                    raise AssertionError(
                        f"owner {owner!r} holds out-of-range block {b}")
                if b in seen:
                    raise AssertionError(
                        f"block {b} owned twice (second: {owner!r})")
                seen.add(b)
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate ids on the free list")
        if free & seen:
            raise AssertionError(
                f"blocks both free and owned: {sorted(free & seen)}")
        if len(free) + len(seen) != self.n_blocks:
            raise AssertionError(
                f"conservation broken: {len(seen)} used + "
                f"{len(free)} free != {self.n_blocks} total")

    def snapshot(self) -> dict:
        """Occupancy summary for status surfaces and metrics gauges."""
        return {
            "blocks": self.n_blocks,
            "block_tokens": self.block_tokens,
            "used": self.used_blocks,
            "free": self.free_blocks,
            "largest_run": self.largest_free_run(),
            "owners": {o: len(t) for o, t in self._tables.items()},
        }
