"""Worker-side namespace introspection and device status probes.

JAX-native rebuild of the reference's ``_get_namespace_info``
(reference: worker.py:426-485) and ``_get_status``
(reference: worker.py:509-567): arrays are described by shape/dtype/
sharding, devices by their platform/kind, and memory numbers come from
``Device.memory_stats()`` instead of ``torch.cuda`` counters.
"""

from __future__ import annotations

import inspect
import types
from typing import Any


def describe_namespace(namespace: dict) -> dict[str, dict]:
    """Build type descriptors for every non-underscore name — the payload
    that powers coordinator-side IDE proxies (reference: worker.py:426-485,
    consumed at magic.py:1131-1314)."""
    import jax
    import numpy as np

    info: dict[str, dict] = {}
    for name, value in list(namespace.items()):
        if name.startswith("_"):
            continue
        try:
            info[name] = _describe_value(value, jax, np)
        except Exception:
            info[name] = {"kind": "object", "type": type(value).__name__,
                          "repr": "<unreprable>"}
    return info


def _describe_value(value: Any, jax, np) -> dict:
    if isinstance(value, jax.Array):
        return {
            "kind": "array",
            "shape": list(value.shape),
            "dtype": str(value.dtype),
            "sharding": _sharding_str(value),
            "device": _device_str(value),
        }
    if isinstance(value, np.ndarray):
        return {"kind": "array", "shape": list(value.shape),
                "dtype": str(value.dtype), "sharding": None,
                "device": "host"}
    if isinstance(value, jax.sharding.Mesh):
        return {"kind": "mesh", "axes": dict(value.shape),
                "devices": int(np.prod(list(value.shape.values()) or [1]))}
    if isinstance(value, jax.sharding.PartitionSpec):
        return {"kind": "pspec", "repr": repr(value)}
    if isinstance(value, types.ModuleType):
        return {"kind": "module", "name": value.__name__,
                "file": getattr(value, "__file__", None)}
    if isinstance(value, type):
        return {"kind": "class", "name": value.__name__,
                "module": value.__module__}
    if callable(value):
        try:
            sig = str(inspect.signature(value))
        except (ValueError, TypeError):
            sig = "(...)"
        doc = inspect.getdoc(value)
        return {"kind": "callable", "signature": sig,
                "doc": (doc or "")[:200],
                "name": getattr(value, "__name__", "<callable>")}
    if isinstance(value, (bool, int, float, str, bytes)):
        return {"kind": "scalar", "type": type(value).__name__,
                "repr": repr(value)[:200]}
    if isinstance(value, (list, tuple, dict, set)):
        return {"kind": "container", "type": type(value).__name__,
                "len": len(value)}
    return {"kind": "object", "type": type(value).__name__,
            "repr": repr(value)[:200]}  # reference truncates at 200 too


def _sharding_str(arr) -> str | None:
    try:
        return str(arr.sharding.spec) if hasattr(arr.sharding, "spec") \
            else type(arr.sharding).__name__
    except Exception:
        return None


def _device_str(arr) -> str:
    try:
        devs = list(arr.devices())
        if len(devs) == 1:
            return str(devs[0])
        return f"{len(devs)} devices"
    except Exception:
        return "unknown"


def device_status(rank: int, world_size: int) -> dict:
    """Per-worker status snapshot: devices, memory, backend
    (reference: worker.py:509-567, with ``memory_stats()`` supplying what
    ``torch.cuda.memory_allocated`` did).  Memory numbers come from the
    same probe the heartbeat telemetry pushes
    (:func:`~nbdistributed_tpu.observability.telemetry.device_memory`),
    so the pull and push views cannot drift."""
    import jax

    from ..observability.telemetry import device_memory

    devices = []
    for d in jax.local_devices():
        entry: dict[str, Any] = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", "unknown"),
        }
        mem = device_memory(d)
        entry["memory_gb"] = None if mem is None else {
            key: (round(mem[key] / 1e9, 3) if mem[key] is not None
                  else None)
            for key in ("in_use", "limit", "peak")
        }
        devices.append(entry)

    return {
        "rank": rank,
        "world_size": world_size,
        "backend": jax.default_backend(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
        "devices": devices,
    }
