"""Checkpoint / restore of named worker-namespace entries.

The reference has **no** checkpoint subsystem (SURVEY §5.4): users call
``torch.save`` by hand in cells.  This module is the TPU-native upgrade
SURVEY §5.4 sketches — a first-class ``%dist_checkpoint`` / ``%dist_restore``
surface that snapshots arbitrary pytrees (model params, optax opt states,
plain arrays, scalars) out of each rank's persistent namespace.

Design: **per-rank, coordination-free.**  Each rank writes
``{path}/rank_{r}/`` independently.  This is deliberate, not a fallback:

- namespace values are rank-local by construction (each worker process
  owns its own REPL state), so there is no global pytree to assemble;
- a checkpoint must be takeable from a ``%%rank`` subset and restorable
  into a *differently sized* world (each rank simply reads its own dir),
  and must not hang when a rank has died mid-session;
- orbax's multiprocess commit protocol is the opposite trade: it
  barriers the whole world and rejects host-local ``jax.Array`` values
  outright in multi-process settings ("Cannot serialize host local
  jax.Array in multi-host setting", orbax 0.11 ``jax_array_handlers``),
  which is exactly the shape interactive per-rank state has.

On-disk layout (``{path}/rank_{r}/``):

- ``manifest.json`` — format version, rank/world size, and for every
  saved name its leaf layout: per-leaf ``kind`` (``jax``/``np``/``obj``),
  dtype string and shape for arrays;
- ``arrays.npz`` — one uint8 entry ``{name}.{i}`` per array leaf holding
  the raw bytes (raw-bytes + manifest dtype, because npz itself mangles
  extended dtypes like bfloat16 into opaque void fields);
- ``aux.pkl`` — pickled treedefs plus any non-array leaves.  Pickle here
  is the same trust model as ``torch.load``: you restore only files you
  (or your job) wrote.  The *wire* protocol stays pickle-free.

Arrays restore as ``jax.Array`` or numpy leaves matching what was
saved; dtype (incl. bfloat16) and shape are exact.  Device *placement*
is not persisted: restored ``jax.Array`` leaves land on the default
device (the manifest records each leaf's original sharding string for
inspection), so multi-device-per-worker sessions re-apply shardings
afterwards, e.g. ``params = apply_shardings(params, mesh, rules)``.
"""

from __future__ import annotations

import json
import os
import pickle
import re
import shutil
import zlib
from typing import Any

# Array leaves additionally carry a per-leaf "crc32" in the manifest
# (ISSUE 19): restore refuses torn or bit-rotted bytes with an error
# naming the exact leaf instead of silently resurrecting corrupted
# state.  Purely additive — the key is optional on read, so version 1
# checkpoints written before it restore unchanged.
FORMAT_VERSION = 1


def _rank_dir(path: str, rank: int) -> str:
    return os.path.join(os.path.expanduser(path), f"rank_{rank}")


def _leaf_entries(value: Any):
    """Flatten ``value``; returns (leaves, treedef)."""
    import jax

    return jax.tree_util.tree_flatten(value)


def _byte_serializable(dtype) -> bool:
    """True when raw bytes + ``str(dtype)`` can round-trip the array.
    Structured/void/object dtypes can't (``jnp.dtype("[('a','<i4')]")``
    is unparseable) — those go through the pickle path instead."""
    import jax.numpy as jnp

    if dtype.hasobject or dtype.names is not None or dtype.kind == "V":
        return False
    try:
        return jnp.dtype(str(dtype)) == dtype
    except TypeError:
        return False


def _as_bytes(host):
    """Zero-extra-copy uint8 view of an array's bytes (contiguous
    arrays view in place; strided ones pay the one unavoidable copy)."""
    import numpy as np

    return np.ascontiguousarray(host).reshape(-1).view(np.uint8)


def save(path: str, namespace: dict, names: list[str], *, rank: int = 0,
         world_size: int = 1) -> dict:
    """Snapshot ``names`` out of ``namespace`` into ``{path}/rank_{rank}``.

    Returns a summary dict: per name, leaf count and array bytes.
    """
    import jax
    import numpy as np

    missing = [n for n in names if n not in namespace]
    if missing:
        raise KeyError(f"names not defined on rank {rank}: {missing}")

    d = _rank_dir(path, rank)
    # Stage into a sibling tmp dir and swap in only once fully written —
    # a failed or interrupted save must never corrupt an existing good
    # checkpoint (and the manifest always matches the arrays beside it).
    tmp = d + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)

    manifest: dict = {"version": FORMAT_VERSION, "rank": rank,
                      "world_size": world_size, "entries": {}}
    arrays: dict[str, np.ndarray] = {}
    treedefs: dict[str, Any] = {}
    objects: dict[str, Any] = {}
    summary: dict[str, dict] = {}

    for name in names:
        leaves, treedef = _leaf_entries(namespace[name])
        treedefs[name] = treedef
        leaf_meta = []
        nbytes = 0
        for i, leaf in enumerate(leaves):
            key = f"{name}.{i}"
            if isinstance(leaf, jax.Array):
                if not leaf.is_fully_addressable:
                    raise ValueError(
                        f"{name!r} leaf {i} spans devices this process "
                        "cannot address (globally sharded array). "
                        "Per-rank checkpoints hold rank-local state; "
                        "gather it first (e.g. x = all_gather(x)) or "
                        "checkpoint from a single-process mesh.")
                host = np.asarray(jax.device_get(leaf))
                arrays[key] = _as_bytes(host)
                leaf_meta.append({"kind": "jax", "dtype": str(host.dtype),
                                  "shape": list(host.shape),
                                  "sharding": str(leaf.sharding),
                                  "crc32": zlib.crc32(arrays[key])})
                nbytes += host.nbytes
            elif isinstance(leaf, np.ndarray) and \
                    _byte_serializable(leaf.dtype):
                arrays[key] = _as_bytes(leaf)
                leaf_meta.append({"kind": "np", "dtype": str(leaf.dtype),
                                  "shape": list(leaf.shape),
                                  "crc32": zlib.crc32(arrays[key])})
                nbytes += leaf.nbytes
            else:
                # Non-array leaves, plus object/structured-dtype ndarrays
                # whose dtypes can't round-trip through the byte path.
                objects[key] = leaf
                leaf_meta.append({"kind": "obj"})
        manifest["entries"][name] = {"leaves": leaf_meta}
        summary[name] = {"leaves": len(leaves), "bytes": nbytes}

    # Stream the zip straight to disk — peak memory stays at the uint8
    # views, not checkpoint-size buffers.
    with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
        np.savez(f, **arrays)
    with open(os.path.join(tmp, "aux.pkl"), "wb") as f:
        pickle.dump({"treedefs": treedefs, "objects": objects}, f)
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    old = d + ".old"
    shutil.rmtree(old, ignore_errors=True)
    if os.path.exists(d):
        os.rename(d, old)
    os.rename(tmp, d)
    shutil.rmtree(old, ignore_errors=True)
    return summary


class AsyncSave:
    """Handle for a background :func:`save`.

    ``done()`` polls; ``wait(timeout)`` joins and returns the save
    summary, re-raising any exception the background save hit.  The
    snapshot semantics are taken at :func:`save_async` call time:
    ``jax.Array``/numpy leaves are immutable-by-convention (training
    steps build new buffers), so the thread can read them lazily;
    plain-Python ("obj") leaves are pickled up front so later cell
    mutations cannot tear the checkpoint.
    """

    def __init__(self, thread, result_box):
        self._thread = thread
        self._box = result_box

    def done(self) -> bool:
        return not self._thread.is_alive()

    def wait(self, timeout: float | None = None) -> dict:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("async checkpoint still writing")
        if "error" in self._box:
            raise self._box["error"]
        return self._box["summary"]


def save_async(path: str, namespace: dict, names: list[str], *,
               rank: int = 0, world_size: int = 1) -> AsyncSave:
    """Start :func:`save` in a background thread and return a handle.

    The synchronous cost is validation + a *defensive device-side
    copy* of each ``jax.Array`` leaf (async-dispatched ``jnp.copy`` —
    returns immediately) + starting the thread; the blocking
    ``device_get`` and all disk IO happen in the thread.  The device
    copy is load-bearing, not paranoia: this framework's own train
    steps donate params/optimizer buffers (``make_tp_train_step``
    ``donate=True`` default), so the *next* step deletes the buffers
    a lazy reference would still be draining — the copy owns fresh
    buffers no donation can touch.  Cost: one transient device-side
    duplicate of the saved tree until the thread finishes (plan HBM
    accordingly for near-full-memory models).  numpy leaves are
    ``copy()``-ed and other Python leaves pickle-round-tripped at
    call time, so in-place host mutations cannot tear the snapshot
    either.
    """
    import pickle as _pickle
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    missing = [n for n in names if n not in namespace]
    if missing:
        raise KeyError(f"names not defined on rank {rank}: {missing}")
    snapshot: dict = {}
    for n in names:
        leaves, treedef = _leaf_entries(namespace[n])
        frozen = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array) and leaf.is_fully_addressable:
                c = jnp.copy(leaf)        # donation-proof device copy
                c.copy_to_host_async()    # start the D2H DMA now
                frozen.append(c)
            elif isinstance(leaf, jax.Array):
                frozen.append(leaf)  # save() rejects with its message
            elif isinstance(leaf, np.ndarray):
                frozen.append(leaf.copy())   # freeze host buffer
            else:
                # Mutable Python leaf: freeze NOW via a pickle
                # round-trip so post-call cell mutations can't tear
                # the snapshot.
                frozen.append(_pickle.loads(_pickle.dumps(leaf)))
        snapshot[n] = jax.tree_util.tree_unflatten(treedef, frozen)

    box: dict = {}

    def run():
        try:
            box["summary"] = save(path, snapshot, names, rank=rank,
                                  world_size=world_size)
        except BaseException as e:  # surfaced at wait()
            box["error"] = e

    t = threading.Thread(target=run, name=f"nbd-ckpt-save-r{rank}",
                         daemon=True)
    t.start()
    return AsyncSave(t, box)


def _decode_array(raw, meta, *, to_device: bool):
    import jax.numpy as jnp
    import numpy as np

    dtype = jnp.dtype(meta["dtype"])  # jnp.dtype knows bfloat16 & friends
    # npz gives a fresh writable C-contiguous uint8 array; reinterpret
    # in place (no copy) — jnp.asarray below copies to device anyway.
    host = raw.view(dtype).reshape(meta["shape"])
    return jnp.asarray(host) if to_device else host


def restore(path: str, namespace: dict, names: list[str] | None = None, *,
            rank: int = 0) -> dict:
    """Load entries from ``{path}/rank_{rank}`` back into ``namespace``.

    ``names=None`` restores everything in the manifest.  Returns the same
    per-name summary shape as :func:`save`.
    """
    d = _rank_dir(path, rank)
    mpath = os.path.join(d, "manifest.json")
    if not os.path.exists(mpath):
        raise FileNotFoundError(
            f"no checkpoint for rank {rank} at {path!r} "
            f"(missing {mpath})")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("version") != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version "
                         f"{manifest.get('version')!r}")
    import jax
    import numpy as np

    apath = os.path.join(d, "aux.pkl")
    try:
        with open(apath, "rb") as f:
            aux = pickle.load(f)
    except Exception as e:
        raise ValueError(
            f"torn checkpoint: {apath} is missing or unreadable "
            f"({type(e).__name__}: {e}) — the manifest names entries "
            f"this file should hold; refusing to restore") from e

    entries = manifest["entries"]
    if names is None:
        names = list(entries)
    missing = [n for n in names if n not in entries]
    if missing:
        raise KeyError(f"names not in checkpoint: {missing} "
                       f"(has {sorted(entries)})")

    zpath = os.path.join(d, "arrays.npz")
    try:
        npz_cm = np.load(zpath)
    except Exception as e:
        raise ValueError(
            f"torn checkpoint: {zpath} is missing or unreadable "
            f"({type(e).__name__}: {e}); refusing to restore") from e
    summary: dict[str, dict] = {}
    with npz_cm as npz:
        for name in names:
            leaf_meta = entries[name]["leaves"]
            leaves = []
            nbytes = 0
            for i, meta in enumerate(leaf_meta):
                key = f"{name}.{i}"
                if meta["kind"] == "obj":
                    leaves.append(aux["objects"][key])
                else:
                    try:
                        raw = npz[key]
                    except KeyError:
                        raise ValueError(
                            f"torn checkpoint: {zpath} has no entry "
                            f"{key!r} though the manifest declares it; "
                            f"refusing to restore") from None
                    except Exception as e:
                        # e.g. zipfile.BadZipFile: the archive's own
                        # CRC tripped before ours could.
                        raise ValueError(
                            f"checkpoint integrity failure: entry "
                            f"{key!r} in {zpath} is unreadable "
                            f"({type(e).__name__}: {e}); refusing "
                            f"to restore") from e
                    want = meta.get("crc32")
                    if want is not None:
                        got = zlib.crc32(np.ascontiguousarray(raw))
                        if got != want:
                            raise ValueError(
                                f"checkpoint integrity failure: "
                                f"{name!r} leaf {i} ({key} in {zpath}) "
                                f"has crc32 {got:#010x}, manifest says "
                                f"{want:#010x} — bytes changed on disk "
                                f"(bit rot or torn write); refusing "
                                f"to restore")
                    arr = _decode_array(raw, meta,
                                        to_device=meta["kind"] == "jax")
                    leaves.append(arr)
                    nbytes += arr.nbytes
            namespace[name] = jax.tree_util.tree_unflatten(
                aux["treedefs"][name], leaves)
            summary[name] = {"leaves": len(leaf_meta), "bytes": nbytes}
    return summary


def verify_rank(path: str, rank: int) -> list[str]:
    """Integrity-check one rank dir against its manifest without
    restoring anything: every declared array entry must exist in
    ``arrays.npz`` and match its manifest crc32; ``aux.pkl`` must
    load.  Returns a list of human-readable problems (empty = clean).
    Pre-crc32 checkpoints report their unverifiable leaves as such
    rather than passing silently."""
    import numpy as np

    d = _rank_dir(path, rank)
    mpath = os.path.join(d, "manifest.json")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except Exception as e:
        return [f"{mpath}: unreadable manifest "
                f"({type(e).__name__}: {e})"]
    problems: list[str] = []
    apath = os.path.join(d, "aux.pkl")
    try:
        with open(apath, "rb") as f:
            pickle.load(f)
    except Exception as e:
        problems.append(f"{apath}: missing or unreadable "
                        f"({type(e).__name__}: {e})")
    zpath = os.path.join(d, "arrays.npz")
    unverifiable = 0
    try:
        npz_cm = np.load(zpath)
    except Exception as e:
        problems.append(f"{zpath}: missing or unreadable "
                        f"({type(e).__name__}: {e})")
        return problems
    with npz_cm as npz:
        have = set(npz.files)
        for name, entry in manifest.get("entries", {}).items():
            for i, meta in enumerate(entry["leaves"]):
                if meta["kind"] == "obj":
                    continue
                key = f"{name}.{i}"
                if key not in have:
                    problems.append(f"{zpath}: entry {key!r} declared "
                                    f"by the manifest is absent")
                    continue
                want = meta.get("crc32")
                if want is None:
                    unverifiable += 1
                    continue
                try:
                    raw = npz[key]
                except Exception as e:
                    problems.append(f"{zpath}: entry {key!r} "
                                    f"unreadable ({type(e).__name__}: "
                                    f"{e})")
                    continue
                got = zlib.crc32(np.ascontiguousarray(raw))
                if got != want:
                    problems.append(
                        f"{name!r} leaf {i} ({key}): crc32 {got:#010x} "
                        f"!= manifest {want:#010x} (bit rot or torn "
                        f"write)")
    if unverifiable:
        problems.append(f"{unverifiable} array leaf(s) predate the "
                        f"integrity manifest (no crc32 recorded) — "
                        f"unverifiable, not necessarily bad")
    return problems


def info(path: str, *, verify: bool = False) -> dict:
    """Describe a checkpoint directory: which ranks, which names.
    ``verify=True`` additionally crc-checks every rank's arrays
    against its manifest (reads every byte — priced accordingly) and
    reports per-rank ``integrity``: ``"ok"`` or the problem list."""
    root = os.path.expanduser(path)
    out: dict = {"path": root, "ranks": {}}
    if not os.path.isdir(root):
        return out
    for entry in sorted(os.listdir(root)):
        # Exact rank_<digits> only — skips rank_N.tmp/.old staging dirs
        # left by an interrupted save.
        if not re.fullmatch(r"rank_\d+", entry):
            continue
        mpath = os.path.join(root, entry, "manifest.json")
        if not os.path.exists(mpath):
            continue
        with open(mpath) as f:
            manifest = json.load(f)
        rank = int(entry.split("_", 1)[1])
        desc = {
            "world_size": manifest.get("world_size"),
            "names": sorted(manifest.get("entries", {})),
        }
        if verify:
            problems = verify_rank(root, rank)
            desc["integrity"] = problems if problems else "ok"
        out["ranks"][rank] = desc
    return out
