"""REPL cell executor: the 3-path AST strategy.

Reimplements the execution semantics of the reference's
``_execute_code_streaming`` (reference: worker.py:248-387) as a pure,
unit-testable function:

(a) the whole cell parses as a single expression  -> ``eval`` it;
(b) it parses as statements whose last node is an ``ast.Expr``
    -> ``exec`` everything but the last, then ``eval`` the last
    (reference: worker.py:319-333);
(c) otherwise -> plain ``exec`` (reference: worker.py:365-373).

A non-None final value is ``repr()``-ed, pushed through the stream hook
with stream kind ``"result"`` (reference: worker.py:291-304) and included
in the returned output. Objects never leave the worker from this path —
strings only (reference: worker.py:313-314). The namespace dict is the
exec globals, so state persists across cells (reference: worker.py:284).
"""

from __future__ import annotations

import ast
import io
import sys
import time
import traceback
from typing import Any, Callable

from ..observability.spans import maybe_span

StreamFn = Callable[[str, str], None]  # (text, stream_kind) -> None


class _StreamingStdout(io.TextIOBase):
    """stdout replacement that pushes output through ``stream_fn`` and
    mirrors into a buffer for the final response (reference:
    worker.py:30-69).

    Unlike the reference — which ships one control-plane message per
    ``write()`` call, so ``print('a', 1)`` costs four sends (SURVEY §3.2
    flags this as a hot loop) — pushes are line-buffered: complete lines
    stream immediately, partial tails on ``drain()``.
    """

    def __init__(self, stream_fn: StreamFn):
        self._stream_fn = stream_fn
        self._buffer = io.StringIO()
        self._pending = ""

    def write(self, text: str) -> int:
        self._buffer.write(text)
        self._pending += text
        # \r flushes too so carriage-return progress bars stream live.
        cut = max(self._pending.rfind("\n"), self._pending.rfind("\r"))
        if cut >= 0:
            lines, self._pending = (self._pending[:cut + 1],
                                    self._pending[cut + 1:])
            if lines.strip():
                self._push(lines)
        return len(text)

    def _push(self, text: str) -> None:
        try:
            self._stream_fn(text, "stdout")
        except Exception:
            pass  # a failing push must not kill user code

    def drain(self) -> None:
        """Flush any partial trailing line (called at cell end)."""
        if self._pending.strip():
            self._push(self._pending)
        self._pending = ""

    def flush(self) -> None:  # reference: worker.py:65-66
        pass

    def getvalue(self) -> str:
        return self._buffer.getvalue()

    def writable(self) -> bool:
        return True


def execute_cell(code: str, namespace: dict, stream_fn: StreamFn | None = None,
                 *, rank: int = 0, filename: str = "<cell>") -> dict[str, Any]:
    """Execute one cell in ``namespace`` with REPL semantics.

    Returns ``{"output", "status": "success", "rank", "duration_s"}`` or
    ``{"error", "traceback", "rank", "duration_s"}``.  Unlike the
    reference, the duration is *measured* on the worker (SURVEY §5.1
    calls out the reference's durations as keyword-based guesses).
    """
    stream_fn = stream_fn or (lambda text, kind: None)
    old_stdout = sys.stdout
    streaming = _StreamingStdout(stream_fn)
    sys.stdout = streaming
    t0 = time.perf_counter()
    result_value: Any = None
    has_result = False
    # Span around the user code itself (a child of the worker's
    # handler-dispatch span), so a merged trace separates cell compute
    # from control-plane handling.  No-op unless a trace is active.
    cell_span = maybe_span("cell", kind="cell",
                           attrs={"rank": rank,
                                  "code": code.strip()[:120]})
    cell_span.__enter__()
    try:
        try:
            # Path (a): whole cell is a single expression.
            expr = compile(code, filename, "eval")
        except SyntaxError:
            tree = ast.parse(code, filename)
            if tree.body and isinstance(tree.body[-1], ast.Expr):
                # Path (b): statements ending in an expression.
                last = tree.body.pop()
                if tree.body:
                    exec(compile(tree, filename, "exec"), namespace)
                expr_ast = ast.Expression(last.value)
                ast.copy_location(expr_ast, last)
                result_value = eval(compile(expr_ast, filename, "eval"),
                                    namespace)
                has_result = True
            else:
                # Path (c): plain statements.
                exec(compile(tree, filename, "exec"), namespace)
        else:
            result_value = eval(expr, namespace)
            has_result = True

        streaming.drain()
        output = streaming.getvalue()
        if has_result and result_value is not None:
            text = repr(result_value)
            try:
                stream_fn(text, "result")
            except Exception:
                pass
            if output and not output.endswith("\n"):
                output += "\n"
            output += text
        return {
            "output": output,
            "status": "success",
            "rank": rank,
            "duration_s": time.perf_counter() - t0,
        }
    except KeyboardInterrupt:
        # %dist_interrupt delivers SIGINT (Jupyter-style): the cell
        # aborts with an error response, the worker stays alive.
        streaming.drain()
        return {
            "error": "KeyboardInterrupt (cell interrupted by "
                     "%dist_interrupt)",
            "traceback": traceback.format_exc(),
            "rank": rank,
            "duration_s": time.perf_counter() - t0,
        }
    except Exception as e:
        streaming.drain()
        return {
            "error": str(e),
            "traceback": traceback.format_exc(),
            "rank": rank,
            "duration_s": time.perf_counter() - t0,
        }
    finally:
        cell_span.__exit__(None, None, None)
        sys.stdout = old_stdout


def execute_repeat(code: str, namespace: dict,
                   stream_fn: StreamFn | None = None, *,
                   repeat: int, until: str | None = None,
                   rank: int = 0, filename: str = "<cell>",
                   progress: Callable[[int, int, float | None, float],
                                      None] | None = None
                   ) -> dict[str, Any]:
    """Worker-side step loop (ISSUE 14): **compile once, run the cell
    body ``repeat`` times** — one dispatch amortizes the per-cell
    control-plane overhead over k steps, which is the whole point of
    ``%%distributed --repeat k``.

    Semantics relative to :func:`execute_cell`:

    * the cell is compiled ONCE (body + optional trailing expression,
      the same 3-path split), then executed k times in ``namespace``;
    * the trailing expression's value is evaluated every step; when it
      is a real scalar (loss, metric) it is reported per step through
      ``progress(step_index, k, last_scalar, steps_per_s)`` — the
      worker piggybacks that on heartbeats — and only the LAST step's
      value is echoed in the reply (k result echoes would flood the
      stream for zero information);
    * ``until`` (an expression string) is evaluated after each step;
      truthy stops the loop early (``--until "loss < 0.1"``);
    * KeyboardInterrupt between (or inside) steps aborts the loop with
      an error reply that still reports ``steps`` completed — state
      from finished steps is intact, exactly like interrupting a
      hand-written worker-side loop;
    * the caller's replay cache sees ONE request — a redelivery is
      answered from the cached reply and never re-runs any step.
    """
    stream_fn = stream_fn or (lambda text, kind: None)
    old_stdout = sys.stdout
    streaming = _StreamingStdout(stream_fn)
    sys.stdout = streaming
    t0 = time.perf_counter()
    steps = 0
    last_scalar: float | None = None
    result_value: Any = None
    has_result = False
    cell_span = maybe_span("cell", kind="cell",
                           attrs={"rank": rank, "repeat": repeat,
                                  "code": code.strip()[:120]})
    cell_span.__enter__()
    try:
        tree = ast.parse(code, filename)
        expr_code = None
        if tree.body and isinstance(tree.body[-1], ast.Expr):
            last = tree.body.pop()
            expr_ast = ast.Expression(last.value)
            ast.copy_location(expr_ast, last)
            expr_code = compile(expr_ast, filename, "eval")
        body_code = (compile(tree, filename, "exec")
                     if tree.body else None)
        until_code = (compile(until, "<until>", "eval")
                      if until else None)
        stopped_early = False
        for _ in range(max(1, int(repeat))):
            if body_code is not None:
                exec(body_code, namespace)
            if expr_code is not None:
                result_value = eval(expr_code, namespace)
                has_result = True
                if isinstance(result_value, (int, float)) \
                        and not isinstance(result_value, bool):
                    last_scalar = float(result_value)
            steps += 1
            if progress is not None:
                elapsed = time.perf_counter() - t0
                try:
                    progress(steps, max(1, int(repeat)), last_scalar,
                             steps / elapsed if elapsed > 0 else 0.0)
                except Exception:
                    pass  # telemetry must never kill the loop
            if until_code is not None and eval(until_code, namespace):
                stopped_early = True
                break
        streaming.drain()
        output = streaming.getvalue()
        if has_result and result_value is not None:
            text = repr(result_value)
            try:
                stream_fn(text, "result")
            except Exception:
                pass
            if output and not output.endswith("\n"):
                output += "\n"
            output += text
        duration = time.perf_counter() - t0
        return {
            "output": output,
            "status": "success",
            "rank": rank,
            "duration_s": duration,
            "steps": steps,
            "repeat": int(repeat),
            "stopped_early": stopped_early,
            "steps_per_s": round(steps / duration, 3)
            if duration > 0 else 0.0,
            "last_scalar": last_scalar,
        }
    except KeyboardInterrupt:
        streaming.drain()
        return {
            "error": f"KeyboardInterrupt (step loop interrupted after "
                     f"{steps}/{repeat} steps)",
            "traceback": traceback.format_exc(),
            "rank": rank,
            "duration_s": time.perf_counter() - t0,
            "steps": steps,
            "repeat": int(repeat),
        }
    except Exception as e:
        streaming.drain()
        return {
            "error": f"{e} (at step {steps + 1}/{repeat})",
            "traceback": traceback.format_exc(),
            "rank": rank,
            "duration_s": time.perf_counter() - t0,
            "steps": steps,
            "repeat": int(repeat),
        }
    finally:
        cell_span.__exit__(None, None, None)
        sys.stdout = old_stdout
